"""Whole-plan megafusion correctness suite (ISSUE 6).

Covers the one-program apply-path contract:
  - the optimizer's `MegafusionRule` collapses a fitted pipeline's whole
    apply plan (featurize → scale → linear → argmax, chunk loop
    included) into ONE `MegafusedPlanOperator` program, with outputs
    allclose-identical to the serial unfused path at exact-multiple AND
    ragged example counts;
  - the host batcher hands a shape-stable bucket's padded chunk stack to
    one scan-bodied program (43 items / chunk 16 → 1 executed program),
    with the (indices, results) chunk contract intact;
  - ineligible plans — streaming host stages, host-code stages, fan-out
    — fall back cleanly to the PR-4/5 per-program path, and
    ``validate()``'s KP401 diagnostics say why;
  - `ExecutionConfig.megafusion` (KEYSTONE_MEGAFUSION) kill switch
    reverts to the PR-4/5 plan with identical values;
  - the acceptance gate: ``dispatch.programs_executed == 1`` on the
    apply run of ≥2 example pipelines, and warm megafused runs perform
    0 cold compiles;
  - AOT warmup re-arms for chains whose estimator slots resolve after
    the warm scan (the serving/re-apply path covers the megafused
    program too);
  - the KP2xx memory model prices the scan's in-program live set.
"""

import numpy as np
import pytest

from keystone_tpu import Dataset, HostDataset, Pipeline, PipelineEnv, Transformer
from keystone_tpu.telemetry import counter
from keystone_tpu.utils import batching
from keystone_tpu.workflow.env import (
    config_override,
    dispatch_override,
    overlap_override,
)
from keystone_tpu.workflow.optimizer import DefaultOptimizer

RAGGED_N, CHUNK = 43, 16


def _reset():
    PipelineEnv.reset()


@pytest.fixture(autouse=True)
def _clean_env():
    _reset()
    yield
    _reset()


# --------------------------------------------------------------------------
# plan rewrite: one Megafused node, one executed program


def _fitted_apply_pipeline(n_train=24, d=6, k=3, seed=3):
    """featurize → scaler-fit → linear-fit → argmax over a device
    Dataset: the canonical megafusable apply shape."""
    from keystone_tpu.nodes.learning import LinearMapEstimator
    from keystone_tpu.nodes.stats import NormalizeRows, StandardScaler
    from keystone_tpu.nodes.util import ClassLabelIndicatorsFromInt, MaxClassifier

    rng = np.random.default_rng(seed)
    X = np.abs(rng.normal(size=(n_train, d))).astype(np.float32) + 1.0
    y = rng.integers(0, k, n_train).astype(np.int32)
    train = Dataset.from_numpy(X)
    labels = ClassLabelIndicatorsFromInt(k)(Dataset.from_numpy(y)).get()
    pipe = (NormalizeRows().to_pipeline()
            .and_then(StandardScaler(), train)
            .and_then(LinearMapEstimator(0.1), train, labels)
            >> MaxClassifier())
    return pipe, train


@pytest.mark.parametrize("n_test", [24, RAGGED_N])  # multiple AND ragged
def test_apply_plan_collapses_to_one_program(n_test):
    """The apply run executes exactly ONE program (a Megafused node in
    the plan), identical to the serial unfused path — including at a
    ragged count, where padded-row masking must stay exact through the
    in-program scan."""
    rng = np.random.default_rng(11)
    Xt = np.abs(rng.normal(size=(n_test, 6))).astype(np.float32) + 1.0

    with overlap_override(False), dispatch_override(False), \
            config_override(megafusion=False):
        PipelineEnv.get().set_optimizer(DefaultOptimizer(fuse=False))
        pipe, train = _fitted_apply_pipeline()
        pipe(train).get()  # fit
        reference = pipe(Dataset.from_numpy(Xt)).get().numpy()
    _reset()

    pipe, train = _fitted_apply_pipeline()
    pipe(train).get()  # fit run (fan-out: not the megafused path)
    c = counter("dispatch.programs_executed")
    res = pipe(Dataset.from_numpy(Xt))
    before = c.value
    out = res.get().numpy()
    assert int(c.value - before) == 1, "apply run was not one program"
    labels = [op.label for op in res.executor.optimized_graph.operators.values()]
    assert any(l.startswith("Megafused[") for l in labels), labels
    np.testing.assert_allclose(out, reference, rtol=1e-5, atol=1e-6)


def test_fit_bakes_megafused_transformer():
    """`Pipeline.fit()` resolves the MegafusedPlanOperator: the fitted
    pipeline carries the baked scan-bodied transformer and applies
    identically to the lazy path."""
    from keystone_tpu.nodes.util.fusion import MegafusedBatchTransformer

    pipe, train = _fitted_apply_pipeline()
    lazy = pipe(train).get().numpy()
    fitted = pipe.fit()
    baked = [op for op in fitted.graph.operators.values()
             if isinstance(op, MegafusedBatchTransformer)]
    assert baked, "fit() did not bake a MegafusedBatchTransformer"
    np.testing.assert_array_equal(fitted(train).numpy(), lazy)


# --------------------------------------------------------------------------
# host batcher: the chunk loop moves in-program


def _host_items(n=RAGGED_N, dim=6, seed=0):
    rng = np.random.default_rng(seed)
    return [np.abs(rng.normal(size=(dim,)).astype(np.float32)) + 1.0
            for _ in range(n)]


def test_host_bucket_scans_as_one_program():
    """43 same-shape items at chunk 16: ONE executed program for the
    whole bucket (vs 3 with megafusion off), indices covering exactly
    range(43), values identical to the per-chunk path."""
    import jax

    items = _host_items()
    fn = jax.jit(lambda xb: xb * 2.0 + 1.0)
    c = counter("dispatch.programs_executed")

    with config_override(megafusion=True, pad_chunks=True):
        before = c.value
        seen = {}
        for idxs, payload in batching.map_host_batched_stream(
                items, fn, chunk=CHUNK):
            assert idxs is not None
            assert len(idxs) == len(payload) <= CHUNK
            for i, row in zip(idxs, payload):
                assert i not in seen
                seen[i] = row
        mega_programs = int(c.value - before)
    assert mega_programs == 1, mega_programs
    assert sorted(seen) == list(range(RAGGED_N))

    with config_override(megafusion=False, pad_chunks=True):
        before = c.value
        reference = batching.map_host_batched(items, fn, chunk=CHUNK)
        plain_programs = int(c.value - before)
    assert plain_programs == 3  # ceil(43 / 16) per-chunk dispatches
    for i in range(RAGGED_N):
        np.testing.assert_allclose(np.asarray(seen[i]),
                                   np.asarray(reference[i]), rtol=1e-6)


def test_host_code_batch_fn_falls_back_per_chunk():
    """A host (non-jitted) batch fn is not traceable under the scan: the
    megafused path declines and the per-chunk contract is unchanged."""
    items = _host_items()
    shapes = []

    def hostfn(xb):
        shapes.append(xb.shape[0])
        return np.asarray(xb) * 2.0

    with config_override(megafusion=True, pad_chunks=True):
        out = batching.map_host_batched(items, hostfn, chunk=CHUNK)
    assert shapes == [CHUNK, CHUNK, CHUNK], shapes  # padded, per chunk
    for i in range(RAGGED_N):
        np.testing.assert_allclose(out[i], items[i] * 2.0, rtol=1e-6)


def test_host_megafusion_residency_cap(monkeypatch):
    """One scan program never stacks an unbounded bucket: runs split at
    `_MEGAFUSED_MAX_TRIPS` chunks, so a huge bucket still streams —
    capped chunks per dispatch — instead of materializing whole."""
    import jax

    monkeypatch.setattr(batching, "_MEGAFUSED_MAX_TRIPS", 4)
    items = _host_items(n=40)  # 10 chunks of 4 at chunk=4
    fn = jax.jit(lambda xb: xb * 2.0)
    c = counter("dispatch.programs_executed")
    with config_override(megafusion=True, pad_chunks=True):
        before = c.value
        out = batching.map_host_batched(items, fn, chunk=4)
        programs = int(c.value - before)
    assert programs == 3  # ceil(10 trips / cap 4) scan programs
    for i in range(40):
        np.testing.assert_allclose(np.asarray(out[i]), items[i] * 2.0,
                                   rtol=1e-6)


def test_pad_chunks_off_disables_host_megafusion():
    """Shape-stable padding is the contract the in-program scan rides
    on; with it off, the per-chunk dispatch path remains."""
    import jax

    items = _host_items()
    fn = jax.jit(lambda xb: xb * 2.0)
    c = counter("dispatch.programs_executed")
    with config_override(megafusion=True, pad_chunks=False):
        before = c.value
        batching.map_host_batched(items, fn, chunk=CHUNK)
        programs = int(c.value - before)
    assert programs == 3  # ragged tail keeps its own dispatch


# --------------------------------------------------------------------------
# ineligible plans fall back (streaming stage, fan-out)


class _ChunkProducer(Transformer):
    """Bucketed host-batch stage streaming chunks (the SIFT pattern)."""

    chunkable = True

    def apply(self, x):
        return np.asarray(x, np.float32) * 2.0

    def apply_batch_stream(self, data):
        return batching.map_host_batched_stream(
            data.items, lambda xb: np.asarray(xb) * 2.0, chunk=4)


def test_streaming_plan_keeps_chunk_flow():
    """A plan headed by a stream-producing host stage does NOT megafuse:
    chunks keep draining lazily through the fused elementwise chain
    (no Megafused node, ≥2 index-carrying chunks)."""
    from keystone_tpu.nodes.stats import NormalizeRows, SignedHellingerMapper

    items = _host_items(n=12)
    pipe = (_ChunkProducer().to_pipeline()
            >> NormalizeRows() >> SignedHellingerMapper())
    with overlap_override(True, prefetch_depth=1):
        res = pipe(HostDataset(items))
        labels = [op.label
                  for op in res.executor.optimized_graph.operators.values()]
        assert not any(l.startswith("Megafused[") for l in labels), labels
        n_chunks = 0
        seen = {}
        for idxs, payload in res.stream():
            assert idxs is not None, "stream materialized"
            n_chunks += 1
            for i, item in zip(idxs, payload):
                seen[i] = item
        assert n_chunks >= 2, "producer chunks were collapsed"
    assert sorted(seen) == list(range(12))


def _fusable_fn(name):
    class _F(Transformer):
        fusable = True

        def __init__(self):
            self._name = name

        @property
        def label(self):
            return self._name

        def apply(self, x):
            return x + 1.0

    return _F()


def test_fanout_terminates_megafusion():
    """A fan-out inside the chain keeps both branches as separate
    programs — megafusion never duplicates work across consumers."""
    from keystone_tpu.workflow.fusion_rule import MegafusionRule, NodeFusionRule
    from keystone_tpu.workflow.graph import Graph
    from keystone_tpu.workflow.operators import DatasetOperator

    g = Graph()
    g, data = g.add_node(
        DatasetOperator(Dataset.from_numpy(np.ones((4, 2), np.float32))), [])
    g, a = g.add_node(_fusable_fn("A"), [data])
    g, b = g.add_node(_fusable_fn("B"), [a])
    g, c = g.add_node(_fusable_fn("C"), [b])
    g, d = g.add_node(_fusable_fn("D"), [b])
    g, _ = g.add_sink(c)
    g, _ = g.add_sink(d)

    plan = NodeFusionRule().apply((g, {}))
    plan = MegafusionRule().apply(plan)
    labels = sorted(op.label for op in plan[0].operators.values()
                    if not op.label.startswith("Dataset"))
    assert labels == ["C", "D", "Fused[A >> B]"], labels


def test_absorbed_cacher_prefix_not_poisoned():
    """Review regression: a Cacher at the HEAD of a merged chain is
    absorbed — its saveable prefix must be dropped with it, or the
    whole-chain output gets saved under the Cacher's cross-pipeline
    state key and a second pipeline sharing that head silently reads
    the wrong value through SavedStateLoadRule."""
    from keystone_tpu.nodes.stats import NormalizeRows, SignedHellingerMapper
    from keystone_tpu.nodes.util import Cacher

    rng = np.random.default_rng(21)
    X = np.abs(rng.normal(size=(16, 5))).astype(np.float32) + 1.0
    ds = Dataset.from_numpy(X)

    shared = Cacher("c")
    pipe1 = (shared.to_pipeline() >> NormalizeRows()
             >> Cacher("mid") >> SignedHellingerMapper())
    pipe2 = shared.to_pipeline() >> NormalizeRows()

    pipe1(ds).get()  # saves whatever prefixes the plan kept
    out2 = pipe2(ds).get().numpy()
    expected = X / np.linalg.norm(X, axis=1, keepdims=True)
    np.testing.assert_allclose(out2, expected, rtol=1e-5, atol=1e-6)


# --------------------------------------------------------------------------
# kill switch parity


def test_kill_switch_reverts_to_pr45_plan():
    """`megafusion=False` (KEYSTONE_MEGAFUSION=0) reproduces the PR-4/5
    two-program apply plan with identical predictions."""
    from keystone_tpu.dispatch_bench import measure_example

    mega = measure_example("MnistRandomFFT", "megafused")
    pr45 = measure_example("MnistRandomFFT", "optimized")
    assert mega["apply_run_programs"] == 1
    assert pr45["apply_run_programs"] == 2  # the PR-4/5 floor
    np.testing.assert_allclose(mega["test_pred"], pr45["test_pred"],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(mega["train_pred"], pr45["train_pred"],
                               rtol=1e-5, atol=1e-5)


# --------------------------------------------------------------------------
# acceptance gate: 1 program/apply run on ≥2 examples, identical outputs


@pytest.mark.parametrize("example", ["MnistRandomFFT", "RandomPatchCifar"])
def test_one_program_per_apply_run(example):
    """ISSUE 6 acceptance: `dispatch.programs_executed == 1` on the
    example's apply run under the megafused plan, outputs
    allclose-identical to the serial unfused path."""
    from keystone_tpu.dispatch_bench import measure_example

    base = measure_example(example, "serial_unfused")
    mega = measure_example(example, "megafused")
    assert mega["apply_run_programs"] == 1, mega["apply_run_programs"]
    assert mega["fit_run_programs"] <= base["fit_run_programs"]
    np.testing.assert_allclose(
        mega["train_pred"], base["train_pred"], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        mega["test_pred"], base["test_pred"], rtol=1e-5, atol=1e-5)


def test_report_carries_plan_breakdown_rows():
    """The dispatch report's per-plan breakdown row (satellite): one
    flat record per example with all four plan columns, and the
    megafused one-program gate counted."""
    from keystone_tpu.dispatch_bench import PLANS, dispatch_count_report

    rep = dispatch_count_report(examples=("MnistRandomFFT",))
    assert rep["plans"] == list(PLANS)
    (row,) = rep["plan_breakdown"]
    assert row["example"] == "MnistRandomFFT"
    assert all(p in row for p in PLANS)
    assert row["megafused"] == 1
    assert rep["examples_at_one_program"] == 1
    assert rep["all_outputs_match"]


def test_warm_megafused_run_zero_cold_compiles():
    """ISSUE 6 acceptance: a rebuilt-from-scratch megafused run against
    a warm persistent cache performs 0 cold compiles and still executes
    the apply run as one program."""
    from keystone_tpu.compile_bench import measure_example_compiles

    rep = measure_example_compiles("MnistRandomFFT")
    assert rep["plan"] == "megafused"
    assert rep["warm_programs_compiled"] == 0, rep
    assert rep["warm_run"]["apply_programs_executed"] == 1, rep
    assert rep["outputs_match_cold"]


# --------------------------------------------------------------------------
# AOT warmup re-arm (satellite)


def test_warmup_rearms_after_fit_resolution(monkeypatch):
    """A fused-chain program whose estimator slots were unresolved when
    the warm scan ran is re-armed once the fits force: the next
    execute() on the same executor submits the chain's warmup (the
    serving/re-apply path is warm on first force)."""
    import keystone_tpu.workflow.executor as executor_mod

    warmed = []

    def fake_submit(op, element, counts):
        # counts: one count or the serving-ladder sequence of them
        if isinstance(counts, int):
            counts = (counts,)
        warmed.append((getattr(op, "label", str(op)), tuple(element.shape),
                       tuple(int(c) for c in counts)))

    monkeypatch.setattr(executor_mod, "_submit_warmup", fake_submit)

    with config_override(aot_warmup=True):
        pipe, train = _fitted_apply_pipeline()
        res = pipe(train)
        ex = res.executor
        res.get()  # forces fits; warm scan saw unresolved estimator slots
        executor_mod.drain_warmups()
        with ex._warm_lock:
            had_pending = bool(ex._warm_pending) or bool(warmed)
        assert had_pending, "warm scan neither warmed nor parked the chain"
        before = len(warmed)
        ex._rearm_warmup()  # what the next execute()/scheduler tick runs
        executor_mod.drain_warmups()
    new = warmed[before:]
    assert not ex._warm_pending or new, (ex._warm_pending, warmed)


# --------------------------------------------------------------------------
# validate() diagnostics + memory model


def test_validate_explains_ineligible_plan():
    """KP401: a stream-producing host stage in an otherwise fusable
    chain shows up as an INFO diagnostic naming the fallback reason."""
    from keystone_tpu.nodes.stats import NormalizeRows, SignedHellingerMapper

    pipe = (_ChunkProducer().to_pipeline()
            >> NormalizeRows() >> SignedHellingerMapper())
    report = pipe.validate(level="full", raise_on_error=False)
    kp401 = report.by_rule("KP401")
    assert kp401, str(report)
    assert any("host-staging" in d.message or "stream" in d.message
               for d in kp401)


def test_validate_fusable_plan_has_no_fallback_diags():
    from keystone_tpu.nodes.stats import NormalizeRows, SignedHellingerMapper

    pipe = (NormalizeRows().to_pipeline() >> SignedHellingerMapper())
    report = pipe.validate(level="full", raise_on_error=False)
    assert not report.by_rule("KP401"), str(report)


def test_memory_model_prices_scan_live_set():
    """`MegafusedPlanOperator.scan_live_nbytes`: the in-program carry is
    chunk_rows × the largest adjacent stage-boundary pair."""
    from keystone_tpu.analysis.specs import DataSpec, shape_struct
    from keystone_tpu.nodes.stats import NormalizeRows
    from keystone_tpu.nodes.util import MaxClassifier
    from keystone_tpu.workflow.fusion_rule import MegafusedPlanOperator

    op = MegafusedPlanOperator([NormalizeRows(), MaxClassifier()])
    spec = DataSpec(element=shape_struct((6,), np.float32),
                    count=100, kind="dataset")
    live = op.scan_live_nbytes([spec], chunk_rows=16)
    # boundaries: 24B → 24B → 4B per item; worst adjacent pair 48B
    assert live == 48 * 16, live

    # unknown elements refuse an estimate instead of guessing
    from keystone_tpu.analysis.specs import UNKNOWN

    assert op.scan_live_nbytes(
        [DataSpec(element=UNKNOWN, kind="dataset")], 16) is None
