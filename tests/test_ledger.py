"""Decision-ledger acceptance suite (PR 11).

Covers the observability contract the ledger exists for:
  - schema round-trip: decisions recorded through the real
    `record_decision` path survive write → parse → render with the
    chosen entry, ≥1 priced alternative, and predicted cost intact;
  - both artifact forms load (`KEYSTONE_LEDGER` JSONL and a Chrome
    trace whose metadata embeds the decisions);
  - ``--diff`` names an injected ``KEYSTONE_MEGAFUSION=0`` kill-switch
    flip (config flip + removed decision + suspect env), reports a
    seeded prediction drift, and exits 0 on a self-diff;
  - predicted-vs-observed exactness pins on MnistRandomFFT: the
    megafused plan's ONE recorded megafusion decision predicts exactly
    the 1 program the traced apply run executes (residual 0), the
    cold-compile prediction upper-bounds the observed compiles, and a
    warm re-apply observes exactly 0 cold compiles;
  - the acceptance diff: a default (megafused) run vs a
    ``KEYSTONE_MEGAFUSION=0`` run names the changed decision AND the
    observed program-count regression.
"""

import json

import numpy as np
import pytest

from keystone_tpu import PipelineEnv
from keystone_tpu.telemetry import ledger, registry, trace_run
from keystone_tpu.telemetry.__main__ import main as telemetry_main
from keystone_tpu.workflow.env import (
    config_override,
    dispatch_override,
    overlap_override,
)


@pytest.fixture(autouse=True)
def _fresh_session():
    ledger.clear_session()
    yield
    ledger.clear_session()


def _record_sample(kind="megafusion", labels=("Fused[A >> B]",),
                   predicted=None):
    return ledger.record_decision(
        kind=kind,
        rule="MegafusionRule" if kind == "megafusion" else "NodeFusionRule",
        vertices=[3, 4, 5],
        labels=list(labels),
        chosen={"entry": "megafused_scan_program", "programs": 1,
                "members": 3},
        alternatives=[{"entry": "per_stage_dispatch", "programs": 5,
                       "cost_programs": 5},
                      {"entry": "pairwise_fusion", "programs": 3,
                       "cost_programs": 3}],
        predicted=predicted or {"programs_per_apply": 1,
                                "programs_eliminated": 4,
                                "cold_compiles_max": 1},
    )


# --------------------------------------------------------------------------
# schema round-trip: write → parse → render


def test_ledger_round_trip_jsonl(tmp_path):
    rec = _record_sample()
    assert rec is not None and rec["enforced"]
    path = ledger.write_session(str(tmp_path / "run.ledger.jsonl"))

    run = ledger.read_ledger(path)
    header = run["header"]
    assert header["ledger_version"] == ledger.LEDGER_VERSION
    # the header snapshots every kill-switch field WITH its env name —
    # the channel --diff uses to name a flip
    assert set(header["config"]) == set(ledger.CONFIG_ENV)
    assert header["config_env"]["megafusion"] == "KEYSTONE_MEGAFUSION"

    (d,) = run["decisions"]
    assert d["kind"] == "megafusion"
    assert d["chosen"]["entry"] == "megafused_scan_program"
    assert len(d["alternatives"]) >= 1
    assert d["predicted"]["programs_per_apply"] == 1
    assert d["seq"] == rec["seq"]

    # the runner-up is the best-priced alternative the chosen entry beat
    ru = ledger.runner_up(d)
    assert ru["entry"] == "pairwise_fusion" and ru["cost_programs"] == 3

    table = ledger.render_ledger(run)
    assert "megafusion" in table and "megafused_scan_program" in table
    assert "pairwise_fusion" in table  # runner-up column
    assert "1 decision(s)" in table


def test_ledger_jsonl_lines_are_independently_parseable(tmp_path):
    """A killed run leaves a parseable prefix: every line is one JSON
    object, header first."""
    _record_sample()
    _record_sample(kind="fusion", labels=("A", "B"))
    path = ledger.write_session(str(tmp_path / "run.ledger.jsonl"))
    lines = [json.loads(line) for line in
             open(path).read().splitlines() if line.strip()]
    assert len(lines) == 3
    assert "ledger_version" in lines[0]
    assert [ln["seq"] for ln in lines[1:]] == [1, 2]


def test_ambient_jsonl_path_appends_incrementally(tmp_path):
    """With `ExecutionConfig.ledger_path` armed (the KEYSTONE_LEDGER
    channel), each record lands on disk at decision time — no explicit
    flush required."""
    path = tmp_path / "ambient.ledger.jsonl"
    with config_override(ledger_path=str(path)):
        _record_sample()
        assert path.exists()
        first = open(path).read().splitlines()
        assert len(first) == 2  # header + one record
        _record_sample(kind="fusion", labels=("C",))
        assert len(open(path).read().splitlines()) == 3
    run = ledger.read_ledger(str(path))
    assert [d["kind"] for d in run["decisions"]] == ["megafusion", "fusion"]


def test_traced_run_defaults_ledger_alongside_trace(tmp_path):
    with config_override(trace_path=str(tmp_path / "run.json"),
                         ledger_path=None):
        assert ledger.resolve_ledger_path() == \
            str(tmp_path / "run.json") + ".ledger.jsonl"
    with config_override(trace_path=None, ledger_path=None):
        assert ledger.resolve_ledger_path() is None


def test_trace_metadata_form_loads(tmp_path):
    """The second artifact form: a trace whose `keystone` metadata
    embeds the decisions loads through the same `read_ledger`."""
    path = str(tmp_path / "run.json")
    with trace_run(path):
        _record_sample()
    run = ledger.read_ledger(path)
    assert run["trace"] is not None
    assert run["header"].get("config", {}).get("megafusion") is True
    (d,) = run["decisions"]
    assert d["kind"] == "megafusion"


def test_suppressed_scope_records_nothing():
    with ledger.suppressed():
        assert _record_sample() is None
    assert ledger.session_decisions() == []


def test_truncated_tail_is_a_parseable_prefix(tmp_path):
    """A run killed mid-append leaves a partial final line; read_ledger
    must return the intact prefix, not raise (the documented contract).
    Corruption anywhere but the tail still raises."""
    _record_sample()
    _record_sample(kind="fusion", labels=("A",))
    path = str(tmp_path / "killed.ledger.jsonl")
    ledger.write_session(path)
    with open(path, "a") as f:
        f.write('{"seq": 3, "kind": "precis')  # killed mid-write
    run = ledger.read_ledger(path)
    assert [d["kind"] for d in run["decisions"]] == ["megafusion", "fusion"]
    assert run["header"]["ledger_version"] == ledger.LEDGER_VERSION

    # mid-file corruption is NOT silently skipped
    lines = open(path).read().splitlines()
    lines[1] = lines[1][:20]
    (tmp_path / "corrupt.jsonl").write_text("\n".join(lines))
    with pytest.raises(ValueError):
        ledger.read_ledger(str(tmp_path / "corrupt.jsonl"))


def test_mid_run_config_change_gets_its_own_header(tmp_path):
    """A process sweeping plans via scoped config overrides (the
    dispatch bench) must not file every decision under the first plan's
    config: a config change mid-file appends a fresh header, and fields
    that varied within the run are excluded from --diff's flip
    comparison (no phantom CONFIG FLIP regressions)."""
    path = tmp_path / "sweep.ledger.jsonl"
    with config_override(ledger_path=str(path)):
        with config_override(megafusion=False):
            _record_sample(kind="fusion", labels=("A",))
        _record_sample()  # back under the ambient (megafusion on) config
    run = ledger.read_ledger(str(path))
    assert len(run["headers"]) == 2
    assert run["headers"][0]["config"]["megafusion"] is False
    assert run["headers"][1]["config"]["megafusion"] is True

    # diff vs a constant-config run: megafusion varied within the sweep
    # run, so it cannot be (and is not) reported as a flip
    b = _write_run(tmp_path, "b.jsonl", megafusion=True)
    diff = ledger.diff_runs(run, ledger.read_ledger(b))
    assert diff["config_flips"] == []


def test_removed_decision_without_flip_names_no_suspect(tmp_path):
    """A decision that vanished under identical config (pipeline edit,
    savings floor) must not blame a kill switch that never flipped."""
    a = _write_run(tmp_path, "a.jsonl", megafusion=True, with_mega=True)
    b = _write_run(tmp_path, "b.jsonl", megafusion=True, with_mega=False)
    diff = ledger.diff_runs(ledger.read_ledger(a), ledger.read_ledger(b))
    assert diff["config_flips"] == []
    (removed,) = diff["decisions_removed"]
    assert removed["kind"] == "megafusion"
    assert removed["suspect_env"] is None


# --------------------------------------------------------------------------
# --diff: kill-switch flip, seeded drift, self-diff


def _write_run(tmp_path, name, megafusion=True, with_mega=True,
               predicted=None):
    ledger.clear_session()
    with config_override(megafusion=megafusion):
        _record_sample(kind="fusion", labels=("A", "B"))
        if with_mega:
            _record_sample(predicted=predicted)
        return ledger.write_session(str(tmp_path / name))


def test_diff_names_injected_megafusion_flip(tmp_path, capsys):
    a = _write_run(tmp_path, "a.jsonl", megafusion=True, with_mega=True)
    b = _write_run(tmp_path, "b.jsonl", megafusion=False, with_mega=False)

    diff = ledger.diff_runs(ledger.read_ledger(a), ledger.read_ledger(b))
    (flip,) = diff["config_flips"]
    assert flip["env"] == "KEYSTONE_MEGAFUSION"
    assert flip["a"] is True and flip["b"] is False
    (removed,) = diff["decisions_removed"]
    assert removed["kind"] == "megafusion"
    assert removed["suspect_env"] == "KEYSTONE_MEGAFUSION"
    assert diff["regressions"] >= 2

    # the CLI contract: exit 1 on regressions, the flip named by env var
    assert telemetry_main(["--diff", a, b]) == 1
    out = capsys.readouterr().out
    assert "CONFIG FLIP: KEYSTONE_MEGAFUSION" in out
    assert "DECISION REMOVED: megafusion" in out
    assert "suspect: KEYSTONE_MEGAFUSION" in out


def test_diff_reports_seeded_prediction_drift(tmp_path, capsys):
    a = _write_run(tmp_path, "a.jsonl")
    b = _write_run(tmp_path, "b.jsonl",
                   predicted={"programs_per_apply": 1,
                              "programs_eliminated": 9,
                              "cold_compiles_max": 1})
    diff = ledger.diff_runs(ledger.read_ledger(a), ledger.read_ledger(b))
    assert diff["config_flips"] == []
    (drift,) = diff["prediction_drift"]
    assert drift["metric"] == "programs_eliminated"
    assert drift["a"] == 4 and drift["b"] == 9
    assert telemetry_main(["--diff", a, b]) == 1
    assert "PREDICTION DRIFT" in capsys.readouterr().out


def test_diff_of_run_against_itself_is_clean(tmp_path, capsys):
    a = _write_run(tmp_path, "a.jsonl")
    diff = ledger.diff_runs(ledger.read_ledger(a), ledger.read_ledger(a))
    assert diff["regressions"] == 0
    assert telemetry_main(["--diff", a, a]) == 0
    assert "0 regression(s)" in capsys.readouterr().out


def test_ledger_cli_renders_table(tmp_path, capsys):
    a = _write_run(tmp_path, "a.jsonl")
    assert telemetry_main(["--ledger", a]) == 0
    out = capsys.readouterr().out
    assert "megafused_scan_program" in out and "runner-up" in out
    assert telemetry_main(["--ledger", a, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert [d["kind"] for d in payload["decisions"]] == \
        ["fusion", "megafusion"]


# --------------------------------------------------------------------------
# predicted vs observed on a real example: exactness pins


def _traced_apply(trace_path, plan="megafused"):
    """Fit MnistRandomFFT outside the measured window, then trace ONE
    apply run with a fresh metrics registry — the run-exact join shape
    `reconcile_decisions` documents."""
    from keystone_tpu.dispatch_bench import EXAMPLES, _plan_context

    optimizer, overlap_on, concurrent_on, overrides = _plan_context(plan)
    PipelineEnv.reset()
    try:
        PipelineEnv.get().set_optimizer(optimizer)
        with overlap_override(overlap_on), \
                dispatch_override(concurrent_on), \
                config_override(**overrides):
            predictor, train, test = EXAMPLES["MnistRandomFFT"]()
            fit_pred = np.asarray(predictor(train).get().numpy())
            from keystone_tpu.workflow.executor import drain_warmups

            drain_warmups()  # background AOT compiles are the fit's
            ledger.clear_session()
            registry().reset()
            with trace_run(trace_path):
                apply_pred = np.asarray(predictor(test).get().numpy())
                drain_warmups()
    finally:
        PipelineEnv.reset()
    return fit_pred, apply_pred


def test_predicted_vs_observed_exactness_mnist(tmp_path):
    """The acceptance pin: the megafused plan's recorded megafusion
    decision predicts EXACTLY the one program the traced apply run
    executed, and the cold-compile prediction upper-bounds the observed
    compiles."""
    from keystone_tpu.analysis.reconcile import reconcile_decisions

    path = str(tmp_path / "apply.json")
    _traced_apply(path)
    run = ledger.read_ledger(path)
    assert run["trace"] is not None

    kinds = {d["kind"] for d in run["decisions"]}
    assert "megafusion" in kinds
    # every enforced decision carries chosen + ≥1 priced alternative +
    # predicted cost — the acceptance schema
    for d in run["decisions"]:
        assert d["enforced"] and d["chosen"]
        assert len(d["alternatives"]) >= 1
        assert d["predicted"]

    rec = reconcile_decisions(run)
    assert rec["run_predicted"]["programs_executed"] == 1
    assert rec["run_observed"]["programs_executed"] == 1
    assert rec["residuals"]["programs_executed"] == 0
    assert rec["run_predicted"]["megafused_programs"] == 1
    assert rec["run_observed"]["megafused_programs"] == 1
    # compiles: the prediction is an upper bound (the persistent cache
    # may serve the program warm), never an undercount
    observed_cold = rec["run_observed"].get("programs_compiled")
    if observed_cold is not None:
        assert observed_cold <= rec["run_predicted"]["programs_compiled_max"]
        assert rec["residuals"]["programs_compiled"] >= 0

    # per-decision: the megafusion row observes its span exactly
    mega_rows = [r for r in rec["rows"] if r["kind"] == "megafusion"]
    assert mega_rows
    assert mega_rows[0]["observed"]["programs_executed"] == 1
    assert mega_rows[0]["residuals"]["programs_per_apply"] == 0


def test_warm_reapply_observes_zero_cold_compiles(tmp_path):
    """Second traced apply of the same fitted pipeline: still exactly 1
    program, exactly 0 cold compiles — predicted-vs-observed exact on
    both counts."""
    from keystone_tpu.analysis.reconcile import reconcile_decisions
    from keystone_tpu.dispatch_bench import EXAMPLES, _plan_context
    from keystone_tpu.workflow.executor import drain_warmups

    optimizer, overlap_on, concurrent_on, overrides = \
        _plan_context("megafused")
    path = str(tmp_path / "warm.json")
    PipelineEnv.reset()
    try:
        PipelineEnv.get().set_optimizer(optimizer)
        with overlap_override(overlap_on), \
                dispatch_override(concurrent_on), \
                config_override(**overrides):
            predictor, train, test = EXAMPLES["MnistRandomFFT"]()
            predictor(train).get()
            predictor(test).get()  # first apply: compiles here
            drain_warmups()
            ledger.clear_session()
            registry().reset()
            with trace_run(path):
                predictor(test).get()
                drain_warmups()
    finally:
        PipelineEnv.reset()
    run = ledger.read_ledger(path)
    rec = reconcile_decisions(run)
    assert rec["run_observed"]["programs_executed"] == 1
    assert rec["run_predicted"]["programs_executed"] == 1
    assert rec["run_observed"].get("programs_compiled", 0) == 0


def test_acceptance_diff_default_vs_megafusion_off(tmp_path):
    """The acceptance criterion end-to-end: --diff between a default
    (megafused) run and a KEYSTONE_MEGAFUSION=0 run names the changed
    decision AND the observed program-count regression."""
    from keystone_tpu.analysis.reconcile import reconcile_decisions

    path_a = str(tmp_path / "default.json")
    path_b = str(tmp_path / "mega_off.json")
    _, pred_a = _traced_apply(path_a, plan="megafused")
    _, pred_b = _traced_apply(path_b, plan="optimized")
    np.testing.assert_array_equal(pred_a, pred_b)

    run_a = ledger.read_ledger(path_a)
    run_b = ledger.read_ledger(path_b)
    diff = ledger.diff_runs(
        run_a, run_b,
        reconciliation_a=reconcile_decisions(run_a),
        reconciliation_b=reconcile_decisions(run_b))

    # the flip is named by env var, not inferred from its fallout
    assert any(f["env"] == "KEYSTONE_MEGAFUSION"
               for f in diff["config_flips"])
    # the changed decision is named, with the kill switch as suspect
    removed = [d for d in diff["decisions_removed"]
               if d["kind"] == "megafusion"]
    assert removed and removed[0]["suspect_env"] == "KEYSTONE_MEGAFUSION"
    # and the observed quantity that regressed is reported: 1 program
    # under megafusion, more without it
    regress = {r["metric"]: r for r in diff["observed_regressions"]}
    assert "programs_executed" in regress
    assert regress["programs_executed"]["a"] == 1
    assert regress["programs_executed"]["b"] > 1
    assert diff["regressions"] >= 3


# --------------------------------------------------------------------------
# the cost-model drift report


def test_cost_model_drift_from_trace(tmp_path):
    from keystone_tpu.analysis.reconcile import (
        cost_model_drift,
        drift_cost_weights,
        format_drift,
    )
    from keystone_tpu.nodes.learning.calibrate import CostWeights

    path = str(tmp_path / "apply.json")
    _traced_apply(path)
    trace = ledger.read_ledger(path)["trace"]
    drift = cost_model_drift(trace)
    assert drift["spans"] > 0 and drift["observed_bytes"] > 0
    by_weight = {r["weight"]: r for r in drift["rows"]}
    # mem_weight is implied by observed seconds-per-byte; cpu_weight by
    # the embedded roofline FLOPs joined against the same spans (the
    # executor embeds keystone.roofline on every traced run); network
    # has no span observable and keeps its current value
    assert by_weight["mem_weight"]["implied"] == pytest.approx(
        drift["observed_seconds"] / drift["observed_bytes"])
    assert by_weight["cpu_weight"]["implied"] is not None
    assert drift["observed_flops"] > 0
    assert by_weight["cpu_weight"]["implied"] > 0
    assert by_weight["network_weight"]["implied"] is None
    assert drift["suggested"]["mem_weight"] == \
        by_weight["mem_weight"]["implied"]
    assert drift["suggested"]["cpu_weight"] == \
        by_weight["cpu_weight"]["implied"]
    assert drift["suggested"]["network_weight"] == \
        by_weight["network_weight"]["current"]
    assert drift["roofline"] is not None
    assert drift["roofline"]["stages_joined"] > 0

    weights = drift_cost_weights(trace)
    assert isinstance(weights, CostWeights)
    assert weights.mem_weight == drift["suggested"]["mem_weight"]
    assert weights.cpu_weight == drift["suggested"]["cpu_weight"]

    rendered = format_drift(drift)
    assert "mem_weight" in rendered and "unmeasured" in rendered
    assert "flops residual" in rendered
