"""Certified serving runtime tests (serving/ — the live KP9xx half).

The acceptance contract: a started runtime serves traffic *because* a
certificate holds — every dispatched batch shape is on the warmed pad
ladder (0 cold compiles), results are exactly the direct
`FittedPipeline.apply` results, overload is shed (counted and
flight-dumped) instead of buffered, hot-swap loses zero requests, KP905
refuses over-budget tenants statically, and the
``KEYSTONE_SERVING_COALESCE=0`` kill switch reproduces per-request
dispatch bit-for-bit.
"""

import threading
import time

import numpy as np
import pytest

from keystone_tpu.analysis import ServingEnvelope
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
from keystone_tpu.nodes.stats import LinearRectifier, PaddedFFT, RandomSignNode
from keystone_tpu.nodes.util import (
    ClassLabelIndicatorsFromInt,
    MaxClassifier,
    VectorCombiner,
)
from keystone_tpu.serving import (
    AdmissionRefused,
    CertificationError,
    IngressError,
    MicroBatcher,
    NdarrayIngress,
    ServingRuntime,
    ShedError,
    TenantRegistry,
    TextIngress,
    split_fitted_at,
)
from keystone_tpu.telemetry import ledger
from keystone_tpu.telemetry.flight import reset_flight
from keystone_tpu.telemetry.metrics import counter
from keystone_tpu.telemetry.streaming import reset_live
from keystone_tpu.telemetry.watchdog import active_watchdog, disarm_watchdog
from keystone_tpu.workflow import Pipeline, PipelineEnv
from keystone_tpu.workflow.env import config_override

DIM, N, K = 16, 48, 3
LADDER = (1, 2, 4, 8)


@pytest.fixture(autouse=True)
def _reset_env(monkeypatch):
    for var in ("KEYSTONE_SLO_MS", "KEYSTONE_SERVING_MAX_BATCH",
                "KEYSTONE_SERVING_TENANTS", "KEYSTONE_SERVING_COALESCE",
                "KEYSTONE_SERVING_QUEUE_DEPTH",
                "KEYSTONE_SERVING_WINDOW_MS"):
        monkeypatch.delenv(var, raising=False)
    PipelineEnv.reset()
    reset_live()
    yield
    disarm_watchdog()
    reset_flight()
    reset_live()
    PipelineEnv.reset()


def _fit_predictor(label_seed: int = 0):
    """The tiny real fitted pipeline from test_serving.py: gather(2 fft
    branches) → block LS → argmax. ``label_seed`` varies the training
    labels so hot-swap tests get a genuinely different model."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(N, DIM)).astype(np.float32)
    y = np.random.default_rng(label_seed).integers(0, K, N).astype(np.int32)
    branches = [
        RandomSignNode(DIM, seed=i) >> PaddedFFT() >> LinearRectifier(0.0)
        for i in range(2)
    ]
    feat = Pipeline.gather(branches) >> VectorCombiner()
    train = Dataset.from_numpy(X)
    labels = ClassLabelIndicatorsFromInt(K)(Dataset.from_numpy(y)).get()
    pred = feat.and_then(
        BlockLeastSquaresEstimator(32, 1, 1e-2), train, labels
    ) >> MaxClassifier()
    return pred.fit(), X


@pytest.fixture(scope="module")
def fitted_and_data():
    return _fit_predictor()


def _direct(fitted, X):
    return np.asarray(fitted.apply(Dataset.from_numpy(X)).numpy())


def _runtime(fitted, max_batch: int = 8, **kw):
    kw.setdefault("envelope", ServingEnvelope(max_batch=max_batch,
                                              slo_seconds=1.0))
    kw.setdefault("name", "test-runtime")
    return ServingRuntime(fitted, NdarrayIngress((DIM,)), **kw)


def _fire(rt, X, indices, timeout=60.0):
    """Submit rows concurrently; returns (results dict, errors list)."""
    results, errors = {}, []

    def client(i):
        try:
            results[i] = rt.submit(X[i], timeout=timeout)
        except Exception as e:  # noqa: BLE001 - recorded for asserts
            errors.append((i, e))

    threads = [threading.Thread(target=client, args=(i,)) for i in indices]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results, errors


def _compile_events(fn):
    """Run fn, return (number of XLA compile requests, result)."""
    from jax._src import monitoring

    events = []

    def listener(name, **kw):
        if name == "/jax/compilation_cache/compile_requests_use_cache":
            events.append(name)

    monitoring.register_event_listener(listener)
    try:
        out = fn()
    finally:
        try:
            monitoring._event_listeners.remove(listener)
        except ValueError:  # pragma: no cover - listener wrapper changed
            monitoring.clear_event_listeners()
    return len(events), out


# --------------------------------------------------------- core dispatch


def test_concurrent_requests_coalesce_on_ladder_and_match_direct(
        fitted_and_data):
    fitted, X = fitted_and_data
    ref = _direct(fitted, X)
    rt = _runtime(fitted).start()
    try:
        results, errors = _fire(rt, X, range(8))
        assert not errors, errors
        for i in range(8):
            assert np.allclose(results[i], ref[i]), i
        stats = rt.stats()
        assert stats["dispatched_shapes"], "nothing dispatched"
        assert stats["dispatched_outside_ladder"] == [], (
            "a coalesced dispatch left the certified pad ladder: "
            f"{stats['dispatched_shapes']} vs ladder {stats['ladder']}")
    finally:
        rt.stop()


def test_single_request_matches_direct_apply(fitted_and_data):
    fitted, X = fitted_and_data
    ref = _direct(fitted, X)
    rt = _runtime(fitted).start()
    try:
        out = rt.submit(X[3])
        assert np.allclose(out, ref[3])
        assert rt.stats()["dispatched_outside_ladder"] == []
    finally:
        rt.stop()


def test_saturated_queue_path_matches_direct_apply(fitted_and_data):
    """More offered work than one batch can carry: every request still
    completes with the direct-apply result (backlog drains through
    successive ladder-shaped dispatches, nothing is reordered across
    its own row)."""
    fitted, X = fitted_and_data
    ref = _direct(fitted, X)
    rt = _runtime(fitted, max_batch=4).start()
    try:
        results, errors = _fire(rt, X, range(32))
        assert not errors, errors
        for i in range(32):
            assert np.allclose(results[i], ref[i]), i
        assert rt.stats()["dispatched_outside_ladder"] == []
        assert counter("serving.dispatches").snapshot()["value"] >= 8
    finally:
        rt.stop()


def test_warm_runtime_serves_full_ladder_with_zero_cold_compiles(
        fitted_and_data):
    fitted, X = fitted_and_data
    rt = _runtime(fitted).start()  # start() warms + drains the manifest
    try:
        def serve():
            for b in LADDER:
                results, errors = _fire(rt, X, range(b))
                assert not errors and len(results) == b
        n_compiles, _ = _compile_events(serve)
        assert n_compiles == 0, (
            f"warm runtime performed {n_compiles} cold compile(s) while "
            f"serving concurrency levels {LADDER} — the warmed-manifest "
            "claim (0 cold compiles at any in-envelope shape) is broken")
        assert rt.stats()["dispatched_outside_ladder"] == []
    finally:
        rt.stop()


def test_ragged_coalesced_batch_pads_onto_ladder_with_zero_compiles(
        fitted_and_data):
    """A coalescing window can close on ANY count ≤ max_batch (say 3,
    or 11 of 16) — the dispatch must pad onto the pow-2 rung and slice
    the riders back out, because a top-level Dataset apply otherwise
    runs at its exact leading dim and cold-compiles an off-ladder
    program the certificate never priced or warmed."""
    fitted, X = fitted_and_data
    ref = _direct(fitted, X)
    rt = _runtime(fitted).start()
    try:
        def ragged():
            return {n: rt._apply_batch(X[:n]) for n in (3, 5, 6, 7)}
        n_compiles, outs = _compile_events(ragged)
        assert n_compiles == 0, (
            f"{n_compiles} cold compile(s) dispatching ragged coalesced "
            "counts (3, 5, 6, 7) on a warm runtime — ragged batches must "
            "pad onto the warmed ladder, not compile their own programs")
        for n, out in outs.items():
            assert out.shape[0] == n, (n, out.shape)
            assert np.allclose(out, ref[:n]), n
        stats = rt.stats()
        assert stats["dispatched_outside_ladder"] == []
        assert set(stats["dispatched_shapes"]) <= {4, 8}
    finally:
        rt.stop()


# ------------------------------------------------------------- hot swap


def test_hot_swap_mid_traffic_loses_nothing_and_flips_atomically():
    fitted_a, X = _fit_predictor(label_seed=0)
    fitted_b, _ = _fit_predictor(label_seed=99)
    ref_a = _direct(fitted_a, X)
    ref_b = _direct(fitted_b, X)
    assert not np.allclose(ref_a, ref_b), "swap fixture models identical"
    rt = _runtime(fitted_a).start()
    try:
        stop_traffic = threading.Event()
        outcomes, errors = [], []

        def client_loop(i):
            while not stop_traffic.is_set():
                try:
                    out = rt.submit(X[i % N])
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    return
                ok_a = np.allclose(out, ref_a[i % N])
                ok_b = np.allclose(out, ref_b[i % N])
                outcomes.append((ok_a, ok_b))
                i += 4

        threads = [threading.Thread(target=client_loop, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        rt.swap(fitted_b)  # certifies + warms B, then one atomic flip
        time.sleep(0.3)
        stop_traffic.set()
        for t in threads:
            t.join(timeout=30)
        assert not errors, f"hot swap dropped requests: {errors[:3]}"
        assert outcomes
        # every response matches exactly one of the two versions — no
        # torn batch ever mixed weights
        assert all(ok_a or ok_b for ok_a, ok_b in outcomes)
        # after the flip, fresh requests are served by B
        post = rt.submit(X[5])
        assert np.allclose(post, ref_b[5])
        assert rt.certificate is not None and rt.certificate.certified
        assert counter("serving.hot_swaps").snapshot()["value"] >= 1
    finally:
        rt.stop()


# ----------------------------------------------------- admission (KP905)


def test_registry_refuses_over_budget_tenant_statically(fitted_and_data):
    fitted, _ = fitted_and_data
    rt = _runtime(fitted)
    registry = TenantRegistry(hbm_budget_bytes=1000)
    registry.admit("tenant-a", rt, per_device_peak_bytes=600)
    mark = ledger.session_mark()
    with pytest.raises(AdmissionRefused, match="KP905"):
        registry.admit("tenant-b", rt, per_device_peak_bytes=600)
    assert registry.tenants() == ["tenant-a"]
    assert registry.resident_bytes() == 600
    records = [r for r in ledger.session_since(mark)
               if r["kind"] == "serving_admission"]
    assert records and records[-1]["chosen"]["entry"] == "refuse"
    # evicting the resident tenant frees the budget
    registry.evict("tenant-a")
    registry.admit("tenant-b", rt, per_device_peak_bytes=600)
    assert registry.tenants() == ["tenant-b"]


def test_runtime_certificate_carries_priced_residency(fitted_and_data):
    fitted, _ = fitted_and_data
    rt = _runtime(fitted).start()
    try:
        assert rt.certificate.per_device_peak_bytes
        registry = TenantRegistry(hbm_budget_bytes=1 << 40)
        registry.admit("priced", rt)  # peak defaults from the cert
        assert registry.resident_bytes() == \
            rt.certificate.per_device_peak_bytes
    finally:
        rt.stop()


# ----------------------------------------------------------- load shed


def test_shed_increments_counter_and_dumps_flight_ring(tmp_path,
                                                       monkeypatch):
    monkeypatch.setenv("KEYSTONE_FLIGHT_DIR", str(tmp_path))
    release = threading.Event()

    def slow_apply(batch):
        release.wait(10.0)
        return batch

    with config_override(serving_queue_depth=1, serving_window_ms=0.0):
        mb = MicroBatcher(slow_apply, max_batch=1).start()
    before = counter("serving.shed_total").snapshot()["value"]
    try:
        row = np.zeros(4, np.float32)
        threads = []
        shed = []

        def client():
            try:
                mb.submit(row, timeout=20.0)
            except ShedError as e:
                shed.append(e)

        for _ in range(8):
            t = threading.Thread(target=client)
            t.start()
            threads.append(t)
        deadline = time.monotonic() + 5.0
        while not shed and time.monotonic() < deadline:
            time.sleep(0.01)
        release.set()
        for t in threads:
            t.join(timeout=30)
        assert shed, "no request was shed with a depth-1 queue"
        after = counter("serving.shed_total").snapshot()["value"]
        assert after - before >= len(shed)
        dumps = list(tmp_path.glob("keystone_flight_*_shed.json"))
        assert dumps, "shed did not dump the flight ring"
    finally:
        release.set()
        mb.stop()


# --------------------------------------------------------- kill switch


def test_coalesce_kill_switch_reverts_to_per_request_bit_for_bit(
        fitted_and_data):
    fitted, X = fitted_and_data
    with config_override(serving_coalesce=False):
        rt = _runtime(fitted).start()
        try:
            assert rt._batcher._thread is None, (
                "kill switch must not start a dispatcher thread")
            for i in range(4):
                out = rt.submit(X[i])
                ref = np.asarray(
                    fitted.apply(
                        Dataset.from_numpy(X[i:i + 1])).numpy())[0]
                assert np.array_equal(np.asarray(out), ref), (
                    f"kill-switch result for row {i} is not bit-for-bit "
                    "the direct per-request apply")
            assert rt.stats()["dispatched_shapes"] == [1]
        finally:
            rt.stop()


# -------------------------------------------------------------- ingress


def test_ingress_refuses_off_schema_requests(fitted_and_data):
    fitted, X = fitted_and_data
    rt = _runtime(fitted).start()
    try:
        with pytest.raises(IngressError, match="declared ingress"):
            rt.submit(np.zeros(DIM + 1, np.float32))
        with pytest.raises(IngressError):
            rt.submit(np.zeros((2, DIM), np.float32))
        # a castable dtype is accepted, not refused
        out = rt.submit(X[0].astype(np.float64))
        assert out is not None
    finally:
        rt.stop()


def test_uncertified_pipeline_is_refused_at_start(fitted_and_data):
    fitted, _ = fitted_and_data
    rt = _runtime(fitted, envelope=ServingEnvelope(
        max_batch=8, slo_seconds=1e-9))  # KP903 cannot hold
    with pytest.raises(CertificationError, match="KP903"):
        rt.start()
    assert active_watchdog() is None


# ------------------------------------------------- text ingress (split)


def test_text_ingress_serves_newsgroups_device_tail():
    from keystone_tpu.pipelines.text_pipelines import (
        build_newsgroups_predictor,
        synthetic_corpus,
    )

    labels, docs = synthetic_corpus(64, 3, vocab_size=120, doc_len=30)
    fitted = build_newsgroups_predictor(
        docs, labels, 3, ngram_orders=(1,), common_features=500).fit()
    doc_list = list(docs)
    direct = [int(np.asarray(fitted.apply(d))) for d in doc_list[:6]]

    host_ops, tail = split_fitted_at(fitted, "NaiveBayesModel")
    assert [op.label for op in host_ops] == [
        "Trim", "LowerCase", "Tokenizer", "NGramsFeaturizer",
        "TermFrequency", "SparseFeatureVectorizer"]
    ingress = TextIngress(host_ops)
    row = ingress.accept(doc_list[0])
    rt = ServingRuntime(
        tail, ingress, element_shape=row.shape,
        envelope=ServingEnvelope(max_batch=8, slo_seconds=1.0),
        name="newsgroups").start()
    try:
        assert rt.certificate.certified, (
            "the Newsgroups device tail must certify clean — the KP901 "
            "suppression promised exactly this split")
        results, errors = _fire(rt, doc_list, range(6))
        assert not errors, errors
        for i in range(6):
            assert int(np.asarray(results[i])) == direct[i]
        assert rt.stats()["dispatched_outside_ladder"] == []
        with pytest.raises(IngressError, match="document string"):
            rt.submit(123)
    finally:
        rt.stop()


def test_split_refuses_missing_boundary(fitted_and_data):
    fitted, _ = fitted_and_data
    with pytest.raises(ValueError, match="not on the apply path"):
        split_fitted_at(fitted, "NoSuchStage")


# ------------------------------------------------------ handoff record


def test_start_emits_certificate_handoff_record(fitted_and_data):
    fitted, _ = fitted_and_data
    mark = ledger.session_mark()
    rt = _runtime(fitted).start()
    try:
        records = [r for r in ledger.session_since(mark)
                   if r["kind"] == "serving_handoff"]
        assert len(records) == 1
        rec = records[0]
        assert rec["labels"] == ["test-runtime"]
        assert rec["chosen"]["entry"] == "coalesced micro-batching"
        assert rec["chosen"]["ladder_shapes"] == list(LADDER)
        assert rec["chosen"]["warmed_sites"] == rt.warmed_sites >= 1
        assert rec["predicted"]["worst_shape_seconds"] > 0
        # the watchdog armed from the same certificate
        wd = active_watchdog()
        assert wd is not None
        assert set(wd.bounds) == set(LADDER)
    finally:
        rt.stop()
    assert active_watchdog() is None, "stop() must disarm the watchdog"
