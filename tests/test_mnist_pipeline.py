"""End-to-end MnistRandomFFT slice on real data (sklearn digits), the
build plan's minimum end-to-end milestone (SURVEY.md §7.4)."""

from keystone_tpu.pipelines.mnist_random_fft import MnistRandomFFTConfig, run


def test_mnist_random_fft_end_to_end():
    result = run(MnistRandomFFTConfig(num_ffts=4, block_size=512, lam=1e-3))
    # digits with random-FFT features solves well above chance; the
    # reference quality bar for this config is a few percent error.
    assert result["test_accuracy"] > 0.90, result["summary"]
    assert result["train_error"] < 0.05
