"""Averaged-perceptron POS/NER: real tagging accuracy on held-out
sentences of the bundled corpora (the capability the reference gets from
its downloaded Epic CRF models, POSTagger.scala:24-36, NER.scala:20-32 —
VERDICT r1 item 9 asked for accuracy assertions, not just shapes)."""

import os

import numpy as np

from keystone_tpu.nodes.nlp.annotators import NER, POSTagger, _DATA_DIR
from keystone_tpu.nodes.nlp.perceptron_tagger import (
    AveragedPerceptronTagger,
    load_tagged_corpus,
)


def _held_out_accuracy(corpus, n_iter=8):
    sentences = load_tagged_corpus(os.path.join(_DATA_DIR, corpus))
    rng = np.random.default_rng(0)
    order = rng.permutation(len(sentences))
    cut = int(len(sentences) * 0.8)
    train = [sentences[i] for i in order[:cut]]
    test = [sentences[i] for i in order[cut:]]
    tagger = AveragedPerceptronTagger().train(train, n_iter=n_iter)
    correct = total = 0
    for sent in test:
        tokens = [w for w, _ in sent]
        pred = tagger(tokens)
        for p, (_, gold) in zip(pred, sent):
            correct += p == gold
            total += 1
    return correct / total


def test_pos_held_out_accuracy():
    acc = _held_out_accuracy("pos_corpus.txt")
    assert acc >= 0.90, acc


def test_ner_held_out_accuracy():
    acc = _held_out_accuracy("ner_corpus.txt")
    assert acc >= 0.90, acc


def test_trained_pos_tagger_tags_new_sentence():
    tagger = POSTagger.trained()
    tagged = tagger.apply(["The", "farmer", "repairs", "the", "old", "cart", "."])
    tags = [t for _, t in tagged]
    assert tags == ["DT", "NN", "VBZ", "DT", "JJ", "NN", "."]


def test_trained_ner_tags_new_sentence():
    ner = NER.trained()
    tagged = ner.apply(["Emma", "visited", "Berlin", "with", "Thomas", "."])
    tags = dict(tagged)
    assert tags["Emma"] == "PER"
    assert tags["Berlin"] == "LOC"
    assert tags["Thomas"] == "PER"
    assert tags["visited"] == "O"


def test_save_load_round_trip(tmp_path):
    sentences = load_tagged_corpus(os.path.join(_DATA_DIR, "pos_corpus.txt"))
    tagger = AveragedPerceptronTagger().train(sentences, n_iter=3)
    path = str(tmp_path / "tagger.json")
    tagger.save(path)
    loaded = AveragedPerceptronTagger.load(path)
    tokens = [w for w, _ in sentences[0]]
    assert loaded(tokens) == tagger(tokens)


def test_model_hook_still_accepts_custom_callable():
    tagger = POSTagger(model=lambda toks: ["X"] * len(toks))
    assert tagger.apply(["a", "b"]) == [("a", "X"), ("b", "X")]


def test_bundled_tagger_cached_per_corpus():
    from keystone_tpu.nodes.nlp.annotators import bundled_tagger

    assert bundled_tagger("pos_corpus.txt") is bundled_tagger("pos_corpus.txt")
    assert bundled_tagger("pos_corpus.txt") is not bundled_tagger("ner_corpus.txt")
