"""Averaged-perceptron POS/NER: real tagging accuracy on held-out
sentences of the bundled corpora (the capability the reference gets from
its downloaded Epic CRF models, POSTagger.scala:24-36, NER.scala:20-32 —
VERDICT r1 item 9 asked for accuracy assertions, not just shapes)."""

import os

import numpy as np

from keystone_tpu.nodes.nlp.annotators import NER, POSTagger, _DATA_DIR
from keystone_tpu.nodes.nlp.perceptron_tagger import (
    AveragedPerceptronTagger,
    StructuredPerceptronTagger,
    load_tagged_corpus,
)


def _split(corpus):
    sentences = load_tagged_corpus(os.path.join(_DATA_DIR, corpus))
    rng = np.random.default_rng(0)
    order = rng.permutation(len(sentences))
    cut = int(len(sentences) * 0.8)
    return ([sentences[i] for i in order[:cut]],
            [sentences[i] for i in order[cut:]])


def _held_out_accuracy(corpus, cls=AveragedPerceptronTagger):
    train, test = _split(corpus)
    tagger = cls().train(train)
    correct = total = 0
    for sent in test:
        tokens = [w for w, _ in sent]
        pred = tagger(tokens)
        for p, (_, gold) in zip(pred, sent):
            correct += p == gold
            total += 1
    return correct / total


def test_pos_held_out_accuracy():
    acc = _held_out_accuracy("pos_corpus.txt")
    assert acc >= 0.90, acc


def test_ner_held_out_accuracy():
    acc = _held_out_accuracy("ner_corpus.txt")
    assert acc >= 0.90, acc


def test_structured_beats_greedy_on_both_corpora():
    """The model-class upgrade (VERDICT r3 #7): Viterbi-decoded
    structured perceptron must beat the greedy averaged perceptron on the
    SAME held-out split of each bundled corpus (measured: POS 0.978 vs
    0.961, NER 0.976 vs 0.968)."""
    for corpus in ("pos_corpus.txt", "ner_corpus.txt"):
        greedy = _held_out_accuracy(corpus, AveragedPerceptronTagger)
        struct = _held_out_accuracy(corpus, StructuredPerceptronTagger)
        assert struct > greedy, (corpus, struct, greedy)
        assert struct >= 0.95, (corpus, struct)


def test_structured_save_load_round_trip(tmp_path):
    train, test = _split("pos_corpus.txt")
    tagger = StructuredPerceptronTagger().train(train, n_iter=3)
    path = str(tmp_path / "struct.json")
    tagger.save(path)
    loaded = StructuredPerceptronTagger.load(path)
    for sent in test[:5]:
        tokens = [w for w, _ in sent]
        assert loaded(tokens) == tagger(tokens)


def test_structured_empty_and_single_token():
    train, _ = _split("pos_corpus.txt")
    tagger = StructuredPerceptronTagger().train(train, n_iter=2)
    assert tagger([]) == []
    assert len(tagger(["dog"])) == 1


def test_viterbi_uses_transitions():
    """A corpus where the emission-only argmax is wrong and only the
    learned transition structure disambiguates: 'x' is tagged A after P
    and B after Q with identical emission context frequency."""
    sents = [[("p", "P"), ("x", "A")], [("q", "Q"), ("x", "B")]] * 6
    tagger = StructuredPerceptronTagger().train(sents, n_iter=6)
    assert tagger(["p", "x"]) == ["P", "A"]
    assert tagger(["q", "x"]) == ["Q", "B"]


def test_trained_pos_tagger_tags_new_sentence():
    tagger = POSTagger.trained()
    tagged = tagger.apply(["The", "farmer", "repairs", "the", "old", "cart", "."])
    tags = [t for _, t in tagged]
    assert tags == ["DT", "NN", "VBZ", "DT", "JJ", "NN", "."]


def test_trained_ner_tags_new_sentence():
    ner = NER.trained()
    tagged = ner.apply(["Emma", "visited", "Berlin", "with", "Thomas", "."])
    tags = dict(tagged)
    assert tags["Emma"] == "PER"
    assert tags["Berlin"] == "LOC"
    assert tags["Thomas"] == "PER"
    assert tags["visited"] == "O"


def test_save_load_round_trip(tmp_path):
    sentences = load_tagged_corpus(os.path.join(_DATA_DIR, "pos_corpus.txt"))
    tagger = AveragedPerceptronTagger().train(sentences, n_iter=3)
    path = str(tmp_path / "tagger.json")
    tagger.save(path)
    loaded = AveragedPerceptronTagger.load(path)
    tokens = [w for w, _ in sentences[0]]
    assert loaded(tokens) == tagger(tokens)


def test_model_hook_still_accepts_custom_callable():
    tagger = POSTagger(model=lambda toks: ["X"] * len(toks))
    assert tagger.apply(["a", "b"]) == [("a", "X"), ("b", "X")]


def test_bundled_tagger_cached_per_corpus():
    from keystone_tpu.nodes.nlp.annotators import bundled_tagger

    assert bundled_tagger("pos_corpus.txt") is bundled_tagger("pos_corpus.txt")
    assert bundled_tagger("pos_corpus.txt") is not bundled_tagger("ner_corpus.txt")
    # trained() now serves the structured (Viterbi) model class
    assert isinstance(bundled_tagger("pos_corpus.txt"), StructuredPerceptronTagger)


def test_lemmatizer_rules_and_exceptions():
    """Rule+exception lemmatizer (VERDICT r3 #7; CoreNLP Morphology
    architecture: irregular table first, then ordered suffix rules)."""
    from keystone_tpu.nodes.nlp.annotators import _lemma

    # exception table: irregular verbs / nouns / comparatives
    assert _lemma("went") == "go"
    assert _lemma("was") == "be" and _lemma("were") == "be"
    assert _lemma("children") == "child"
    assert _lemma("mice") == "mouse"
    assert _lemma("better") == "good"
    assert _lemma("wrote") == "write"
    # ordered rules
    assert _lemma("studies") == "study"      # -ies -> y
    assert _lemma("boxes") == "box"          # -xes -> x
    assert _lemma("cats") == "cat"           # plain -s
    assert _lemma("running") == "run"        # doubled consonant
    assert _lemma("making") == "make"        # silent-e restore
    assert _lemma("visited") == "visit"      # no-e exception set
    assert _lemma("opened") == "open"
    assert _lemma("believed") == "believe"   # v-final always restores
    assert _lemma("invited") == "invite"     # default restores the e
    assert _lemma("decided") == "decide"
    assert _lemma("escaped") == "escape"
    assert _lemma("studied") == "study"      # -ied -> y
    assert _lemma("walked") == "walk"
    assert _lemma("sizes") == "size"         # -zes: -ze stem class
    assert _lemma("prizes") == "prize"
    # -z silent-e restore requires a preceding vowel: a consonant
    # cluster before the z never dropped an e
    assert _lemma("sized") == "size"         # vowel+z -> restore
    assert _lemma("dozed") == "doze"
    assert _lemma("analyzed") == "analyze"   # y counts as the vowel
    assert _lemma("paralyzed") == "paralyze"
    assert _lemma("waltzed") == "waltz"      # consonant+z -> keep
    assert _lemma("waltzing") == "waltz"
    assert _lemma("blitzed") == "blitz"
    # v-final stays unconditional regardless of the preceding letter
    assert _lemma("carved") == "carve"
    assert _lemma("served") == "serve"
    # invariants the rules must NOT mangle
    assert _lemma("news") == "news"
    assert _lemma("species") == "species"
    assert _lemma("thing") == "thing"
    assert _lemma("glass") == "glass"        # -ss guard
    assert _lemma("The") == "the"            # case folding
    # adverbs keep their own lemma (WordNet/CoreNLP behavior); the old
    # -ly rule mangled family/assembly-class nouns
    assert _lemma("quickly") == "quickly"
    assert _lemma("family") == "family"
    assert _lemma("assembly") == "assembly"


def test_lemmatizer_gold_fidelity():
    """Corpus-level fidelity measurement (VERDICT r4 missing #3): the
    lemmatizer against a 487-pair curated inflection->lemma gold set
    (tests/resources/lemma_gold.tsv) spanning regular plurals, -es/-ies/
    -ves classes, irregular nouns/verbs/participles, gemination vs
    inherent doubles (running/telling), silent-e restoration classes
    (-nc/-rc/-rg/-dg soft clusters, CVC), latinate/greek plurals,
    comparatives, and invariant -s words. The measured accuracy is
    asserted as a floor so morphology regressions fail loudly; misses
    are printed for diagnosis."""
    import os

    from keystone_tpu.nodes.nlp.annotators import _lemma

    path = os.path.join(os.path.dirname(__file__), "resources",
                        "lemma_gold.tsv")
    pairs = [line.split("\t") for line in
             open(path).read().strip().split("\n")]
    assert len(pairs) >= 480
    misses = [(w, g.strip(), _lemma(w)) for w, g in pairs
              if _lemma(w) != g.strip()]
    acc = (len(pairs) - len(misses)) / len(pairs)
    assert acc >= 0.97, (acc, misses[:20])
