"""Out-of-core execution (planner-governed host spill + windowed
streaming, the arXiv 1610.09451 §5 regime brought onto the pad ladder).

The acceptance contract asserted in-tree: the windowed spill prefetcher
covers exactly ``range(count)`` at window-multiple AND ragged counts on
both the serial and overlapped paths, with padded phantom rows never
escaping; `OutOfCoreDataset`/`SpilledDataset` round-trip losslessly
through their sanctioned drains while charging the spill byte counters;
the unified planner prices the host-spill alternative (feasible)
against the device cache (INF) under a budget the cache busts, enforces
a HOST-placed `CacheMarker` end-to-end with output parity, and appends
the kind="spill" ledger record; and ``KEYSTONE_OOC_SPILL=0`` reproduces
the spill-free plan bit-for-bit (no spill entries scored, empty spill
set, no host placement).
"""

import jax
import numpy as np
import pytest

from keystone_tpu.analysis.plan_ir import plan_unified
from keystone_tpu.analysis.propagate import spec_pass
from keystone_tpu.data.dataset import Dataset, OutOfCoreDataset, SpilledDataset
from keystone_tpu.loaders import synthetic_out_of_core
from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
from keystone_tpu.nodes.stats import LinearRectifier, PaddedFFT, RandomSignNode
from keystone_tpu.nodes.util import ClassLabelIndicatorsFromInt, MaxClassifier
from keystone_tpu.parallel.mesh import make_mesh, use_mesh
from keystone_tpu.telemetry import counter, ledger
from keystone_tpu.utils.batching import map_spill_windows, stream_spill_windows
from keystone_tpu.workflow.autocache import CacheMarker
from keystone_tpu.workflow.env import config_override, overlap_override
from keystone_tpu.workflow.pipeline import PipelineEnv


def _host_rows(n, dim=16, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randn(n, dim).astype(np.float32)


def _loader(X):
    return lambda lo, hi: X[lo:hi]


# ------------------------------------------------- windowed streaming


@pytest.mark.parametrize("overlap", [False, True],
                         ids=["serial", "overlapped"])
@pytest.mark.parametrize("count", [512, 513, 3 * 128 - 29],
                         ids=["multiple", "ragged+1", "ragged-tail"])
def test_spill_windows_cover_exactly_and_reassemble(count, overlap):
    """Every yielded index appears exactly once, in order, at
    window-multiple AND ragged counts; slicing each padded device
    window to ``len(indices)`` rows reassembles the host source
    bit-for-bit on the serial and overlapped paths alike."""
    X = _host_rows(count)
    with overlap_override(overlap):
        seen, parts = [], []
        for idxs, win in stream_spill_windows(_loader(X), count,
                                              window=128):
            assert len(idxs) <= win.shape[0]  # padded onto the ladder
            seen.extend(int(i) for i in idxs)
            parts.append(np.asarray(win)[: len(idxs)])
    assert seen == list(range(count))
    np.testing.assert_array_equal(np.concatenate(parts), X)


@pytest.mark.parametrize("overlap", [False, True],
                         ids=["serial", "overlapped"])
def test_map_spill_windows_slices_padding_before_results(overlap):
    """`map_spill_windows` applies the fn to the PADDED window but
    yields per-row results with phantom rows already sliced off — the
    ragged final window contributes exactly its true rows."""
    count = 5 * 64 - 17
    X = _host_rows(count)
    out = np.zeros_like(X)
    with overlap_override(overlap):
        for idxs, results in map_spill_windows(_loader(X), count,
                                               lambda w: w * 2.0,
                                               window=64):
            for i, r in zip(idxs, results):
                out[i] = r
    np.testing.assert_allclose(out, X * 2.0, rtol=1e-6)


def test_spill_window_trips_counted():
    count = 4 * 128
    before = counter("spill.window_trips").value
    list(stream_spill_windows(_loader(_host_rows(count)), count,
                              window=128))
    assert counter("spill.window_trips").value - before == 4


# --------------------------------------------------- the dataset forms


def test_out_of_core_row_loader_crosses_shards():
    """`row_loader` ranges spanning shard boundaries concatenate the
    overlapping shards exactly; `window_iter` coverage is exact with a
    ragged final shard; `materialize()` is the lossless full drain."""
    X = _host_rows(1000, dim=8)
    bounds = [0, 256, 512, 768, 1000]  # ragged 232-row final shard
    ds = OutOfCoreDataset(
        [(lambda lo=lo, hi=hi: X[lo:hi])
         for lo, hi in zip(bounds, bounds[1:])],
        [hi - lo for lo, hi in zip(bounds, bounds[1:])])
    assert ds.count == 1000
    np.testing.assert_array_equal(ds.row_loader(200, 600), X[200:600])
    np.testing.assert_array_equal(ds.row_loader(760, 1000), X[760:1000])
    seen = []
    for idxs, win in ds.window_iter(window=128):
        seen.extend(int(i) for i in idxs)
        np.testing.assert_array_equal(np.asarray(win)[: len(idxs)],
                                      X[idxs[0]: idxs[-1] + 1])
    assert seen == list(range(1000))
    np.testing.assert_array_equal(np.asarray(ds.materialize().array),
                                  X)


def test_synthetic_out_of_core_is_deterministic():
    a = synthetic_out_of_core(600, 8, shard_rows=256)
    b = synthetic_out_of_core(600, 8, shard_rows=256)
    np.testing.assert_array_equal(a.row_loader(100, 500),
                                  b.row_loader(100, 500))


def test_spilled_dataset_round_trip_counts_bytes():
    """spill() → rehydrate() is lossless (device padding trimmed at the
    spill seam) and both directions charge the spill byte counters."""
    X = _host_rows(300, dim=8)
    ds = Dataset.from_numpy(X)
    out_before = counter("spill.bytes_out").value
    spilled = SpilledDataset.spill(ds)
    assert spilled.is_spilled and spilled.count == 300
    assert counter("spill.bytes_out").value - out_before >= X.nbytes
    in_before = counter("spill.bytes_in").value
    back = spilled.rehydrate()
    assert counter("spill.bytes_in").value - in_before >= X.nbytes
    assert back.count == 300
    # .array may re-pad to the device shard multiple; true rows first
    np.testing.assert_array_equal(np.asarray(back.array)[:300], X)


# ------------------------------------------------- the planner's choice


def _predictor(data, labels_ds, dim=64, classes=4):
    featurizer = (RandomSignNode(dim).to_pipeline() >> PaddedFFT()
                  >> LinearRectifier(0.0))
    labels = ClassLabelIndicatorsFromInt(classes)(labels_ds)
    return featurizer.and_then(
        BlockLeastSquaresEstimator(32, num_iter=1, lam=1e-3),
        data, labels) >> MaxClassifier()


def _data(n=4096, dim=64, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, dim).astype(np.float32),
            rng.randint(0, classes, size=n).astype(np.int32))


TIGHT = 32 << 10  # busts every device cache at n=4096, dim=64

# The spill tier's economics live in the unified scorer's pinned-bytes
# model; on a MULTI-device mesh the PR-9 placement axis independently
# prices every vertex's full per-device bytes against the same budget
# (KP600), which walls off sub-dataset budgets before the cache/spill
# menu is even consulted. The spill demonstration therefore runs on a
# host-only (1-device) mesh — the regime the out-of-core tier targets.


def _applied(X, y, **cfg):
    with config_override(unified_min_savings_seconds=0.0, **cfg):
        applied = _predictor(Dataset.from_numpy(X),
                             Dataset.from_numpy(y))(Dataset.from_numpy(X))
        applied.executor.optimized_graph  # optimize under THIS config
        return applied


def _markers(applied):
    g = applied.executor.optimized_graph
    return [(v.id, g.get_operator(v).placement) for v in g.operators
            if isinstance(g.get_operator(v), CacheMarker)]


def test_spill_menu_prices_device_inf_against_host_feasible():
    """Under a budget every device cache busts, the solver's priced
    menu carries the INF device-cache entry AND the feasible spill
    entry for the same vertex — the pair IS the ledger's alternative
    set — and the chosen assignment spills."""
    X, y = _data()
    with use_mesh(make_mesh(jax.devices()[:1])):
        applied = _applied(X, y, hbm_budget_bytes=TIGHT)
        specs, _ = spec_pass(applied.executor.graph, {})
        plan = plan_unified(applied.executor.graph, specs,
                            hbm_budget_bytes=TIGHT,
                            include_boundary_policies=False,
                            allow_spill=True)
    entries = {c["entry"]: c for c in plan.scored_candidates}
    inf_caches = [e for e, c in entries.items()
                  if e.startswith("cache_") and not c["feasible"]]
    feasible_spills = [e for e, c in entries.items()
                       if e.startswith("spill_") and c["feasible"]]
    assert inf_caches, entries
    assert feasible_spills, entries
    assert plan.chosen.spills, "tight budget chose no spill"
    assert plan.chosen.spills <= plan.chosen.caches
    for vid in plan.chosen.spills:
        pred = plan.spill_predictions[vid]
        assert pred["bytes"] > 0 and pred["reload_seconds"] > 0, pred


def test_host_cache_marker_enforced_with_output_parity():
    """End-to-end under the tight budget: the optimized graph carries a
    HOST-placed CacheMarker, the run completes, outputs match the
    unconstrained arm (f32 summation-order noise only — the chunk
    decision differs), and the ledger carries the kind="spill" record
    with priced alternatives."""
    X, y = _data()
    with use_mesh(make_mesh(jax.devices()[:1])):
        PipelineEnv.reset()
        base = np.asarray(_applied(X, y).get().data)

        PipelineEnv.reset()
        mark = ledger.session_mark()
        applied = _applied(X, y, hbm_budget_bytes=TIGHT)
        assert any(p == "host" for _, p in _markers(applied)), \
            _markers(applied)
        out = np.asarray(applied.get().data)
    assert out.shape == base.shape
    assert np.mean(out != base) < 0.01  # argmax ties at the noise floor

    spills = [d for d in ledger.session_since(mark)
              if d["kind"] == "spill"]
    assert spills, "spill enforcement appended no ledger record"
    rec = spills[0]
    assert rec["chosen"]["placement"] == "host"
    assert rec["chosen"]["spills"][0]["reload_seconds"] > 0
    assert any(a["entry"].startswith("cache_") and not a["feasible"]
               for a in rec["alternatives"]), rec["alternatives"]
    assert any(a["entry"].startswith("spill_") and a["feasible"]
               for a in rec["alternatives"]), rec["alternatives"]


def test_kill_switch_reproduces_spill_free_plan_bit_for_bit():
    """The KEYSTONE_OOC_SPILL=0 arm scores NO spill entries, keeps an
    empty spill set, places no host cache, and — where no spill wins
    anyway — chooses the identical assignment as the on-arm, so the
    plan is bit-for-bit the PR-19 plan."""
    X, y = _data()
    with use_mesh(make_mesh(jax.devices()[:1])):
        applied = _applied(X, y, hbm_budget_bytes=TIGHT,
                           ooc_spill=False)
        assert not any(p == "host" for _, p in _markers(applied))

        specs, _ = spec_pass(applied.executor.graph, {})
        off = plan_unified(applied.executor.graph, specs,
                           hbm_budget_bytes=TIGHT,
                           include_boundary_policies=False,
                           allow_spill=False)
        assert off.chosen.spills == frozenset()
        assert not [c for c in off.scored_candidates
                    if c["entry"].startswith("spill_")]

        # generous budget: spill never wins, so both arms choose the
        # SAME assignment — the off-arm is inert, not merely similar
        on = plan_unified(applied.executor.graph, specs,
                          include_boundary_policies=False,
                          allow_spill=True)
        off2 = plan_unified(applied.executor.graph, specs,
                            include_boundary_policies=False,
                            allow_spill=False)
    assert on.chosen.spills == frozenset()
    assert on.chosen == off2.chosen
