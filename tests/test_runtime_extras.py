"""Tests for the runtime extras: auto-fusion rule, profiler, KRR
checkpoint/resume, VectorSplitter, native IO, annotators, stats."""

import numpy as np
import pytest

from keystone_tpu import Dataset, HostDataset, PipelineEnv, Transformer
from keystone_tpu.nodes.images.core import ImageVectorizer, PixelScaler, Pooler
from keystone_tpu.nodes.stats import LinearRectifier, PaddedFFT, RandomSignNode
from keystone_tpu.nodes.util import VectorSplitter
from keystone_tpu.nodes.util.fusion import FusedBatchTransformer
from keystone_tpu.utils.stats import about_eq, normalize_rows
from keystone_tpu.workflow.fusion_rule import NodeFusionRule


def test_node_fusion_rule_fuses_chain():
    p = (RandomSignNode(8).to_pipeline() >> PaddedFFT() >> LinearRectifier())
    from keystone_tpu.workflow.optimizer import DefaultOptimizer

    graph, _ = DefaultOptimizer().execute(p.graph)
    fused_nodes = [
        n for n in graph.nodes
        if isinstance(graph.get_operator(n), FusedBatchTransformer)
    ]
    assert len(fused_nodes) == 1
    assert len(graph.get_operator(fused_nodes[0]).stages) == 3


def test_fused_pipeline_output_matches_unfused():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(32, 8)).astype(np.float32)
    p = RandomSignNode(8).to_pipeline() >> PaddedFFT() >> LinearRectifier()
    fused_out = p(Dataset(X)).get().numpy()
    from keystone_tpu.workflow.optimizer import DefaultOptimizer

    PipelineEnv.reset()
    PipelineEnv.get().set_optimizer(DefaultOptimizer(fuse=False))
    unfused_out = p(Dataset(X)).get().numpy()
    np.testing.assert_allclose(fused_out, unfused_out, atol=1e-5)


def test_fusion_not_applied_across_branches():
    """A node with two consumers must not be absorbed into a chain."""
    from keystone_tpu.workflow import Pipeline

    shared = RandomSignNode(8)
    p = Pipeline.gather([
        shared.to_pipeline() >> LinearRectifier(),
        shared.to_pipeline() >> LinearRectifier(1.0),
    ])
    rng = np.random.default_rng(1)
    X = rng.normal(size=(16, 8)).astype(np.float32)
    out = p(Dataset(X)).get()
    assert out.count == 16  # executes correctly with branching


def test_profiler_records_forced_nodes():
    from keystone_tpu.utils.profiling import profile_execution

    ds = Dataset(np.ones((16, 4), np.float32))
    p = Transformer.from_function(lambda x: x * 2, name="double").to_pipeline()
    with profile_execution() as prof:
        p(ds).get()
    assert any("double" in label for label in prof.profiles)
    assert "seconds" in prof.report()


def test_krr_checkpoint_resume(tmp_path):
    from keystone_tpu.nodes.learning import KernelRidgeRegression

    rng = np.random.default_rng(2)
    X = rng.normal(size=(64, 3)).astype(np.float32)
    Y = rng.normal(size=(64, 2)).astype(np.float32)
    full = KernelRidgeRegression(1.0, 0.5, block_size=16, num_epochs=2).fit(
        Dataset(X), Dataset(Y)
    )
    # run with checkpointing every block; simulate crash by pre-seeding a
    # mid-run checkpoint, then confirm the final model matches
    ck = KernelRidgeRegression(
        1.0, 0.5, block_size=16, num_epochs=2,
        checkpoint_dir=str(tmp_path), blocks_before_checkpoint=1,
    )
    model = ck.fit(Dataset(X), Dataset(Y))
    np.testing.assert_allclose(
        np.asarray(model.alpha), np.asarray(full.alpha), atol=1e-5
    )
    # checkpoint removed after successful fit
    import os

    assert not any(f.endswith(".npz") for f in os.listdir(tmp_path))


def test_vector_splitter_blocks():
    X = np.arange(24, dtype=np.float32).reshape(4, 6)
    blocks = VectorSplitter(4).apply_batch(Dataset(X))
    assert [b.array.shape[1] for b in blocks] == [4, 2]
    np.testing.assert_allclose(blocks[1].numpy(), X[:, 4:])


def test_native_io_parity():
    from keystone_tpu.utils import native_io

    rng = np.random.default_rng(3)
    rec = rng.integers(0, 256, size=(20, 3073), dtype=np.uint8)
    imgs, labs = native_io.parse_cifar(rec)
    ref = rec[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).astype(np.float32)
    np.testing.assert_array_equal(imgs, ref)
    np.testing.assert_array_equal(labs, rec[:, 0])


def test_native_csv_parity(tmp_path):
    from keystone_tpu.utils import native_io

    X = np.random.default_rng(4).normal(size=(30, 5)).astype(np.float32)
    path = str(tmp_path / "x.csv")
    np.savetxt(path, X, delimiter=",", fmt="%.6f")
    np.testing.assert_allclose(native_io.parse_csv(path), X, atol=1e-5)


def test_annotators():
    from keystone_tpu.nodes.nlp import NER, CoreNLPFeatureExtractor, POSTagger

    pos = POSTagger().apply(["the", "cats", "ran", "quickly"])
    assert pos[0][1] == "DT" and pos[3][1] == "RB"
    ner = NER().apply(["Today", "Alice", "visited", "NASA"])
    assert ner[1][1] == "ENTITY" and ner[3][1] == "ENTITY"
    # note: sentence-initial TitleCase is deliberately demoted to O
    feats = CoreNLPFeatureExtractor([1]).apply("yesterday Alice was running")
    assert ("ENTITY",) in feats and ("run",) in feats


def test_stats_helpers():
    assert about_eq([1.0, 2.0], [1.0, 2.0 + 1e-10])
    assert not about_eq([1.0], [1.1])
    assert not about_eq(np.ones(3), np.array([1.0, 1.0, 2.0]))  # shape-safe
    N = normalize_rows(np.array([[3.0, 4.0]]))
    np.testing.assert_allclose(np.linalg.norm(N, axis=1), 1.0)
    # zero row: floored denominator, no nan
    Z = normalize_rows(np.array([[0.0, 0.0]]), floor=0.5)
    np.testing.assert_allclose(Z, [[0.0, 0.0]])


def test_native_csv_rejects_empty_fields(tmp_path):
    """',,' must error (fall back to loadtxt's ValueError), never shift
    values across rows (review regression)."""
    from keystone_tpu.utils import native_io

    path = str(tmp_path / "bad.csv")
    with open(path, "w") as f:
        f.write("1.0,2.0,3.0\n4.0,,6.0\n7.0,8.0,9.0\n")
    if native_io.available():
        with pytest.raises(Exception):
            native_io.parse_csv(path)


def test_csv_loader_preserves_float64(tmp_path):
    from keystone_tpu.loaders import csv_data_loader

    path = str(tmp_path / "wide.csv")
    with open(path, "w") as f:
        f.write("1.0000000123,2.0\n3.0,4.0\n")
    ds = csv_data_loader(path, dtype=np.float64)
    assert ds.numpy()[0, 0] == 1.0000000123


def test_krr_checkpoint_keyed_on_data(tmp_path):
    """A checkpoint from dataset A must not resume a fit on same-shape
    dataset B (review regression)."""
    from keystone_tpu.nodes.learning import KernelRidgeRegression

    rng = np.random.default_rng(9)
    A = rng.normal(size=(32, 3)).astype(np.float32)
    B = rng.normal(size=(32, 3)).astype(np.float32)
    Y = rng.normal(size=(32, 2)).astype(np.float32)
    est = KernelRidgeRegression(1.0, 0.5, block_size=32, num_epochs=1,
                                checkpoint_dir=str(tmp_path))
    pa = est._ckpt_path(Dataset(A), Dataset(Y))
    pb = est._ckpt_path(Dataset(B), Dataset(Y))
    assert pa != pb


def test_fused_program_shared_across_instances():
    """Two pipelines with the same structure but different parameter
    values must share ONE compiled program (params are traced arguments,
    not baked constants) — rebuilding a pipeline never recompiles."""
    from keystone_tpu.nodes.images.core import Convolver, SymmetricRectifier
    from keystone_tpu.nodes.util import fusion

    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 255, size=(16, 12, 12, 3)).astype(np.float32)

    def build(seed):
        filters = np.random.default_rng(seed).normal(size=(8, 6 * 6 * 3)).astype(np.float32)
        return FusedBatchTransformer(
            [
                PixelScaler(),
                Convolver(filters, 12, 12, 3, normalize_patches=True),
                SymmetricRectifier(alpha=0.1),
                Pooler(3, 4, pool_fn="sum"),
                ImageVectorizer(),
            ],
            microbatch=8,
        )

    fusion._PROGRAM_CACHE.clear()
    out1 = build(1).apply_batch(Dataset(imgs)).numpy()
    assert len(fusion._PROGRAM_CACHE) == 1
    out2 = build(2).apply_batch(Dataset(imgs)).numpy()
    assert len(fusion._PROGRAM_CACHE) == 1  # cache hit, no new program
    assert not np.allclose(out1, out2)  # different params flowed through
    out1_again = build(1).apply_batch(Dataset(imgs)).numpy()
    np.testing.assert_allclose(out1, out1_again, atol=1e-5)


def test_device_filter_learning_matches_host_reference():
    """learn_filters' on-device patch/moments path must reproduce the
    host-side extract_patches + ZCA math (reference driver-side filter
    learning, RandomPatchCifar.scala:45-57)."""
    import jax.numpy as jnp

    from keystone_tpu.pipelines.random_patch_cifar import (
        RandomPatchCifarConfig,
        learn_filters,
    )
    from keystone_tpu.utils.images import extract_patches

    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 255, size=(64, 16, 16, 3)).astype(np.float32)
    train = Dataset(imgs)
    config = RandomPatchCifarConfig(
        num_filters=8, patch_size=6, sample_patches=6400
    )
    filters, whitener = learn_filters(train, config)
    assert filters.shape == (8, 6 * 6 * 3)
    # whitener decorrelates: covariance of whitened sample ≈ scaled identity
    pats = extract_patches(np.asarray(train.array) / 255.0, 6, 1)
    pats = pats - pats.mean(axis=1, keepdims=True)
    pats = pats / np.maximum(np.linalg.norm(pats, axis=1, keepdims=True), 10 / 255)
    wh = (pats - whitener.means_np) @ whitener.whitener_np
    cov = np.cov(wh.T)
    off = cov - np.diag(np.diag(cov))
    assert np.abs(off).max() < 0.1 * np.abs(np.diag(cov)).max()


def test_spread_take_empty_dataset_returns_zero_rows():
    """spread_take on an empty Dataset must not fabricate examples from
    padding rows (it would silently mis-profile sparsity in
    LeastSquaresEstimator._measure)."""
    from keystone_tpu.data.dataset import Dataset

    ds = Dataset(np.zeros((0, 5), np.float32))
    assert ds.count == 0
    out = ds.spread_take(256)
    assert out.shape == (0, 5)


def test_spread_take_spreads_and_bounds():
    from keystone_tpu.data.dataset import Dataset

    X = np.arange(40, dtype=np.float32).reshape(10, 4)
    ds = Dataset(X)
    out = ds.spread_take(4)
    assert out.shape == (4, 4)
    # evenly spread: first and last valid rows included, never padding
    assert out[0, 0] == X[0, 0] and out[-1, 0] == X[-1, 0]
    full = ds.spread_take(100)  # m > count clamps to count
    np.testing.assert_allclose(full, X)


def test_execution_profiler_times_and_reports():
    """profile_execution wraps node expressions and attributes forced
    executions (SURVEY §5 profiling; AutoCacheRule.profileNodes analog)."""
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.utils.profiling import profile_execution
    from keystone_tpu.workflow.pipeline import Transformer

    class Add(Transformer):
        def __init__(self, v):
            self.v = v

        def apply(self, x):
            return x + self.v

    X = np.ones((8, 3), np.float32)
    with profile_execution() as prof:
        out = (Add(1.0) >> Add(2.0))(Dataset(X)).get()
    np.testing.assert_allclose(out.numpy(), X + 3.0)
    assert prof.profiles, "no nodes profiled"
    assert sum(p.forced for p in prof.profiles.values()) >= 2
    rep = prof.report()
    assert "seconds" in rep and "forced" in rep


# ---- OperatorSuite.scala:104-124, 247-283: invalid-input checks -----------


def test_transformer_operator_rejects_invalid_inputs():
    from keystone_tpu.workflow.expressions import (
        DatasetExpression,
        DatumExpression,
    )
    from keystone_tpu.workflow.operators import TransformerOperator

    class T(TransformerOperator):
        def single_transform(self, inputs):
            return 4

        def batch_transform(self, inputs):
            return [4]

    t = T()
    with pytest.raises(ValueError):
        t.execute([DatasetExpression.of([4]), DatumExpression.of(4)])  # mixed
    with pytest.raises(ValueError):
        t.execute([])  # empty


def test_delegating_operator_rejects_invalid_inputs():
    from keystone_tpu.workflow.expressions import (
        DatasetExpression,
        DatumExpression,
        TransformerExpression,
    )
    from keystone_tpu.workflow.operators import (
        DelegatingOperator,
        TransformerOperator,
    )

    class T(TransformerOperator):
        def single_transform(self, inputs):
            return 4

        def batch_transform(self, inputs):
            return [4]

    op = DelegatingOperator()
    texpr = TransformerExpression(lambda: T())
    with pytest.raises(ValueError):  # mixed data deps
        op.execute([texpr, DatasetExpression.of([4]), DatumExpression.of(4)])
    with pytest.raises(ValueError):  # empty
        op.execute([])
    with pytest.raises(ValueError):  # transformer only, no data
        op.execute([texpr])
    with pytest.raises(ValueError):  # first dep not a transformer
        op.execute([DatasetExpression.of([4]), DatasetExpression.of([4])])
