"""Sharding-aware plan optimizer tests (keystone_tpu/analysis/planner.py
+ workflow.optimizer.ShardingPlannerRule).

The acceptance contract: on a 2×4 ('data','model') mesh the planner
chooses row-sharded featurize and a model-parallel solve input and
strictly beats the default placement's priced boundary bytes (same cost
function on both sides); ``KEYSTONE_SHARDING_PLANNER=0`` reproduces the
PR-8 plan bit-for-bit; enforced plans keep outputs allclose-identical
to serial unfused execution at multiple AND ragged counts; KP600
budget-infeasible menu entries are pruned (a budget that excludes
replication forces a sharded choice); and the chosen plan survives
megafusion — the with_sharding_constraint is present in the compiled
program's jaxpr.
"""

import numpy as np
import pytest
import jax
from jax.sharding import PartitionSpec as P

from keystone_tpu.analysis import SpecDataset, plan_sharding
from keystone_tpu.analysis.examples import build_example
from keystone_tpu.analysis.planner import (
    FAMILY_DATA,
    FAMILY_DATA_MODEL,
    FAMILY_REPLICATED,
    family_of,
    realize_family,
)
from keystone_tpu.analysis.propagate import spec_pass
from keystone_tpu.analysis.sharding import sharding_pass
from keystone_tpu.analysis import as_source_spec
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
from keystone_tpu.nodes.stats import (
    CosineRandomFeatures,
    LinearRectifier,
    PaddedFFT,
    RandomSignNode,
)
from keystone_tpu.nodes.util import ClassLabelIndicatorsFromInt, MaxClassifier
from keystone_tpu.nodes.util.fusion import FusedBatchTransformer
from keystone_tpu.parallel import mesh as meshlib
from keystone_tpu.workflow import Pipeline, PipelineEnv, Transformer
from keystone_tpu.workflow.env import config_override
from keystone_tpu.workflow.fusion_rule import MegafusedPlanOperator
from keystone_tpu.workflow.graph import NodeId
from keystone_tpu.workflow.operators import DatasetOperator
from keystone_tpu.workflow.optimizer import DefaultOptimizer


def _mesh_2x4():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 local devices")
    return meshlib.make_mesh(
        devs[:8], shape=(2, 4),
        axis_names=(meshlib.DATA_AXIS, meshlib.MODEL_AXIS))


def _predictor(dim=64, classes=4):
    featurizer = (RandomSignNode(dim).to_pipeline() >> PaddedFFT()
                  >> LinearRectifier(0.0))

    def build(data, labels_ds):
        labels = ClassLabelIndicatorsFromInt(classes)(labels_ds)
        return featurizer.and_then(
            BlockLeastSquaresEstimator(32, num_iter=1, lam=1e-3),
            data, labels) >> MaxClassifier()

    return build


def _data(n, dim=64, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, dim).astype(np.float32),
            rng.randint(0, classes, size=n).astype(np.int32))


# ------------------------------------------------------------ the decision


def test_planner_beats_default_on_examples_2x4():
    """On the 2×4 mesh the planner strictly reduces priced boundary
    bytes vs the PR-8 default placement on at least 2 of the example
    pipelines (the lint.sh audit's acceptance gate, asserted in-tree),
    and never loses on any."""
    mesh = _mesh_2x4()
    strict = 0
    with meshlib.use_mesh(mesh):
        for name in ("MnistRandomFFT", "LinearPixels", "RandomPatchCifar",
                     "TimitPipeline"):
            pipeline, source_spec = build_example(name)
            specs, _ = spec_pass(
                pipeline.graph,
                {pipeline.source: as_source_spec(source_spec)})
            splan = plan_sharding(pipeline.graph, specs, mesh=mesh)
            assert splan is not None, name
            assert splan.planned_cost_bytes <= splan.default_cost_bytes
            if splan.improved:
                strict += 1
                # the decided placement stays lint-clean: zero KP6xx
                # under the chosen plan
                _, diags, _ = sharding_pass(
                    pipeline.graph, specs, mesh=mesh, plan=splan.choices)
                assert not [d for d in diags
                            if d.rule.startswith("KP6")], (name, diags)
    assert strict >= 2, f"strict wins on only {strict} example(s)"


def test_planner_row_sharded_featurize_model_parallel_solve():
    """Budget pressure on a 2×4 mesh: the featurize output feeding the
    solver fit is chosen 2-D data×model — row-sharded (the solver's
    `fit_sharding_demands` row demand holds) AND feature-sharded (the
    model-parallel solve layout) — because data-only and replicated
    placements bust the KP600 per-device budget, and the chosen plan
    beats the default's priced boundary bytes."""
    mesh = _mesh_2x4()
    n, d = 512, 4096
    with meshlib.use_mesh(mesh):
        features = CosineRandomFeatures(d, d, gamma=1.0).to_pipeline()
        data = SpecDataset((d,), np.float32, count=n, name="x")
        labels = SpecDataset((8,), np.float32, count=n, name="y")
        pipe = features.and_then(
            BlockLeastSquaresEstimator(512, num_iter=1), data, labels)
        applied = pipe.apply(data)
        specs, _ = spec_pass(applied.graph, {})
        # features are n×d f32 = 8 MiB; per-device: DATA → 4 MiB,
        # DATA_MODEL → 1 MiB, REPL → 8 MiB. A 2.5 MiB per-device budget
        # excludes everything but data×model.
        budget = int(2.5 * (1 << 20))
        splan = plan_sharding(applied.graph, specs, mesh=mesh,
                              hbm_budget_bytes=budget)
        assert splan is not None
        feat_vids = [
            vid for vid, fam in splan.families.items()
            if isinstance(vid, NodeId)
            and "CosineRandomFeatures" in applied.graph.get_operator(vid).label
        ]
        assert feat_vids
        for vid in feat_vids:
            assert splan.families[vid] == FAMILY_DATA_MODEL, splan.families
            # row-sharded: the leading entry is the data axis (the
            # solver demand); model-parallel: the feature axis rides
            # the model axis
            spec = splan.spec_for(vid)
            assert tuple(spec) == (meshlib.DATA_AXIS, meshlib.MODEL_AXIS)
        assert splan.planned_cost_bytes <= splan.default_cost_bytes

        # the budget is what constrains the menu: data-only and
        # replicated placements of the feature matrix are priced
        # infeasible at this budget (the KP600 pruning)
        from keystone_tpu.analysis.planner import _CostModel

        model = _CostModel(applied.graph, specs, mesh, budget,
                           replicated_threshold_bytes=64 << 20)
        for vid in feat_vids:
            assert model.node_cost(vid, FAMILY_DATA) == float("inf")
            assert model.node_cost(vid, FAMILY_REPLICATED) == float("inf")
            assert model.node_cost(vid, FAMILY_DATA_MODEL) < float("inf")


def test_kp600_infeasible_menu_entries_pruned():
    """A host consumer makes replication the cheap choice (no KP603
    gather, free transitions) — but a per-device budget that replication
    busts prunes it from the menu and forces a sharded choice."""
    mesh = _mesh_2x4()

    class _HostStage(Transformer):
        def apply(self, x):
            return np.asarray(x).sum()

    n, d = 1024, 1024  # 4 MiB total
    with meshlib.use_mesh(mesh):
        pipe = (RandomSignNode(d).to_pipeline()
                >> _HostStage())
        applied = pipe.apply(SpecDataset((d,), np.float32, count=n,
                                         name="x"))
        specs, _ = spec_pass(applied.graph, {})

        free = plan_sharding(applied.graph, specs, mesh=mesh)
        assert free is not None and free.improved
        sign_vid = [
            vid for vid in free.families
            if isinstance(vid, NodeId)
            and "RandomSignNode" in applied.graph.get_operator(vid).label
        ]
        assert sign_vid
        # unconstrained: replication avoids the host all-gather
        assert all(free.families[v] == FAMILY_REPLICATED for v in sign_vid)

        # a 1 MiB per-device budget excludes the 4 MiB replicated copy:
        # the planner must fall back to a sharded family and pay the
        # gather — the KP600-infeasible menu entry is pruned
        tight = plan_sharding(applied.graph, specs, mesh=mesh,
                              hbm_budget_bytes=1 << 20)
        assert tight is not None
        assert all(tight.families[v] != FAMILY_REPLICATED
                   for v in sign_vid), tight.families


def test_family_realization_and_classification_roundtrip():
    mesh = _mesh_2x4()
    spec = SpecDataset((64,), np.float32, count=16, name="x").spec
    for fam in (FAMILY_DATA, FAMILY_DATA_MODEL, FAMILY_REPLICATED):
        sv = realize_family(fam, spec, mesh)
        assert sv is not None
        assert family_of(sv, mesh) == fam
    # indivisible feature width: feature-axis families fall off the menu
    odd = SpecDataset((13,), np.float32, count=16, name="x").spec
    assert realize_family(FAMILY_DATA_MODEL, odd, mesh) is None
    assert realize_family(FAMILY_DATA, odd, mesh) is not None


# ------------------------------------------------------------ enforcement


def _optimized_graph(applied):
    return applied.executor.optimized_graph


def test_kill_switch_reproduces_pr8_plan_bit_for_bit():
    """KEYSTONE_SHARDING_PLANNER=0 (config channel) yields exactly the
    PR-8 plan: same vertices, same operator classes, same dependencies,
    no planner tags, and the plan-input datasets are the caller's own
    objects (no reshard copies)."""
    mesh = _mesh_2x4()
    X, y = _data(64)
    with meshlib.use_mesh(mesh):
        def optimize(optimizer=None):
            PipelineEnv.reset()
            if optimizer is not None:
                PipelineEnv.get().set_optimizer(optimizer)
            data = Dataset.from_numpy(X)
            labels = Dataset.from_numpy(y)
            applied = _predictor()(data, labels)(data)
            return data, _optimized_graph(applied)

        with config_override(sharding_planner=False):
            data_off, g_off = optimize()
        # the pre-planner optimizer construction must agree with the
        # kill switch exactly
        with config_override(sharding_planner=True):
            data_ctor, g_ctor = optimize(
                DefaultOptimizer(sharding_planner=False))
        PipelineEnv.reset()

        def shape(g, data):
            out = []
            for vid in sorted(g.operators, key=lambda v: v.id):
                op = g.get_operator(vid)
                out.append((vid.id, type(op).__name__,
                            tuple(d.id if hasattr(d, "id") else d
                                  for d in g.get_dependencies(vid)),
                            getattr(op, "planned_out_spec", None)))
            return out

        off_shape = shape(g_off, data_off)
        assert off_shape == shape(g_ctor, data_ctor)
        assert all(t[3] is None for t in off_shape)
        # plan-input datasets are the original objects, not reshards
        for g, data in ((g_off, data_off), (g_ctor, data_ctor)):
            ds_ops = [g.get_operator(v) for v in g.operators
                      if isinstance(g.get_operator(v), DatasetOperator)]
            assert any(op.dataset is data for op in ds_ops)


def test_planner_enforces_and_outputs_match_serial_unfused():
    """Planner-on outputs are allclose-identical to serial unfused
    execution at a shard-multiple count AND a ragged count, and the
    enforcement actually happened (a planner tag or a reseeded plan
    input is present in the optimized graph)."""
    mesh = _mesh_2x4()
    build = _predictor()
    for n in (64, 43):  # multiple of 8, and ragged
        X, y = _data(n)
        with meshlib.use_mesh(mesh):
            def run(optimizer, planner_on):
                PipelineEnv.reset()
                if optimizer is not None:
                    PipelineEnv.get().set_optimizer(optimizer)
                with config_override(sharding_planner=planner_on):
                    data = Dataset.from_numpy(X)
                    labels = Dataset.from_numpy(y)
                    applied = build(data, labels)(data)
                    out = np.asarray(applied.get().numpy())
                    graph = _optimized_graph(applied)
                PipelineEnv.reset()
                return out, graph

            planned, g_planned = run(None, True)
            serial, _ = run(DefaultOptimizer(fuse=False,
                                             sharding_planner=False),
                            False)
            np.testing.assert_allclose(planned, serial, rtol=1e-5,
                                       atol=1e-5)
            tagged = [
                op for op in (g_planned.get_operator(v)
                              for v in g_planned.operators)
                if getattr(op, "planned_out_spec", None) is not None
            ]
            reseeded = [
                op for op in (g_planned.get_operator(v)
                              for v in g_planned.operators)
                if isinstance(op, DatasetOperator)
                and meshlib.spec_of_array(
                    jax.tree_util.tree_leaves(op.dataset.data)[0]
                    if hasattr(op.dataset, "data") else None) == P()
            ]
            assert tagged or reseeded, (
                "planner found a win on the 2x4 mesh but enforced "
                "nothing")


def test_chosen_plan_survives_megafusion_constraint_in_jaxpr():
    """A megafused program built under a planner tag carries the
    with_sharding_constraint in its jaxpr — the chosen placement is part
    of the ONE compiled program, not a separate dispatch."""
    mesh = _mesh_2x4()
    n, dim = 64, 64
    with meshlib.use_mesh(mesh):
        # materialize() propagates the tag from the plan operator to the
        # runnable megafused transformer
        plan_op = MegafusedPlanOperator([RandomSignNode(dim),
                                         LinearRectifier(0.0)])
        plan_op.planned_out_spec = P(meshlib.DATA_AXIS, meshlib.MODEL_AXIS)
        mat = plan_op.materialize([])
        assert getattr(mat, "planned_out_spec", None) == plan_op.planned_out_spec

        statics, flat, treedef, fns = mat._decompose()
        program = mat._build_program(mesh, 2, n, treedef, fns)
        ds = Dataset.from_numpy(np.ones((n, dim), np.float32), mesh=mesh)
        jaxpr = jax.make_jaxpr(program)(flat, ds.array, ds.mask)
        assert "sharding_constraint" in str(jaxpr)

        # untagged form compiles WITHOUT the constraint (and under a
        # different program cache key)
        bare = MegafusedPlanOperator([RandomSignNode(dim),
                                      LinearRectifier(0.0)]).materialize([])
        bare_program = bare._build_program(mesh, 2, n, treedef, fns)
        assert "sharding_constraint" not in str(
            jax.make_jaxpr(bare_program)(flat, ds.array, ds.mask))
        key_tagged = mat._program_key(statics, flat, treedef,
                                      (n, dim), "float32", n, 2, mesh)
        key_bare = bare._program_key(statics, flat, treedef,
                                     (n, dim), "float32", n, 2, mesh)
        assert key_tagged != key_bare

        # the constrained program's output actually lands in the
        # planned layout, values unchanged
        out = program(flat, ds.array, ds.mask)
        ref = bare_program(flat, ds.array, ds.mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref))
        assert meshlib.spec_of_array(out) is not None
        assert set(meshlib.spec_axes(meshlib.spec_of_array(out))) == {
            meshlib.DATA_AXIS, meshlib.MODEL_AXIS}


def test_host_dataset_stack_with_planned_spec():
    """The host→device seam takes a planned placement directly:
    `HostDataset.stack(spec=...)` lands the stacked value in the chosen
    layout (one placement, values identical to the default seam)."""
    from keystone_tpu.data.dataset import HostDataset

    mesh = _mesh_2x4()
    items = [np.full((8,), i, np.float32) for i in range(6)]
    with meshlib.use_mesh(mesh):
        planned = HostDataset(items).stack(
            spec=P(meshlib.DATA_AXIS, meshlib.MODEL_AXIS))
        leaf = jax.tree_util.tree_leaves(planned.data)[0]
        assert tuple(meshlib.spec_of_array(leaf)) == (
            meshlib.DATA_AXIS, meshlib.MODEL_AXIS)
        default = HostDataset(items).stack()
        np.testing.assert_array_equal(
            np.asarray(planned.numpy()), np.asarray(default.numpy()))


def test_planner_noop_on_single_device_mesh():
    devs = jax.devices()
    mesh1 = meshlib.make_mesh(devs[:1])
    with meshlib.use_mesh(mesh1):
        pipe = RandomSignNode(16).to_pipeline() >> LinearRectifier(0.0)
        applied = pipe.apply(SpecDataset((16,), np.float32, count=8,
                                         name="x"))
        specs, _ = spec_pass(applied.graph, {})
        assert plan_sharding(applied.graph, specs,
                             mesh=meshlib.current_mesh()) is None
