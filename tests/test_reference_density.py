"""Hand-computed fixtures and edge cases mirroring the reference's
per-node suites (SURVEY §4 categories 6/9): PaddedFFTSuite,
RandomSignNodeSuite, LinearRectifierSuite, SignedHellingerMapperSuite,
CosineRandomFeaturesSuite, ClassLabelIndicatorsSuite, TopKClassifierSuite,
MulticlassClassifierEvaluatorSuite (hand confusion), BinaryClassifierEvaluatorSuite,
MeanAveragePrecisionSuite (hand 11-point fixture), StandardScalerSuite.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.data.dataset import Dataset


# ------------------------------------------------------------- stats nodes


def test_padded_fft_matches_numpy_golden():
    """PaddedFFTSuite: pad 5 → 8, real positive half, vs numpy."""
    from keystone_tpu.nodes.stats.random_features import PaddedFFT

    x = np.array([1.0, 2.0, -1.0, 0.5, 3.0], np.float32)
    got = np.asarray(PaddedFFT().apply(jnp.asarray(x)))
    want = np.fft.rfft(x, n=8).real[:4]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    assert got.shape == (4,)


def test_padded_fft_pow2_input_not_padded():
    from keystone_tpu.nodes.stats.random_features import PaddedFFT

    x = np.arange(8, dtype=np.float32)
    got = np.asarray(PaddedFFT().apply(jnp.asarray(x)))
    want = np.fft.rfft(x, n=8).real[:4]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_random_sign_node_signs_and_determinism():
    from keystone_tpu.nodes.stats.random_features import RandomSignNode

    n1 = RandomSignNode(64, seed=3)
    n2 = RandomSignNode(64, seed=3)
    s = np.asarray(n1.signs)
    assert set(np.unique(s)) <= {-1.0, 1.0}
    np.testing.assert_array_equal(s, np.asarray(n2.signs))
    x = np.ones(64, np.float32)
    np.testing.assert_array_equal(np.asarray(n1.apply(jnp.asarray(x))), s)


def test_linear_rectifier_golden():
    from keystone_tpu.nodes.stats.random_features import LinearRectifier

    x = jnp.asarray(np.array([-2.0, 0.0, 0.3, 5.0], np.float32))
    got = np.asarray(LinearRectifier(max_val=0.1, alpha=0.2).apply(x))
    np.testing.assert_allclose(got, [0.1, 0.1, 0.1, 4.8], rtol=1e-6)


def test_signed_hellinger_golden():
    from keystone_tpu.nodes.stats.normalization import SignedHellingerMapper

    x = jnp.asarray(np.array([-4.0, 0.0, 9.0, -0.25], np.float32))
    got = np.asarray(SignedHellingerMapper().apply(x))
    np.testing.assert_allclose(got, [-2.0, 0.0, 3.0, -0.5], rtol=1e-6)


def test_normalize_rows_unit_norm_and_zero_row():
    from keystone_tpu.nodes.stats.normalization import NormalizeRows

    node = NormalizeRows()
    v = np.array([3.0, 4.0], np.float32)
    got = np.asarray(node.apply(jnp.asarray(v)))
    np.testing.assert_allclose(got, [0.6, 0.8], rtol=1e-6)
    # zero vector: eps floor prevents nan
    z = np.asarray(node.apply(jnp.zeros(4)))
    assert np.all(np.isfinite(z)) and np.all(z == 0.0)


def test_cosine_random_features_definition_and_range():
    from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures

    node = CosineRandomFeatures(8, 32, gamma=0.5, seed=1)
    x = np.random.default_rng(0).normal(size=(5, 8)).astype(np.float32)
    got = np.asarray(node.apply_batch(Dataset(x)).numpy())
    want = np.cos(x @ np.asarray(node.W) + np.asarray(node.b))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    assert np.all(got >= -1.0 - 1e-6) and np.all(got <= 1.0 + 1e-6)


def test_cosine_random_features_rejects_unknown_distribution():
    from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures

    with pytest.raises(ValueError):
        CosineRandomFeatures(4, 4, distribution="levy")


def test_standard_scaler_zero_variance_column():
    """A constant column must not produce nan/inf after scaling."""
    from keystone_tpu.nodes.stats.scalers import StandardScaler

    X = np.random.default_rng(0).normal(size=(32, 4)).astype(np.float32)
    X[:, 2] = 7.0
    model = StandardScaler().fit(Dataset(X))
    out = np.asarray(model.apply_batch(Dataset(X)).numpy())
    assert np.all(np.isfinite(out))
    np.testing.assert_allclose(out[:, 2], 0.0, atol=1e-5)
    np.testing.assert_allclose(out[:, 0].std(), 1.0, atol=0.05)


# ------------------------------------------------------------- util nodes


def test_class_label_indicators_golden_and_validation():
    from keystone_tpu.nodes.util.basic import ClassLabelIndicatorsFromInt

    node = ClassLabelIndicatorsFromInt(3)
    np.testing.assert_allclose(
        np.asarray(node.apply(jnp.asarray(1))), [-1.0, 1.0, -1.0]
    )
    with pytest.raises(ValueError):
        ClassLabelIndicatorsFromInt(1)


def test_class_label_indicators_from_int_array_multilabel():
    from keystone_tpu.nodes.util.basic import ClassLabelIndicatorsFromIntArray

    node = ClassLabelIndicatorsFromIntArray(4)
    ys = jnp.asarray(np.array([0, 2, -1], np.int32))  # -1 = padding
    np.testing.assert_allclose(
        np.asarray(node.apply(ys)), [1.0, -1.0, 1.0, -1.0]
    )


def test_topk_classifier_ordering():
    from keystone_tpu.nodes.util.basic import TopKClassifier

    x = jnp.asarray(np.array([0.1, 0.9, 0.5, 0.7], np.float32))
    got = np.asarray(TopKClassifier(3).apply(x))
    np.testing.assert_array_equal(got, [1, 3, 2])


def test_vector_combiner_concatenates_gather_tuple():
    from keystone_tpu.nodes.util.basic import VectorCombiner

    a = np.ones((4, 2), np.float32)
    b = 2 * np.ones((4, 3), np.float32)
    ds = Dataset(a).with_data((jnp.asarray(a), jnp.asarray(b)))
    got = VectorCombiner().apply_batch(ds).numpy()
    assert got.shape == (4, 5)
    np.testing.assert_allclose(got[:, 2:], 2.0)


def test_densify_sparsify_roundtrip():
    import scipy.sparse as sp

    from keystone_tpu.data.sparse import SparseDataset
    from keystone_tpu.nodes.util.basic import Densify, Sparsify

    X = np.zeros((6, 8), np.float32)
    X[0, 1] = 3.0
    X[5, 7] = -2.0
    sd = SparseDataset(sp.csr_matrix(X))
    dense = Densify().apply_batch(sd)
    np.testing.assert_allclose(np.asarray(dense.numpy()), X)
    back = Sparsify().apply_batch(dense)
    np.testing.assert_allclose(back.matrix.toarray(), X)


def test_shuffler_preserves_multiset():
    from keystone_tpu.nodes.util.basic import Shuffler

    X = np.arange(40, dtype=np.float32).reshape(10, 4)
    out = Shuffler(seed=1).apply_batch(Dataset(X)).numpy()
    assert out.shape == X.shape
    np.testing.assert_allclose(
        np.sort(out.ravel()), np.sort(X.ravel())
    )


# ------------------------------------------------------------- evaluators


def test_multiclass_hand_computed_confusion():
    """Reference MulticlassClassifierEvaluatorSuite style: 3-class fixture
    with a fully hand-checked confusion matrix and macro metrics."""
    from keystone_tpu.evaluation import MulticlassClassifierEvaluator

    actual = [0, 0, 0, 1, 1, 2, 2, 2, 2, 2]
    pred = [0, 0, 1, 1, 2, 2, 2, 2, 0, 1]
    m = MulticlassClassifierEvaluator(3)(pred, actual)
    want = np.array([[2, 1, 0], [0, 1, 1], [1, 1, 3]], np.float64)
    np.testing.assert_allclose(m.confusion, want)
    assert abs(m.accuracy - 0.6) < 1e-9
    # per-class precision: c0: 2/3, c1: 1/3, c2: 3/4
    assert abs(m.class_precision(0) - 2 / 3) < 1e-9
    assert abs(m.class_precision(1) - 1 / 3) < 1e-9
    assert abs(m.class_precision(2) - 3 / 4) < 1e-9
    # per-class recall: c0: 2/3, c1: 1/2, c2: 3/5
    assert abs(m.class_recall(1) - 1 / 2) < 1e-9
    assert abs(m.macro_recall - (2 / 3 + 1 / 2 + 3 / 5) / 3) < 1e-9


def test_binary_all_four_cells():
    from keystone_tpu.evaluation import BinaryClassifierEvaluator

    #            TP TP FP FN TN FN
    pred = [True, True, True, False, False, False]
    act = [True, True, False, True, False, True]
    m = BinaryClassifierEvaluator()(pred, act)
    assert m.tp == 2 and m.fp == 1 and m.fn == 2 and m.tn == 1
    assert abs(m.precision - 2 / 3) < 1e-9
    assert abs(m.recall - 1 / 2) < 1e-9
    assert abs(m.specificity - 1 / 2) < 1e-9
    assert abs(m.f1 - 2 * (2 / 3) * (1 / 2) / (2 / 3 + 1 / 2)) < 1e-9


def test_map_11_point_hand_fixture():
    """One class, 4 examples, scores ranking = [pos, neg, pos, neg]:
    precision@recall: r=0.5 → max p = 1.0, r=1.0 → max p = 2/3.
    11-point AP = (6 × 1.0 + 5 × 2/3) / 11."""
    from keystone_tpu.evaluation import MeanAveragePrecisionEvaluator

    scores = np.array([[0.9], [0.8], [0.7], [0.1]], np.float32)
    actuals = [[0], [], [0], []]
    ap = MeanAveragePrecisionEvaluator(1)(scores, actuals)
    want = (6 * 1.0 + 5 * (2 / 3)) / 11.0
    assert abs(ap[0] - want) < 1e-9


def test_map_class_with_no_positives_scores_zero():
    from keystone_tpu.evaluation import MeanAveragePrecisionEvaluator

    scores = np.array([[0.9, 0.1], [0.2, 0.8]], np.float32)
    actuals = [[0], [0]]
    aps = MeanAveragePrecisionEvaluator(2)(scores, actuals)
    assert aps[1] == 0.0 and aps[0] > 0.99


# ------------------------------------------------------------ image utils
# (reference ImageUtilsSuite / ImageSuite)


def test_depthwise_conv2d_matches_scipy_separable():
    from scipy.ndimage import convolve1d

    from keystone_tpu.utils.images import depthwise_conv2d

    rng = np.random.default_rng(0)
    img = rng.random(size=(12, 10, 3)).astype(np.float32)
    ky = np.array([0.1, 0.3, 0.6], np.float32)  # asymmetric: pins the
    kx = np.array([0.7, 0.2, 0.1], np.float32)  # correlation orientation
    got = np.asarray(depthwise_conv2d(img, ky, kx))
    want = np.empty_like(img)
    for c in range(3):
        # lax conv is correlation; scipy convolve1d flips, so pre-flip
        t = convolve1d(img[:, :, c], ky[::-1], axis=0, mode="constant")
        want[:, :, c] = convolve1d(t, kx[::-1], axis=1, mode="constant")
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_extract_patches_values_and_count():
    from keystone_tpu.utils.images import extract_patches

    img = np.arange(2 * 5 * 5 * 1, dtype=np.float32).reshape(2, 5, 5, 1)
    pats = extract_patches(img, 3, 2)  # positions (0,0),(0,2),(2,0),(2,2)
    assert pats.shape == (2 * 4, 9)
    np.testing.assert_allclose(pats[0], img[0, 0:3, 0:3, 0].ravel())
    np.testing.assert_allclose(pats[3], img[0, 2:5, 2:5, 0].ravel())


def test_flip_horizontal_and_grayscale_golden():
    from keystone_tpu.utils.images import flip_horizontal, grayscale

    img = np.zeros((2, 3, 3), np.float32)
    img[0, 0] = [1.0, 0.0, 0.0]
    flipped = np.asarray(flip_horizontal(img))
    np.testing.assert_allclose(flipped[0, 2], [1.0, 0.0, 0.0])
    g = np.asarray(grayscale(img))
    assert abs(float(g[0, 0, 0]) - 0.299) < 1e-6


# ------------------------------------------------------------------- nlp
# (reference NGramSuite / StringUtilsSuite)


def test_ngram_equality_and_hash_semantics():
    from keystone_tpu.nodes.nlp.text import NGram

    a, b = NGram(["the", "cat"]), NGram(["the", "cat"])
    c = NGram(["the", "dog"])
    assert a == b and hash(a) == hash(b)
    assert a != c and a != ("the", "cat")
    assert repr(a) == "[the,cat]"
    assert len({a, b, c}) == 2


def test_ngrams_featurizer_orders_and_counts_modes():
    from keystone_tpu.data.dataset import HostDataset
    from keystone_tpu.nodes.nlp.text import NGramsCounts, NGramsFeaturizer

    feats = NGramsFeaturizer([1, 2]).apply(["a", "b", "a"])
    assert ("a",) in feats and ("a", "b") in feats and ("b", "a") in feats
    assert len(feats) == 3 + 2

    ds = HostDataset([feats, NGramsFeaturizer([1, 2]).apply(["a", "c"])])
    merged = NGramsCounts("default").apply_batch(ds)
    (pairs,) = merged.items  # single global sorted (ngram, count) list
    counts = dict(pairs)
    assert counts[("a",)] == 3  # 2 from first doc + 1 from second
    cs = [c for _, c in pairs]
    assert cs == sorted(cs, reverse=True)  # descending sort by count
    with pytest.raises(ValueError):
        NGramsCounts("bogus")


def test_tokenizer_trim_lowercase_chain():
    from keystone_tpu.nodes.nlp.text import LowerCase, Tokenizer, Trim

    s = "  The QUICK brown-fox  "
    out = Tokenizer().apply(LowerCase().apply(Trim().apply(s)))
    assert out[0] == "the" and "quick" in out


def test_corenlp_extractor_with_trained_ner_replaces_entities():
    from keystone_tpu.nodes.nlp.annotators import NER, CoreNLPFeatureExtractor

    ex = CoreNLPFeatureExtractor(orders=(1,), ner=NER.trained())
    grams = ex.apply("John visited Paris yesterday")
    toks = [g[0] for g in grams]
    # entity tokens are replaced by their NE tag, others lemmatized+lowered
    assert "visit" in toks or "visited" in toks
    assert any(t.isupper() for t in toks), toks  # some NE tag survived


# ------------------------------------------------------------ host dataset


def test_host_dataset_map_count_and_cache():
    from keystone_tpu.data.dataset import HostDataset

    hd = HostDataset(["ab", "c", "def"])
    assert hd.count == 3
    lens = hd.map(len)
    assert lens.items == [2, 1, 3]
    assert hd.cache() is hd


def test_sampler_device_gather_matches_host_choice():
    from keystone_tpu.nodes.stats.normalization import Sampler

    X = np.arange(80, dtype=np.float32).reshape(20, 4)
    out = Sampler(6, seed=3).apply_batch(Dataset(X))
    assert out.count == 6
    idx = np.random.default_rng(3).choice(20, 6, replace=False)
    idx.sort()
    np.testing.assert_allclose(out.numpy(), X[idx])
    # n <= size: pass-through
    same = Sampler(50, seed=3).apply_batch(Dataset(X))
    assert same.count == 20


# ------------------------------------------------------------- utils.stats
# (reference StatsSuite / MatrixUtilsSuite)


def test_rows_matrix_roundtrip():
    from keystone_tpu.utils.stats import matrix_to_rows, rows_to_matrix

    M = np.arange(12, dtype=np.float32).reshape(3, 4)
    rows = matrix_to_rows(M)
    assert len(rows) == 3
    np.testing.assert_allclose(rows_to_matrix(rows), M)
