"""Compile-bounded execution correctness suite (ISSUE 5).

Covers the shape-stable chunk dispatch + AOT warmup + persistent
compilation cache + compile accounting contract:

  - padded-tail exactness at non-multiple counts (43 items, chunk 16)
    for plain host batching, fused device chains, and streamed stages —
    outputs identical to the unpadded path, no phantom rows anywhere;
  - the chunk-contract bugfix: `map_host_batched_stream`'s indices cover
    exactly ``range(len(items))`` on BOTH the serial fallback and the
    overlapped path, at ragged counts;
  - compiles-per-run: padding bounds a bucket's programs at one per
    shape (the ragged tail stops compiling its own), and a second
    identical example-pipeline run in-process performs 0 cold compiles;
  - AOT warmup: identical outputs to the cold path, no cold compile at
    force time, `ExecutionConfig.chunk_size` honored end to end.
"""

import numpy as np
import pytest

from keystone_tpu import Dataset, HostDataset, PipelineEnv, Transformer
from keystone_tpu.telemetry import counter
from keystone_tpu.utils import batching
from keystone_tpu.workflow.env import (
    config_override,
    dispatch_override,
    execution_config,
    overlap_override,
)

RAGGED_N, CHUNK = 43, 16


def _items(n=RAGGED_N, dim=6, seed=0):
    rng = np.random.default_rng(seed)
    return [np.abs(rng.normal(size=(dim,)).astype(np.float32)) + 1.0
            for _ in range(n)]


# --------------------------------------------------------------------------
# padded-tail exactness + the chunk contract


@pytest.mark.parametrize("overlap", [False, True])
def test_padded_tail_exact_plain(overlap):
    """map_host_batched at 43 items / chunk 16: the padded path's output
    equals the unpadded path's, element for element."""
    items = _items()
    fn = lambda xb: np.asarray(xb) * 3.0 - 1.0  # noqa: E731

    with overlap_override(overlap), config_override(pad_chunks=True):
        padded = batching.map_host_batched(items, fn, chunk=CHUNK)
    with overlap_override(overlap), config_override(pad_chunks=False):
        ragged = batching.map_host_batched(items, fn, chunk=CHUNK)
    assert len(padded) == len(ragged) == RAGGED_N
    for i in range(RAGGED_N):
        np.testing.assert_allclose(padded[i], items[i] * 3.0 - 1.0,
                                   rtol=1e-6)
        np.testing.assert_allclose(padded[i], ragged[i], rtol=1e-6)


@pytest.mark.parametrize("overlap", [False, True])
def test_stream_indices_cover_exactly_range_n(overlap):
    """Bugfix regression: the serial fallback and `_stream_overlapped`
    agree on the padded chunk contract — indices yielded by
    `map_host_batched_stream` are exactly range(len(items)) with no
    padded phantoms, and every payload length matches its index list."""
    items = _items()
    seen = []
    with overlap_override(overlap, prefetch_depth=1), \
            config_override(pad_chunks=True):
        for idxs, payload in batching.map_host_batched_stream(
                items, lambda xb: np.asarray(xb) * 2.0, chunk=CHUNK):
            assert idxs is not None
            assert len(idxs) == len(payload)
            # a padded chunk must never surface rows beyond its real part
            assert len(payload) <= CHUNK
            seen.extend(idxs)
    assert sorted(seen) == list(range(RAGGED_N))
    assert len(seen) == RAGGED_N  # no duplicates either


class _ChunkProducer16(Transformer):
    """Bucketed host-batch stage streaming 16-row chunks (the
    SIFT/grid-descriptor pattern) — the streamed-stage fixture."""

    chunkable = True

    def apply(self, x):
        return np.asarray(x, np.float32) * 2.0

    def apply_batch_stream(self, data):
        return batching.map_host_batched_stream(
            data.items, lambda xb: np.asarray(xb) * 2.0, chunk=CHUNK)


def test_padded_tail_exact_streamed_consumer():
    """A streaming consumer at a ragged count: chunks flow through a
    fused elementwise chain, the union of streamed indices is exactly
    range(43), and values match the fully serial unpadded reference."""
    from keystone_tpu.nodes.stats import NormalizeRows, SignedHellingerMapper
    from keystone_tpu.workflow.optimizer import DefaultOptimizer

    items = _items()
    pipe = (_ChunkProducer16().to_pipeline()
            >> NormalizeRows() >> SignedHellingerMapper())

    with overlap_override(False), config_override(pad_chunks=False):
        PipelineEnv.get().set_optimizer(DefaultOptimizer(fuse=False))
        reference = pipe(HostDataset(items)).get()
    PipelineEnv.reset()

    with overlap_override(True, prefetch_depth=1), \
            config_override(pad_chunks=True):
        res = pipe(HostDataset(items))
        seen = {}
        for idxs, payload in res.stream():
            assert idxs is not None, "stream materialized"
            for i, item in zip(idxs, payload):
                assert i not in seen, f"index {i} streamed twice"
                seen[i] = item
    PipelineEnv.reset()
    assert sorted(seen) == list(range(RAGGED_N))
    for i in range(RAGGED_N):
        np.testing.assert_allclose(
            np.asarray(reference.items[i]), np.asarray(seen[i]), rtol=1e-5)


def test_padded_tail_exact_fused_device_chain():
    """A fused device chain at count 43 (non-multiple of the 8-device
    mesh): identical to the unfused, unpadded serial path."""
    from keystone_tpu.nodes.learning import LinearMapEstimator
    from keystone_tpu.nodes.stats import NormalizeRows, StandardScaler
    from keystone_tpu.nodes.util import ClassLabelIndicatorsFromInt
    from keystone_tpu.workflow.optimizer import DefaultOptimizer

    rng = np.random.default_rng(5)
    X = np.abs(rng.normal(size=(RAGGED_N, 6))).astype(np.float32) + 1.0
    y = rng.integers(0, 3, RAGGED_N).astype(np.int32)

    def run(fuse, warm):
        PipelineEnv.reset()
        PipelineEnv.get().set_optimizer(DefaultOptimizer(fuse=fuse))
        with config_override(aot_warmup=warm):
            train = Dataset.from_numpy(X)
            labels = ClassLabelIndicatorsFromInt(3)(
                Dataset.from_numpy(y)).get()
            pipe = (NormalizeRows().to_pipeline()
                    .and_then(StandardScaler(), train)
                    .and_then(LinearMapEstimator(0.1), train, labels))
            out = pipe(train).get().numpy()
        PipelineEnv.reset()
        return out

    with overlap_override(False), dispatch_override(False):
        reference = run(fuse=False, warm=False)
    np.testing.assert_allclose(run(fuse=True, warm=False), reference,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(run(fuse=True, warm=True), reference,
                               rtol=1e-5, atol=1e-6)


# --------------------------------------------------------------------------
# compile accounting


def test_padding_bounds_programs_compiled():
    """43 same-shape items at chunk 16: with shape-stable dispatch the
    whole stage compiles ONE program; with it off the ragged tail
    compiles its own second program."""
    import jax

    items = _items()

    def compiles_for(pad):
        fn = jax.jit(lambda xb: xb * 2.0 + 1.0)  # fresh fn: cold by
        # construction, so the delta below measures THIS stage only
        cold = counter("dispatch.programs_compiled")
        with config_override(pad_chunks=pad, compile_cache_dir=None):
            before = cold.value
            out = batching.map_host_batched(items, fn, chunk=CHUNK)
        for i in range(RAGGED_N):
            np.testing.assert_allclose(
                np.asarray(out[i]), items[i] * 2.0 + 1.0, rtol=1e-6)
        return int(cold.value - before)

    assert compiles_for(True) == 1
    assert compiles_for(False) == 2


def test_multi_chunk_bucket_tail_pads_to_full_chunk():
    """Review regression: a ragged tail of a bucket that fills whole
    chunks must pad to the CHUNK size, not its own power-of-two (40
    items at chunk 16 → parts [16, 16, 8]; the 8-tail must dispatch at
    16 or the bucket still compiles two programs)."""
    items = _items(n=40)
    shapes = []

    def fn(xb):
        shapes.append(xb.shape[0])
        return np.asarray(xb) * 2.0

    with config_override(pad_chunks=True):
        out = batching.map_host_batched(items, fn, chunk=CHUNK)
    assert set(shapes) == {CHUNK}, shapes
    assert len(shapes) == 3
    for i in range(40):
        np.testing.assert_allclose(out[i], items[i] * 2.0, rtol=1e-6)

    # a bucket SMALLER than a chunk still takes the pow-2 ladder
    shapes.clear()
    with config_override(pad_chunks=True):
        batching.map_host_batched(_items(n=5), fn, chunk=CHUNK)
    assert shapes == [8], shapes


def test_second_run_performs_zero_cold_compiles():
    """The acceptance gate: an example pipeline rebuilt and re-run in
    the same process against a fresh persistent-cache dir performs 0
    cold compiles on the second run and beats the cold wall clock, with
    identical outputs (compile_bench is the bench-tier twin)."""
    from keystone_tpu.compile_bench import measure_example_compiles

    rep = measure_example_compiles("TimitPipeline")
    assert rep["warm_programs_compiled"] == 0, rep
    assert rep["warm_beats_cold"], rep
    assert rep["apply_compiles_le_plan_programs"], rep
    assert rep["outputs_match_cold"]


def test_ragged_example_counts_stay_identical_and_warm():
    """The same gate at a NON-multiple example count (the padded-row
    machinery live in the measured run)."""
    from keystone_tpu.compile_bench import measure_example_compiles

    rep = measure_example_compiles("TimitPipeline", ragged_test=True)
    assert rep["warm_programs_compiled"] == 0, rep
    assert rep["outputs_match_cold"]


# --------------------------------------------------------------------------
# AOT warmup


def test_warmup_identical_outputs_and_no_force_time_compile():
    """`FusedBatchTransformer.warmup` from a static spec: the warmed
    apply performs zero cold compiles and produces exactly the cold
    path's values."""
    import jax

    from keystone_tpu.nodes.stats import NormalizeRows, SignedHellingerMapper
    from keystone_tpu.nodes.util.fusion import FusedBatchTransformer

    rng = np.random.default_rng(11)
    X = np.abs(rng.normal(size=(RAGGED_N, 6)).astype(np.float32)) + 1.0

    warmed = FusedBatchTransformer([NormalizeRows(), SignedHellingerMapper()])
    status = warmed.warmup(jax.ShapeDtypeStruct((6,), np.float32), RAGGED_N)
    assert status == "compiled"
    assert warmed.warmup(
        jax.ShapeDtypeStruct((6,), np.float32), RAGGED_N) == "cached"

    ds = Dataset.from_numpy(X)
    ds.mask  # its tiny utility jits are not this chain's program
    cold = counter("dispatch.programs_compiled")
    before = cold.value
    out = warmed.apply_batch(ds).numpy()
    assert cold.value == before, "warmed apply still compiled cold"

    reference = FusedBatchTransformer(
        [NormalizeRows(), SignedHellingerMapper()]).apply_batch(
        Dataset.from_numpy(X)).numpy()
    np.testing.assert_allclose(out, reference, rtol=1e-6)


def test_warmup_unwarmable_specs_are_refused():
    from keystone_tpu.nodes.stats import NormalizeRows
    from keystone_tpu.nodes.util.fusion import FusedBatchTransformer

    fused = FusedBatchTransformer([NormalizeRows()])
    assert fused.warmup(object(), 8) is None  # no shape/dtype
    import jax

    assert fused.warmup(jax.ShapeDtypeStruct((4,), np.float32), 0) is None


# --------------------------------------------------------------------------
# chunk-size config


def test_chunk_size_config_reaches_batching_and_memory_model():
    """`ExecutionConfig.chunk_size` is the one chunk number: the host
    batcher's default AND the static memory model's streaming-chunk
    assumption read it."""
    items = _items(n=12, dim=4)
    shapes = []

    def fn(xb):
        shapes.append(xb.shape)
        return xb

    with config_override(chunk_size=4, pad_chunks=False):
        assert execution_config().chunk_size == 4
        batching.map_host_batched(items, fn)  # no explicit chunk
        assert {s[0] for s in shapes} == {4}

        from keystone_tpu.analysis.memory import resolve_chunk_rows

        assert resolve_chunk_rows(None) == 4
        assert resolve_chunk_rows(64) == 64
    assert execution_config().chunk_size == 256  # override scoped
