"""Pipeline-semantics tests (model: reference PipelineSuite.scala).

Covers chaining, laziness, single-vs-batch parity, the fit-once guarantee
(mutable fit counters, PipelineSuite.scala:28-52), incremental state reuse
across applies (:115-240), gather, and fit() → FittedPipeline (:389-520).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from keystone_tpu import Dataset, Pipeline, PipelineEnv, Transformer
from keystone_tpu.workflow import Estimator, FittedPipeline, LabelEstimator


class Add(Transformer):
    def __init__(self, c):
        self.c = c

    def apply(self, x):
        return x + self.c


class Scale(Transformer):
    def __init__(self, c):
        self.c = c

    def apply(self, x):
        return x * self.c


class CountingMeanEstimator(Estimator):
    """Fits a transformer subtracting the dataset mean; counts fits."""

    def __init__(self):
        self.n_fits = 0

    def fit(self, data):
        self.n_fits += 1
        mu = float(np.mean(data.numpy()))
        return Add(-mu)


class CountingLinearLabelEstimator(LabelEstimator):
    def __init__(self):
        self.n_fits = 0

    def fit(self, data, labels):
        self.n_fits += 1
        X = data.numpy()
        y = labels.numpy()
        w, *_ = np.linalg.lstsq(X, y, rcond=None)
        W = w

        class Lin(Transformer):
            def apply(self, x):
                return jnp.dot(x, W)

        return Lin()


def dvec(values):
    return Dataset.from_numpy(np.asarray(values, dtype=np.float32))


def test_transformer_batch_and_single_parity():
    t = Add(2.0)
    ds = dvec([[1.0], [2.0], [3.0]])
    out = t(ds).get()
    np.testing.assert_allclose(out.numpy(), [[3.0], [4.0], [5.0]])
    single = t(np.float32(1.0)).get()
    assert float(single) == 3.0


def test_and_then_composition_order():
    p = Add(1.0).and_then(Scale(10.0))
    out = p(np.float32(2.0)).get()
    assert float(out) == 30.0
    # >> operator sugar
    p2 = Add(1.0) >> Scale(10.0) >> Add(5.0)
    assert float(p2(np.float32(0.0)).get()) == 15.0


def test_laziness_no_execution_until_get():
    calls = []

    class Tracker(Transformer):
        def apply(self, x):
            calls.append(1)
            return x

    result = Tracker()(np.float32(1.0))
    assert calls == []
    result.get()
    assert calls == [1]


def test_estimator_fit_once_across_applies():
    """Do not fit estimators multiple times (PipelineSuite.scala:28-52)."""
    est = CountingMeanEstimator()
    train = dvec([[0.0], [2.0], [4.0]])
    p = Add(0.0).and_then(est, train)
    test1 = dvec([[1.0]])
    test2 = dvec([[5.0]])
    out1 = p(test1).get()
    out2 = p(test2).get()
    assert est.n_fits == 1
    np.testing.assert_allclose(out1.numpy(), [[-1.0]])
    np.testing.assert_allclose(out2.numpy(), [[3.0]])


def test_single_item_apply_reuses_fit():
    est = CountingMeanEstimator()
    train = dvec([[0.0], [2.0], [4.0]])
    p = Add(0.0).and_then(est, train)
    assert float(p(np.float32(3.0)).get()) == 1.0
    assert float(p(np.float32(5.0)).get()) == 3.0
    assert est.n_fits == 1


def test_label_estimator_and_prediction():
    est = CountingLinearLabelEstimator()
    X = dvec([[1.0, 0.0], [0.0, 1.0], [1.0, 1.0]])
    y = dvec([[2.0], [3.0], [5.0]])
    p = Add(0.0).and_then(est, X, y)
    preds = p(X).get().numpy()
    np.testing.assert_allclose(preds, [[2.0], [3.0], [5.0]], atol=1e-4)
    assert est.n_fits == 1


def test_extending_pipeline_reuses_fitted_state():
    """Adding stages after a fit does not refit (PipelineSuite.scala:115-240)."""
    est = CountingMeanEstimator()
    train = dvec([[2.0], [4.0]])
    base = Add(0.0).and_then(est, train)
    _ = base(dvec([[1.0]])).get()
    assert est.n_fits == 1
    extended = base.and_then(Scale(2.0))
    out = extended(dvec([[1.0]])).get()
    assert est.n_fits == 1  # reused via prefix state
    np.testing.assert_allclose(out.numpy(), [[-4.0]])


def test_gather_merges_branches():
    branches = [Add(float(i)) for i in range(3)]
    p = Pipeline.gather(branches)
    out = p(np.float32(10.0)).get()
    assert [float(v) for v in out] == [10.0, 11.0, 12.0]
    # batch path: produces tuple-structured dataset
    ds_out = p(dvec([[1.0], [2.0]])).get()
    parts = ds_out.numpy()
    np.testing.assert_allclose(parts[0], [[1.0], [2.0]])
    np.testing.assert_allclose(parts[2], [[3.0], [4.0]])


def test_fit_produces_serializable_fitted_pipeline(tmp_path):
    est = CountingMeanEstimator()
    train = dvec([[2.0], [4.0]])
    p = Add(1.0).and_then(est, train).and_then(Scale(3.0))
    fitted = p.fit()
    assert isinstance(fitted, FittedPipeline)
    assert est.n_fits == 1
    # fitted pipeline applies eagerly, without refit
    assert float(fitted(np.float32(3.0))) == 0.0  # ((3+1)-4)*3
    assert est.n_fits == 1
    path = str(tmp_path / "fitted.pkl")
    fitted.save(path)
    loaded = FittedPipeline.load(path)
    assert float(loaded(np.float32(5.0))) == 6.0


def test_fit_prunes_training_branches():
    est = CountingMeanEstimator()
    train = dvec([[2.0], [4.0]])
    p = Add(0.0).and_then(est, train)
    fitted = p.fit()
    # no DatasetOperator (training data) should survive in the fitted graph
    from keystone_tpu.workflow import DatasetOperator

    assert not any(
        isinstance(fitted.graph.get_operator(n), DatasetOperator)
        for n in fitted.graph.nodes
    )


def test_cse_merges_shared_featurization():
    """The same transformer instance feeding estimator training and the
    serving path executes once per dataset (EquivalentNodeMergeRule)."""
    calls = []

    class Tracker(Transformer):
        def apply_batch(self, data):
            calls.append(1)
            return data

        def apply(self, x):
            return x

    t = Tracker()
    est = CountingMeanEstimator()
    train = dvec([[1.0], [3.0]])
    p = t.to_pipeline().and_then(est, train)
    out = p(train).get()  # train and serve on the same dataset
    assert est.n_fits == 1
    # featurization ran once for the shared (transformer, dataset) node
    assert len(calls) == 1


def test_pipeline_env_reset_isolates_state():
    est = CountingMeanEstimator()
    train = dvec([[2.0]])
    p = Add(0.0).and_then(est, train)
    _ = p(train).get()
    assert est.n_fits == 1
    PipelineEnv.reset()
    _ = p(train).get()
    assert est.n_fits == 2  # state gone after reset
