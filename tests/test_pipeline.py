"""Pipeline-semantics tests (model: reference PipelineSuite.scala).

Covers chaining, laziness, single-vs-batch parity, the fit-once guarantee
(mutable fit counters, PipelineSuite.scala:28-52), incremental state reuse
across applies (:115-240), gather, and fit() → FittedPipeline (:389-520).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from keystone_tpu import Dataset, Pipeline, PipelineEnv, Transformer
from keystone_tpu.workflow import Estimator, FittedPipeline, LabelEstimator


class Add(Transformer):
    def __init__(self, c):
        self.c = c

    def apply(self, x):
        return x + self.c


class Scale(Transformer):
    def __init__(self, c):
        self.c = c

    def apply(self, x):
        return x * self.c


class CountingMeanEstimator(Estimator):
    """Fits a transformer subtracting the dataset mean; counts fits."""

    def __init__(self):
        self.n_fits = 0

    def fit(self, data):
        self.n_fits += 1
        mu = float(np.mean(data.numpy()))
        return Add(-mu)


class CountingLinearLabelEstimator(LabelEstimator):
    def __init__(self):
        self.n_fits = 0

    def fit(self, data, labels):
        self.n_fits += 1
        X = data.numpy()
        y = labels.numpy()
        w, *_ = np.linalg.lstsq(X, y, rcond=None)
        W = w

        class Lin(Transformer):
            def apply(self, x):
                return jnp.dot(x, W)

        return Lin()


def dvec(values):
    return Dataset.from_numpy(np.asarray(values, dtype=np.float32))


def test_transformer_batch_and_single_parity():
    t = Add(2.0)
    ds = dvec([[1.0], [2.0], [3.0]])
    out = t(ds).get()
    np.testing.assert_allclose(out.numpy(), [[3.0], [4.0], [5.0]])
    single = t(np.float32(1.0)).get()
    assert float(single) == 3.0


def test_and_then_composition_order():
    p = Add(1.0).and_then(Scale(10.0))
    out = p(np.float32(2.0)).get()
    assert float(out) == 30.0
    # >> operator sugar
    p2 = Add(1.0) >> Scale(10.0) >> Add(5.0)
    assert float(p2(np.float32(0.0)).get()) == 15.0


def test_laziness_no_execution_until_get():
    calls = []

    class Tracker(Transformer):
        def apply(self, x):
            calls.append(1)
            return x

    result = Tracker()(np.float32(1.0))
    assert calls == []
    result.get()
    assert calls == [1]


def test_estimator_fit_once_across_applies():
    """Do not fit estimators multiple times (PipelineSuite.scala:28-52)."""
    est = CountingMeanEstimator()
    train = dvec([[0.0], [2.0], [4.0]])
    p = Add(0.0).and_then(est, train)
    test1 = dvec([[1.0]])
    test2 = dvec([[5.0]])
    out1 = p(test1).get()
    out2 = p(test2).get()
    assert est.n_fits == 1
    np.testing.assert_allclose(out1.numpy(), [[-1.0]])
    np.testing.assert_allclose(out2.numpy(), [[3.0]])


def test_single_item_apply_reuses_fit():
    est = CountingMeanEstimator()
    train = dvec([[0.0], [2.0], [4.0]])
    p = Add(0.0).and_then(est, train)
    assert float(p(np.float32(3.0)).get()) == 1.0
    assert float(p(np.float32(5.0)).get()) == 3.0
    assert est.n_fits == 1


def test_label_estimator_and_prediction():
    est = CountingLinearLabelEstimator()
    X = dvec([[1.0, 0.0], [0.0, 1.0], [1.0, 1.0]])
    y = dvec([[2.0], [3.0], [5.0]])
    p = Add(0.0).and_then(est, X, y)
    preds = p(X).get().numpy()
    np.testing.assert_allclose(preds, [[2.0], [3.0], [5.0]], atol=1e-4)
    assert est.n_fits == 1


def test_extending_pipeline_reuses_fitted_state():
    """Adding stages after a fit does not refit (PipelineSuite.scala:115-240)."""
    est = CountingMeanEstimator()
    train = dvec([[2.0], [4.0]])
    base = Add(0.0).and_then(est, train)
    _ = base(dvec([[1.0]])).get()
    assert est.n_fits == 1
    extended = base.and_then(Scale(2.0))
    out = extended(dvec([[1.0]])).get()
    assert est.n_fits == 1  # reused via prefix state
    np.testing.assert_allclose(out.numpy(), [[-4.0]])


def test_gather_merges_branches():
    branches = [Add(float(i)) for i in range(3)]
    p = Pipeline.gather(branches)
    out = p(np.float32(10.0)).get()
    assert [float(v) for v in out] == [10.0, 11.0, 12.0]
    # batch path: produces tuple-structured dataset
    ds_out = p(dvec([[1.0], [2.0]])).get()
    parts = ds_out.numpy()
    np.testing.assert_allclose(parts[0], [[1.0], [2.0]])
    np.testing.assert_allclose(parts[2], [[3.0], [4.0]])


def test_fit_produces_serializable_fitted_pipeline(tmp_path):
    est = CountingMeanEstimator()
    train = dvec([[2.0], [4.0]])
    p = Add(1.0).and_then(est, train).and_then(Scale(3.0))
    fitted = p.fit()
    assert isinstance(fitted, FittedPipeline)
    assert est.n_fits == 1
    # fitted pipeline applies eagerly, without refit
    assert float(fitted(np.float32(3.0))) == 0.0  # ((3+1)-4)*3
    assert est.n_fits == 1
    path = str(tmp_path / "fitted.pkl")
    fitted.save(path)
    loaded = FittedPipeline.load(path)
    assert float(loaded(np.float32(5.0))) == 6.0


def test_fit_prunes_training_branches():
    est = CountingMeanEstimator()
    train = dvec([[2.0], [4.0]])
    p = Add(0.0).and_then(est, train)
    fitted = p.fit()
    # no DatasetOperator (training data) should survive in the fitted graph
    from keystone_tpu.workflow import DatasetOperator

    assert not any(
        isinstance(fitted.graph.get_operator(n), DatasetOperator)
        for n in fitted.graph.nodes
    )


def test_cse_merges_shared_featurization():
    """The same transformer instance feeding estimator training and the
    serving path executes once per dataset (EquivalentNodeMergeRule)."""
    calls = []

    class Tracker(Transformer):
        def apply_batch(self, data):
            calls.append(1)
            return data

        def apply(self, x):
            return x

    t = Tracker()
    est = CountingMeanEstimator()
    train = dvec([[1.0], [3.0]])
    p = t.to_pipeline().and_then(est, train)
    out = p(train).get()  # train and serve on the same dataset
    assert est.n_fits == 1
    # featurization ran once for the shared (transformer, dataset) node
    assert len(calls) == 1


def test_pipeline_env_reset_isolates_state():
    est = CountingMeanEstimator()
    train = dvec([[2.0]])
    p = Add(0.0).and_then(est, train)
    _ = p(train).get()
    assert est.n_fits == 1
    PipelineEnv.reset()
    _ = p(train).get()
    assert est.n_fits == 2  # state gone after reset


# ---- PipelineSuite.scala:115-240: incremental execution-state reuse -------
# The reference counts per-item recomputation with Spark accumulators; the
# analog here is a host-side counter in a per-item transformer over a
# HostDataset (the per-item execution path, like the reference's RDD maps).


class _CountingTriple(Transformer):
    def __init__(self, counter):
        self.counter = counter

    def apply(self, x):
        self.counter[0] += 1
        return str(int(x) * 3)


class _QubEstimator(Estimator):
    def fit(self, data):
        class Qub(Transformer):
            def apply(self, x):
                return x + "qub"

        return Qub()


class _QubLabelEstimator(LabelEstimator):
    def fit(self, data, labels):
        class Qub(Transformer):
            def apply(self, x):
                return x + "qub"

        return Qub()


def _hd(values):
    from keystone_tpu import HostDataset

    return HostDataset(list(values))


def test_incremental_state_variation_1():
    """PipelineSuite.scala:115-148: cached features are not reprocessed
    when the pipeline is extended and re-applied; new data costs only its
    own items."""
    from keystone_tpu.nodes.util import Cacher

    counter = [0]
    featurizer = _CountingTriple(counter).to_pipeline() >> Cacher()
    data = _hd([32, 94, 12])
    features = featurizer(data)
    assert features.get().items == ["96", "282", "36"]
    assert counter[0] == 3

    # reference form: featurizer andThen est.withData(features) — the
    # estimator fits on the ALREADY-featurized result
    # (PipelineSuite.scala:136; and_then(est, data) would re-featurize)
    pipe = featurizer >> _QubEstimator().with_data(features)
    out = pipe(data)
    assert out.get().items == ["96qub", "282qub", "36qub"]
    assert out.get().items == ["96qub", "282qub", "36qub"]
    assert pipe(data).get().items == ["96qub", "282qub", "36qub"]
    assert counter[0] == 3, "cached values must not be reprocessed"

    test_data = _hd([32, 94])
    test_out = pipe(test_data)
    assert test_out.get().items == ["96qub", "282qub"]
    assert test_out.get().items == ["96qub", "282qub"]
    assert counter[0] == 5, "only the new dataset's items run"


def test_incremental_state_variation_2():
    """PipelineSuite.scala:150-192: a model estimated from cached
    features applies to those features without recomputation; a single
    uncached datum costs exactly one run."""
    from keystone_tpu.nodes.util import Cacher

    counter = [0]
    featurizer = _CountingTriple(counter).to_pipeline() >> Cacher()
    data = _hd([32, 94, 12])
    features = featurizer(data)
    assert features.get().items == ["96", "282", "36"]
    assert counter[0] == 3

    test_features = featurizer(_hd([32, 94]))
    assert test_features.get().items == ["96", "282"]
    assert counter[0] == 5

    model = _QubEstimator().with_data(features)
    out = model(features)
    assert out.get().items == ["96qub", "282qub", "36qub"]
    assert out.get().items == ["96qub", "282qub", "36qub"]
    assert counter[0] == 5

    test_out = model(test_features)
    assert test_out.get().items == ["96qub", "282qub"]
    assert counter[0] == 5

    datum_out = model(featurizer(2))
    assert datum_out.get() == "6qub"
    assert datum_out.get() == "6qub"
    assert counter[0] == 6, "single uncached value runs exactly once"


def test_incremental_state_with_label_estimator():
    """PipelineSuite.scala:194-238: label estimators reuse cached feature
    and label branches across applies."""
    from keystone_tpu.nodes.util import Cacher

    counter = [0]
    featurizer = _CountingTriple(counter).to_pipeline() >> Cacher()
    data = _hd([32, 94, 12])
    labels = _hd([64, 188, 24])

    features = featurizer(data)
    assert features.get().items == ["96", "282", "36"]
    assert counter[0] == 3
    label_features = featurizer(labels)
    assert label_features.get().items == ["192", "564", "72"]
    assert counter[0] == 6

    pipe = featurizer >> _QubLabelEstimator().with_data(
        features, label_features
    )
    out = pipe(data)
    assert out.get().items == ["96qub", "282qub", "36qub"]
    assert pipe(data).get().items == ["96qub", "282qub", "36qub"]
    assert counter[0] == 6

    labels_out = pipe(labels)
    assert labels_out.get().items == ["192qub", "564qub", "72qub"]
    assert counter[0] == 6

    test_out = pipe(_hd([32, 94]))
    assert test_out.get().items == ["96qub", "282qub"]
    assert counter[0] == 8


def test_access_features_and_final_value():
    """PipelineSuite.scala:328-387: both an intermediate (features) sink
    and the final prediction share one execution of the common prefix."""
    from keystone_tpu.nodes.util import Cacher

    counter = [0]
    featurizer = _CountingTriple(counter).to_pipeline() >> Cacher()
    data = _hd([1, 2, 3])
    features = featurizer(data)
    pipe = featurizer >> _QubEstimator().with_data(features)
    preds = pipe(data)
    assert features.get().items == ["3", "6", "9"]
    assert preds.get().items == ["3qub", "6qub", "9qub"]
    assert counter[0] == 3, "features and predictions share one run"


def test_incremental_state_with_and_then_chaining():
    """PipelineSuite.scala:240-326: two fitted pipeline halves chained
    with andThen reuse every fit and every cached featurization; the
    exact recomputation counts match the reference."""
    from keystone_tpu import HostDataset
    from keystone_tpu.nodes.util import Cacher

    t1c, t2c, e1c, e2c = [0], [0], [0], [0]

    class T1(Transformer):
        def apply(self, x):
            t1c[0] += 1
            return x + "d"

    class T2(Transformer):
        def apply(self, x):
            t2c[0] += 1
            return x + "e"

    def make_est(counter, suffix):
        class E(Estimator):
            def fit(self, data):
                counter[0] += len(data.items)

                class S(Transformer):
                    def apply(self, x):
                        return x + suffix

                return S()

        return E()

    est1, est2 = make_est(e1c, "abc"), make_est(e2c, "xyz")
    data1 = HostDataset(["h", "i", "j"])
    data2 = HostDataset(["f", "g"])

    pipe_left = (T1().to_pipeline() >> Cacher()).and_then(est1, data1)
    pipe_right = (T2().to_pipeline() >> Cacher()).and_then(est2, data2)
    # nothing executes before .get()
    assert (t1c[0], t2c[0], e1c[0], e2c[0]) == (0, 0, 0, 0)

    assert pipe_left(data1).get().items == ["hdabc", "idabc", "jdabc"]
    assert (t1c[0], t2c[0], e1c[0], e2c[0]) == (3, 0, 3, 0)

    assert pipe_right(data2).get().items == ["fexyz", "gexyz"]
    assert (t1c[0], t2c[0], e1c[0], e2c[0]) == (3, 2, 3, 2)

    pipe = pipe_left >> pipe_right

    # reuses both fits and the cached transformer1(data1); transformer2
    # must run on the new intermediate values
    assert pipe(data1).get().items == ["hdabcexyz", "idabcexyz", "jdabcexyz"]
    assert (t1c[0], t2c[0], e1c[0], e2c[0]) == (3, 5, 3, 2)

    # data2 through the full chain: t1 and t2 both compute; no refits
    assert pipe(data2).get().items == ["fdabcexyz", "gdabcexyz"]
    assert (t1c[0], t2c[0], e1c[0], e2c[0]) == (5, 7, 3, 2)

    # single datum: both transformers compute once; no refits
    assert pipe("l").get() == "ldabcexyz"
    assert (t1c[0], t2c[0], e1c[0], e2c[0]) == (6, 8, 3, 2)


# ---- EstimatorSuite.scala / LabelEstimatorSuite.scala ---------------------


def test_estimator_with_data_raw_and_pipeline_data():
    """EstimatorSuite.scala: withData accepts both raw datasets and lazy
    pipeline results; the fit sees exactly that data."""
    from keystone_tpu import HostDataset

    class FirstAdder(Estimator):
        def fit(self, data):
            first = data.items[0]

            class A(Transformer):
                def apply(self, x):
                    return x + first

            return A()

    train = HostDataset([32, 94, 12])
    test = HostDataset([42, 58, 61])
    pipe = FirstAdder().with_data(train)
    assert pipe(test).get().items == [42 + 32, 58 + 32, 61 + 32]

    class Doubler(Transformer):
        def apply(self, x):
            return x * 2

    pipe2 = FirstAdder().with_data(Doubler().to_pipeline()(train))
    assert pipe2(test).get().items == [42 + 64, 58 + 64, 61 + 64]


def test_label_estimator_with_data_raw_and_pipeline_data():
    """LabelEstimatorSuite.scala:9-50: both data and labels may be raw
    or lazy pipeline results."""
    from keystone_tpu import HostDataset

    class SumFitter(LabelEstimator):
        def fit(self, data, labels):
            s = data.items[0] + labels.items[0]

            class A(Transformer):
                def apply(self, x):
                    return x + s

            return A()

    train = HostDataset([10, 20])
    labels = HostDataset([5, 6])
    test = HostDataset([1, 2])
    pipe = SumFitter().with_data(train, labels)
    assert pipe(test).get().items == [1 + 15, 2 + 15]

    class Neg(Transformer):
        def apply(self, x):
            return -x

    pipe2 = SumFitter().with_data(
        Neg().to_pipeline()(train), Neg().to_pipeline()(labels)
    )
    assert pipe2(test).get().items == [1 - 15, 2 - 15]


def test_gather_incremental_construction():
    """PipelineSuite.scala:429-482: gathering already-fitted pipelines
    reuses their fits; the gathered output matches each branch applied
    separately for both single datums and datasets."""
    from keystone_tpu import HostDataset

    n_fits = [0]

    class FirstAdder(Estimator):
        def fit(self, data):
            n_fits[0] += 1
            first = data.items[0]

            class A(Transformer):
                def apply(self, x):
                    return x + first

            return A()

    class FirstSumAdder(LabelEstimator):
        def fit(self, data, labels):
            n_fits[0] += 1
            s = data.items[0] + int(labels.items[0])

            class A(Transformer):
                def apply(self, x):
                    return x + s

            return A()

    fit_data = HostDataset([32, 94, 12])
    first = Scale(2).to_pipeline() >> Add(-3)
    second = Scale(2).to_pipeline().and_then(FirstAdder(), fit_data)
    third = Scale(4).to_pipeline().and_then(
        FirstSumAdder(), fit_data, HostDataset(["10", "7", "14"])
    )

    assert n_fits[0] == 0, "nothing may have been fit yet"
    assert first(4).get() == 5
    assert second(4).get() == 8 + 64
    assert third(4).get() == 16 + (128 + 10)
    assert n_fits[0] == 2, "both estimators fit by now"

    gathered = Pipeline.gather([first, second, third])
    single = 7
    assert list(gathered(single).get()) == [
        first(single).get(), second(single).get(), third(single).get()
    ]
    data = [13, 2, 83]
    want = [
        [first(x).get(), second(x).get(), third(x).get()] for x in data
    ]
    got = [list(row) for row in gathered(HostDataset(data)).get().items]
    assert got == want
    assert n_fits[0] == 2, "gather must not refit"
