"""Image featurizer tests (model: reference ConvolverSuite golden test vs
scipy — src/test/python/images/pyconv.py — plus shape/semantics suites)."""

import numpy as np
import pytest
import scipy.signal

from keystone_tpu import Dataset
from keystone_tpu.nodes.images import (
    CenterCornerPatcher,
    Convolver,
    Cropper,
    GrayScaler,
    ImageVectorizer,
    PixelScaler,
    Pooler,
    RandomPatcher,
    SymmetricRectifier,
    Windower,
)
from keystone_tpu.nodes.learning import ZCAWhitenerEstimator
from keystone_tpu.nodes.util.fusion import FusedBatchTransformer
from keystone_tpu.utils.images import extract_patches


def test_convolver_matches_scipy_golden():
    """Plain conv (no whitening/normalization) vs scipy.signal.correlate
    (the reference checks per-pixel agreement with a SciPy fixture)."""
    rng = np.random.default_rng(0)
    img = rng.normal(size=(16, 16, 3)).astype(np.float32)
    filters = rng.normal(size=(4, 5, 5, 3)).astype(np.float32)
    conv = Convolver(filters, 16, 16, 3, whitener=None, normalize_patches=False)
    out = np.asarray(conv.apply(img))
    assert out.shape == (12, 12, 4)
    for k in range(4):
        ref = sum(
            scipy.signal.correlate(img[:, :, c], filters[k, :, :, c], mode="valid")
            for c in range(3)
        )
        np.testing.assert_allclose(out[:, :, k], ref, atol=1e-3)


def test_convolver_whitening_fold_matches_explicit_patches():
    """The folded conv must equal: extract patch → subtract patch mean →
    ZCA whiten → dot filters (the reference's im2col semantics,
    Convolver.scala:158-203)."""
    rng = np.random.default_rng(1)
    imgs = rng.normal(size=(3, 12, 12, 3)).astype(np.float32)
    patch = 4
    D = patch * patch * 3
    sample = extract_patches(imgs, patch).astype(np.float32)
    whitener = ZCAWhitenerEstimator(eps=0.1).fit_single(sample)
    filters = rng.normal(size=(8, D)).astype(np.float32)

    conv = Convolver(filters, 12, 12, 3, whitener=whitener, normalize_patches=True)
    out = np.asarray(conv.apply(imgs[0]))

    # explicit path
    patches = extract_patches(imgs[0][None], patch)  # (81, D) row-major grid
    patches = patches - patches.mean(axis=1, keepdims=True)
    whitened = (patches - whitener.means_np) @ whitener.whitener_np
    expected = (whitened @ filters.T).reshape(9, 9, 8)
    np.testing.assert_allclose(out, expected, atol=2e-3)


def test_symmetric_rectifier_doubles_channels():
    x = np.array([[[1.0, -2.0]]], np.float32)
    out = np.asarray(SymmetricRectifier(alpha=0.25).apply(x))
    np.testing.assert_allclose(out[0, 0], [0.75, 0.0, 0.0, 1.75])


def test_pooler_sum_and_max():
    x = np.arange(16, dtype=np.float32).reshape(4, 4, 1)
    s = np.asarray(Pooler(2, 2, pool_fn="sum").apply(x))
    assert s.shape == (2, 2, 1)
    assert s[0, 0, 0] == 0 + 1 + 4 + 5
    m = np.asarray(Pooler(2, 2, pool_fn="max").apply(x))
    assert m[1, 1, 0] == 15


def test_pooler_batch_fn_matches_per_item():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(5, 6, 6, 2)).astype(np.float32)
    p = Pooler(2, 3, pool_fn="sum")
    batched = np.asarray(p.batch_fn()(x))
    for i in range(5):
        np.testing.assert_allclose(batched[i], np.asarray(p.apply(x[i])), atol=1e-5)


def test_fused_transformer_equals_sequential():
    rng = np.random.default_rng(3)
    imgs = rng.uniform(0, 255, size=(23, 12, 12, 3)).astype(np.float32)
    filters = rng.normal(size=(6, 4, 4, 3)).astype(np.float32)
    stages = [
        PixelScaler(),
        Convolver(filters, 12, 12, 3, whitener=None, normalize_patches=False),
        SymmetricRectifier(alpha=0.1),
        Pooler(3, 4, pool_fn="sum"),
        ImageVectorizer(),
    ]
    ds = Dataset(imgs)
    fused_out = FusedBatchTransformer(stages, microbatch=4).apply_batch(ds).numpy()
    seq = ds
    for s in stages:
        seq = s.apply_batch(seq)
    np.testing.assert_allclose(fused_out, seq.numpy(), atol=1e-4)
    assert fused_out.shape[0] == 23


def test_windower_counts_and_values():
    imgs = np.arange(2 * 5 * 5 * 1, dtype=np.float32).reshape(2, 5, 5, 1)
    out = Windower(2, 3).apply_batch(Dataset(imgs))
    # grid positions: ceil((5-3+1)/2)=2 per axis -> 4 patches per image
    assert out.count == 2 * 4
    first = out.numpy()[0]
    np.testing.assert_allclose(first[:, :, 0], imgs[0, 0:3, 0:3, 0])


def test_patchers_and_croppers():
    imgs = np.random.default_rng(4).normal(size=(3, 8, 8, 3)).astype(np.float32)
    rp = RandomPatcher(5, 4, 4, seed=1).apply_batch(Dataset(imgs))
    assert rp.count == 15 and rp.numpy().shape[1:] == (4, 4, 3)
    cc = CenterCornerPatcher(4, 4, with_flips=True).apply_batch(Dataset(imgs))
    assert cc.count == 3 * 10
    crop = np.asarray(Cropper(1, 2, 5, 6).apply(imgs[0]))
    assert crop.shape == (4, 4, 3)
    np.testing.assert_allclose(crop, imgs[0][1:5, 2:6])


def test_grayscaler_ntsc():
    img = np.ones((2, 2, 3), np.float32)
    out = np.asarray(GrayScaler().apply(img))
    np.testing.assert_allclose(out, np.ones((2, 2, 1)), atol=1e-5)


def test_zca_whitener_decorrelates():
    rng = np.random.default_rng(5)
    A = rng.normal(size=(4, 4)).astype(np.float32)
    X = (rng.normal(size=(2000, 4)) @ A).astype(np.float32)
    w = ZCAWhitenerEstimator(eps=1e-5).fit_single(X)
    Xw = (X - w.means_np) @ w.whitener_np
    cov = Xw.T @ Xw / (len(X) - 1)
    np.testing.assert_allclose(cov, np.eye(4), atol=0.05)


def test_windower_device_path_matches_host_reference():
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.images.core import Windower
    from keystone_tpu.utils.images import extract_patches

    rng = np.random.default_rng(5)
    imgs = rng.random(size=(5, 9, 9, 2)).astype(np.float32)  # 5 % shards != 0
    out = Windower(2, 4).apply_batch(Dataset(imgs))
    want = extract_patches(imgs, 4, 2).reshape(-1, 4, 4, 2)
    assert out.count == want.shape[0]
    np.testing.assert_allclose(out.numpy(), want, rtol=1e-6)


def test_random_patcher_device_gather_matches_host_loop():
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.images.core import RandomPatcher

    rng = np.random.default_rng(6)
    imgs = rng.random(size=(6, 12, 12, 3)).astype(np.float32)
    node = RandomPatcher(3, 5, 5, seed=7)
    out = node.apply_batch(Dataset(imgs))
    # host reference with the same seed-derived offsets
    r = np.random.default_rng(7)
    ys = r.integers(0, 12 - 5 + 1, size=(6, 3))
    xs = r.integers(0, 12 - 5 + 1, size=(6, 3))
    want = np.stack([
        imgs[i, ys[i, j]: ys[i, j] + 5, xs[i, j]: xs[i, j] + 5]
        for i in range(6) for j in range(3)
    ])
    assert out.count == 18
    np.testing.assert_allclose(out.numpy(), want, rtol=1e-6)


def test_center_corner_patcher_device_order_and_flips():
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.images.core import CenterCornerPatcher

    rng = np.random.default_rng(8)
    imgs = rng.random(size=(3, 8, 8, 1)).astype(np.float32)
    node = CenterCornerPatcher(4, 4, with_flips=True)
    out = node.apply_batch(Dataset(imgs))
    assert out.count == 3 * 10
    # image-major order: first 10 rows are image 0's crops; row 0 is the
    # top-left crop, row 5 its horizontal flip
    got = out.numpy()
    np.testing.assert_allclose(got[0], imgs[0, :4, :4])
    np.testing.assert_allclose(got[5], imgs[0, :4, :4][:, ::-1])
    np.testing.assert_allclose(got[10], imgs[1, :4, :4])
    # single-item path agrees
    single = np.asarray(node.apply(imgs[0]))
    np.testing.assert_allclose(got[:10], single, rtol=1e-6)


def test_random_image_transformer_device_matches_host():
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.images.core import RandomImageTransformer
    from keystone_tpu.utils.images import flip_horizontal

    rng = np.random.default_rng(9)
    imgs = rng.random(size=(10, 6, 6, 3)).astype(np.float32)
    node = RandomImageTransformer(0.5, flip_horizontal, seed=4)
    got = node.apply_batch(Dataset(imgs)).numpy()
    # host reference with the same draws
    r = np.random.default_rng(4)
    flips = r.random(10) < 0.5
    want = imgs.copy()
    for i in np.nonzero(flips)[0]:
        want[i] = want[i][:, ::-1]
    assert flips.any() and not flips.all()  # both branches exercised
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_random_image_transformer_host_fallback_for_python_transform():
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.images.core import RandomImageTransformer

    def numpy_only(img):  # not jnp-traceable: forces the host fallback
        arr = np.asarray(img)
        return arr[::-1].copy()

    imgs = np.random.default_rng(2).random(size=(6, 4, 4, 1)).astype(np.float32)
    node = RandomImageTransformer(1.0, numpy_only, seed=0)
    got = node.apply_batch(Dataset(imgs)).numpy()
    np.testing.assert_allclose(got, imgs[:, ::-1])


def test_convolver_matches_reference_checked_in_fixture():
    """The reference's own golden (ConvolverSuite.scala:100-140 +
    src/test/python/images/pyconv.py): scipy.signal.convolve(img, k1,
    'valid').sum(2) with k1=arange(27).reshape(3,3,3), checked in as
    convolved.gantrycrane.csv. True convolution flips every axis, so our
    correlation-form Convolver takes the fully-flipped kernel — measured
    agreement with the fixture is EXACT (max |Δ| = 0)."""
    import os

    from PIL import Image as PILImage

    from keystone_tpu.nodes.images.core import Convolver

    base = os.path.join(os.path.dirname(__file__), "resources")
    img = np.asarray(
        PILImage.open(os.path.join(base, "gantrycrane.png")).convert("RGB"),
        np.float32,
    )
    h, w, c = img.shape
    k1 = np.arange(27, dtype=np.float32).reshape(3, 3, 3)
    filt = k1[::-1, ::-1, ::-1].reshape(1, -1)
    conv = Convolver(filt, h, w, c, whitener=None, normalize_patches=False,
                     patch_size=3)
    out = np.asarray(conv.apply(img))[..., 0]
    csv = np.loadtxt(os.path.join(base, "convolved.gantrycrane.csv"),
                     delimiter=",")
    want = np.zeros((int(csv[:, 0].max()) + 1, int(csv[:, 1].max()) + 1))
    want[csv[:, 0].astype(int), csv[:, 1].astype(int)] = csv[:, 2]
    assert out.shape == want.shape
    np.testing.assert_allclose(out, want, atol=1e-2)
