"""Live telemetry plane: flight recorder, streaming sketches, watchdog.

Covers the ISSUE-18 contracts:

  - the flight ring is bounded and tear-free under N concurrent
    emitter threads racing a snapshot loop;
  - a snapshot taken mid-span (the ``megafused_program`` regression)
    exports the open span as incomplete-but-parseable and round-trips
    through the telemetry CLI;
  - the conformance watchdog, armed with a KP9xx certificate record,
    increments ``serving.slo_breaches`` on a breach, dumps the flight
    ring, and emits a ``kind="conformance"`` ledger record naming the
    certified bound — which `reconcile_decisions` joins;
  - the streaming sketches hold fixed memory, stay accurate, and merge;
  - the metrics Histogram reservoir is bounded with working
    percentiles;
  - ``KEYSTONE_LIVE_TELEMETRY=0`` turns the whole plane off.
"""

import json
import threading
import time

import pytest

from keystone_tpu.telemetry import flight, ledger, metrics, streaming, watchdog
from keystone_tpu.telemetry.export import load_trace, summarize, to_chrome_trace
from keystone_tpu.telemetry.spans import (
    Tracer,
    set_tracer,
    span,
    trace_run,
)
from keystone_tpu.workflow.env import config_override


@pytest.fixture(autouse=True)
def fresh_plane():
    """Every test gets a clean registry, ring, sketch table, watchdog,
    and ledger session — and leaves none behind."""
    metrics.registry().reset()
    streaming.reset_live()
    watchdog.disarm_watchdog()
    flight.reset_flight()
    ledger.clear_session()
    set_tracer(None)
    yield
    metrics.registry().reset()
    streaming.reset_live()
    watchdog.disarm_watchdog()
    flight.reset_flight()
    ledger.clear_session()
    set_tracer(None)


CERT = {
    "certified": True,
    "slo_seconds": 0.5,
    "shapes": [
        {"batch": 1, "predicted_seconds": 0.1},
        {"batch": 64, "predicted_seconds": 0.2},
        {"batch": 256, "predicted_seconds": 0.3},
    ],
}


# ------------------------------------------------------------- the ring


def test_ring_is_bounded_and_evicts_oldest():
    ring = flight._Ring(4)
    for i in range(10):
        ring.append(i)
    assert len(ring) == 4
    assert ring.snapshot() == [6, 7, 8, 9]
    assert ring.dropped == 6


def test_flight_ring_bounded_under_concurrent_emitters(tmp_path):
    """N worker threads emit spans while snapshots run in a loop: no
    torn records, capacity bound holds, every dump parses."""
    rec = flight.ensure_flight()
    assert rec is not None
    cap = rec.capacity
    n_threads, per_thread = 8, 300
    stop = threading.Event()
    dumps = []

    def emit(k):
        for i in range(per_thread):
            rec.record_complete(f"work_{k}", "node", rec.now(), 1e-6,
                                idx=i)

    def snapshotter():
        j = 0
        while not stop.is_set():
            p = str(tmp_path / f"snap_{j}.json")
            out = flight.flight_snapshot(p)
            if out:
                dumps.append(out)
            j += 1

    snap = threading.Thread(target=snapshotter)
    snap.start()
    workers = [threading.Thread(target=emit, args=(k,))
               for k in range(n_threads)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    stop.set()
    snap.join()

    assert len(rec.spans) <= cap
    held = len(rec.spans) + rec.spans.dropped
    assert held == n_threads * per_thread
    assert dumps, "snapshot loop never produced a dump"
    for p in dumps:
        trace = load_trace(p)  # every dump is a valid Chrome trace
        events = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
        assert len(events) <= cap + 1  # +1: process_name is ph=M anyway
        for e in events:
            # no torn records: every span has its full field set
            assert {"name", "cat", "ts", "dur", "args"} <= set(e)


def test_tee_copies_closed_spans_into_ring():
    rec = flight.ensure_flight()
    with trace_run() as tracer:
        with span("stage_a", "node"):
            pass
    names = [s.name for s in rec.spans]
    assert "stage_a" in names
    assert "pipeline_run" in names
    # teed copies, not shared records: mutating the ring's copy must
    # not touch the source tracer's record
    src = tracer.spans[0]
    teed = next(s for s in rec.spans if s.name == src.name)
    assert teed is not src


# ------------------------------------- in-flight spans survive the dump


def test_snapshot_mid_span_roundtrips_through_cli(tmp_path, capsys):
    """The satellite regression: a snapshot racing an open
    ``megafused_program`` span emits it incomplete-but-parseable, and
    the telemetry CLI renders the dump."""
    rec = flight.ensure_flight()
    t = Tracer()
    set_tracer(t)
    open_rec = t.start("megafused_program", "node", plan="p0")
    path = str(tmp_path / "midspan.json")
    out = flight.flight_snapshot(path)
    t.end(open_rec)
    set_tracer(None)
    assert out == path

    trace = load_trace(path)
    mega = [e for e in trace["traceEvents"]
            if e.get("name") == "megafused_program"]
    assert mega and mega[0]["args"]["incomplete"] is True
    assert mega[0]["dur"] >= 0.0

    from keystone_tpu.telemetry.__main__ import main as cli_main

    assert cli_main([path]) == 0
    assert cli_main(["--flight", path]) == 0
    rendered = capsys.readouterr().out
    assert "megafused_program" in rendered
    assert "in-flight at dump" in rendered


def test_atexit_style_flush_emits_open_spans():
    """`to_chrome_trace` (the KEYSTONE_TRACE atexit flush path) exports
    in-flight spans instead of dropping them."""
    t = Tracer()
    open_rec = t.start("long_apply", "node")
    trace = to_chrome_trace(t)
    t.end(open_rec)
    names = {e["name"]: e for e in trace["traceEvents"]
             if e.get("ph") == "X"}
    assert "long_apply" in names
    assert names["long_apply"]["args"]["incomplete"] is True
    assert "in-flight at dump" in summarize(trace)


# ------------------------------------------------------------- watchdog


def test_watchdog_bound_lookup_covers_ladder():
    wd = watchdog.ConformanceWatchdog.from_certificate(CERT, "p")
    assert wd.bound_for(64) == 0.2    # exact ladder entry
    assert wd.bound_for(1) == 0.1     # exact ladder entry
    assert wd.bound_for(2) == 0.2     # smallest certified batch >= 2
    assert wd.bound_for(512) is None  # out of envelope: no claim made


def test_watchdog_breach_counts_dumps_and_ledgers(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_FLIGHT_DIR", str(tmp_path))
    flight.ensure_flight()
    wd = watchdog.arm_watchdog(CERT, pipeline="demo")
    assert wd is not None
    mark = ledger.session_mark()

    assert wd.check(64, 0.05) is False  # within bound
    assert wd.check(64, 9.0) is True    # breach

    reg = metrics.registry()
    assert reg.counter("serving.slo_breaches").value == 1
    assert reg.counter("serving.conformance_checks").value == 2

    records = [d for d in ledger.session_since(mark)
               if d["kind"] == "conformance"]
    assert len(records) == 1
    rec = records[0]
    assert rec["predicted"]["bound_seconds"] == pytest.approx(0.2)
    assert rec["chosen"]["observed_seconds"] == pytest.approx(9.0)
    assert rec["chosen"]["chunk_shape"] == 64
    assert rec["alternatives"][0]["cost_seconds"] == pytest.approx(0.2)
    # the dump artifact exists and parses
    dump = rec["chosen"]["flight_dump"]
    assert dump and load_trace(dump)


def test_conformance_record_joins_in_reconcile(tmp_path, monkeypatch):
    """`reconcile_decisions` joins the conformance record's bound
    against the live request spans in the trace."""
    monkeypatch.setenv("KEYSTONE_FLIGHT_DIR", str(tmp_path))
    from keystone_tpu.analysis.reconcile import reconcile_decisions

    flight.ensure_flight()
    watchdog.arm_watchdog(CERT, pipeline="demo")
    with trace_run() as tracer:
        t0 = tracer.now()
        tracer.record_complete("apply_request", "request", t0, 9.0,
                               batch=64, chunk_shape=64, pipeline="demo")
        watchdog.active_watchdog().check(64, 9.0, batch=64)
        trace = to_chrome_trace(tracer)
    run = {"trace": trace,
           "decisions": trace["keystone"]["decisions"],
           "header": {}}
    rec = reconcile_decisions(run)
    rows = [r for r in rec["rows"] if r["kind"] == "conformance"]
    assert len(rows) == 1
    assert rows[0]["observed"]["observed_seconds"] == pytest.approx(9.0)
    assert rows[0]["residuals"]["bound_seconds"] == pytest.approx(0.2 - 9.0)


def test_request_scope_feeds_sketches_and_watchdog(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_FLIGHT_DIR", str(tmp_path))
    wd = watchdog.arm_watchdog(
        {"shapes": [{"batch": 1, "predicted_seconds": 1e-9}],
         "slo_seconds": 0.001, "certified": True},
        pipeline="tight")
    with watchdog.request_scope(1, pipeline="tight"):
        time.sleep(0.002)
    assert wd.checked == 1 and wd.breaches == 1
    assert metrics.registry().counter("serving.requests").value == 1
    sk = streaming.latency_sketch("tight", 1)
    assert sk is not None and sk.count == 1
    # the request span landed in the flight ring
    rec = flight.flight_recorder()
    assert any(s.name == "apply_request" for s in rec.spans)
    h = streaming.health()
    assert h["requests"] == 1
    assert h["watchdog"]["breaches"] == 1
    rendered = streaming.format_health(h)
    assert "tight" in rendered and "breach" in rendered


# ------------------------------------------------------------ streaming


def test_sketch_fixed_memory_and_accuracy():
    sk = streaming.QuantileSketch(max_bins=64)
    for i in range(50_000):
        sk.observe((i % 1000) / 1000.0)
    assert len(sk._bins) <= 64
    assert sk.count == 50_000
    assert sk.quantile(0.5) == pytest.approx(0.5, abs=0.05)
    assert sk.quantile(0.99) == pytest.approx(0.99, abs=0.05)
    assert sk.min == 0.0 and sk.max == pytest.approx(0.999)


def test_sketch_merge():
    a = streaming.QuantileSketch()
    b = streaming.QuantileSketch()
    for i in range(1000):
        a.observe(i / 1000.0)        # [0, 1)
        b.observe(1.0 + i / 1000.0)  # [1, 2)
    a.merge(b)
    assert a.count == 2000
    assert len(a._bins) <= a.max_bins
    assert a.quantile(0.5) == pytest.approx(1.0, abs=0.1)
    assert a.max == pytest.approx(1.999)


def test_histogram_reservoir_bounded_with_percentiles():
    h = metrics.histogram("t.reservoir")
    for i in range(10_000):
        h.observe(i / 10_000.0)
    assert len(h._reservoir) == metrics.RESERVOIR_SIZE
    snap = h.snapshot()
    assert snap["count"] == 10_000              # exact aggregates intact
    assert snap["total"] == pytest.approx(4999.5, rel=1e-6)
    assert snap["p50"] == pytest.approx(0.5, abs=0.08)
    assert snap["p99"] == pytest.approx(0.99, abs=0.05)


# ---------------------------------------------------------- kill switch


def test_kill_switch_disables_the_whole_plane():
    with config_override(live_telemetry=False):
        assert flight.ensure_flight() is None
        assert flight.flight_snapshot() is None
        assert watchdog.arm_watchdog(CERT, pipeline="off") is None
        with watchdog.request_scope(64, pipeline="off") as shape:
            assert shape is None
    # nothing moved: no metrics, no sketches, no recorder
    reg = metrics.registry()
    assert "serving.requests" not in reg.counters
    assert streaming.health()["requests"] == 0
    assert flight.flight_recorder() is None


def test_live_config_field_in_ledger_header():
    header = ledger.run_header()
    assert "live_telemetry" in header["config"]
    assert ledger.CONFIG_ENV["live_telemetry"] == "KEYSTONE_LIVE_TELEMETRY"
    assert "conformance" in ledger.KINDS


# ------------------------------------------------------------------ CLI


def test_cli_live_renders_health(capsys):
    streaming.observe_apply("demo", 64, 0.01)
    from keystone_tpu.telemetry.__main__ import main as cli_main

    assert cli_main(["--live"]) == 0
    out = capsys.readouterr().out
    assert "demo" in out and "p99" in out
    assert cli_main(["--live", "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["latency"][0]["pipeline"] == "demo"
