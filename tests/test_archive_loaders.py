"""Loader tests against CHECKED-IN miniature archives (the analog of
ImageNetLoaderSuite.scala:1-40 / VOCLoaderSuite.scala reading real tars
from test resources). Fixtures are built from the two public test
images by resources/make_archive_fixtures.py and committed."""

import os

import numpy as np
import pytest

from keystone_tpu.loaders.image_loaders import (
    imagenet_loader,
    load_images_from_tar,
    voc_loader,
)

RES = os.path.join(os.path.dirname(__file__), "resources")
IMAGENET_TAR = os.path.join(RES, "imagenet_mini.tar")
VOC_TAR = os.path.join(RES, "voc_mini.tar")
VOC_CSV = os.path.join(RES, "voc_mini_labels.csv")

LABELS_MAP = {"n01234567": 0, "n07654321": 1}


def test_imagenet_loader_reads_archive_and_joins_labels():
    ds = imagenet_loader(IMAGENET_TAR, LABELS_MAP)
    items = ds.items
    # 5 entries in the tar; the n99999999 synset is not in the labels
    # map and must be dropped (ImageNetLoader joins on the synset)
    assert len(items) == 4
    labels = [it.label for it in items]
    assert sorted(labels) == [0, 0, 1, 1]
    for it in items:
        assert it.image.shape == (64, 64, 3)
        assert it.image.dtype == np.float32
        assert 0.0 <= it.image.min() and it.image.max() <= 255.0
        assert it.image.std() > 1.0  # decoded real pixels, not zeros


def test_imagenet_loader_max_images():
    ds = imagenet_loader(IMAGENET_TAR, LABELS_MAP, max_images=2)
    assert len(ds.items) == 2


def test_voc_loader_multilabel_join():
    ds = voc_loader(VOC_TAR, VOC_CSV)
    items = ds.items
    # 4 entries; 000009.jpg has no csv row and is skipped
    assert len(items) == 3
    by_name = {os.path.basename(it.filename): it for it in items}
    assert sorted(by_name) == ["000001.jpg", "000002.jpg", "000003.jpg"]
    assert sorted(by_name["000001.jpg"].labels) == [3, 11]  # multi-label
    assert by_name["000002.jpg"].labels == [0]
    assert by_name["000003.jpg"].labels == [19]
    for it in items:
        assert it.image.shape == (64, 64, 3)


def test_native_fast_path_matches_tarfile_fallback(monkeypatch):
    """The native tar-index + threaded JPEG decode path must produce the
    same (name, label) rows and numerically close pixels vs tarfile+PIL
    (decoders may round differently)."""
    from keystone_tpu.utils import native_io

    if not native_io.available():
        pytest.skip("native io library not built")

    def label_fn(name):
        return {"n01234567": 0, "n07654321": 1}.get(name.split("/")[0])

    native_rows = load_images_from_tar(IMAGENET_TAR, label_fn)
    monkeypatch.setattr(native_io, "available", lambda: False)
    pil_rows = load_images_from_tar(IMAGENET_TAR, label_fn)
    assert [(n, l) for n, _, l in native_rows] == [(n, l) for n, _, l in pil_rows]
    for (_, a, _), (_, b, _) in zip(native_rows, pil_rows):
        assert a.shape == b.shape
        assert np.mean(np.abs(a - b)) < 2.0  # IDCT rounding differences
