"""Dense descriptor extractor tests: shapes, determinism, invariance
properties (the reference checks exact VLFeat descriptor counts; we
check the analogous static grid counts and SIFT normalization bounds)."""

import numpy as np

from keystone_tpu import Dataset, HostDataset
from keystone_tpu.nodes.images import (
    DaisyExtractor,
    HogExtractor,
    LCSExtractor,
    SIFTExtractor,
)


def gray_image(h=64, w=64, seed=0):
    rng = np.random.default_rng(seed)
    return rng.uniform(0, 1, size=(h, w, 1)).astype(np.float32)


def test_sift_shapes_and_norm():
    img = gray_image()
    ext = SIFTExtractor(step=4, bin_size=4, num_scales=2)
    out = np.asarray(ext.apply(img))
    assert out.shape[1] == 128
    # vl_dsift frame geometry (VLFeat.cxx:77-99): frames span
    # [off, dim-1] with footprint 3*binSize+1; scale_step=1 default:
    # s=0: bs=4 step=4 off=5 -> ((63-13+1-5)//4+1)^2 = 12^2
    # s=1: bs=6 step=5 off=2 -> ((63-19+1-2)//5+1)^2 = 9^2
    assert out.shape[0] == 12 * 12 + 9 * 9
    # vlfeat short scaling: quantized entries in [0, 255], unit descriptor
    # x512 -> L2 norm a bit under 512 after flooring
    norms = np.linalg.norm(out, axis=1)
    assert np.all(norms < 513.0)
    assert np.median(norms) > 400.0
    assert out.max() <= 255.0 and out.min() >= 0.0


def test_sift_deterministic_and_batch_parity():
    img = gray_image(seed=1)
    ext = SIFTExtractor(step=8, bin_size=4, num_scales=1)
    a = np.asarray(ext.apply(img))
    b = np.asarray(ext.apply(img))
    np.testing.assert_array_equal(a, b)
    batch = ext.apply_batch(Dataset(np.stack([img, img]))).numpy()
    np.testing.assert_allclose(batch[0], a, atol=1e-4)


def test_sift_host_dataset_path():
    out = SIFTExtractor(step=8, num_scales=1).apply_batch(
        HostDataset([gray_image(seed=2), gray_image(seed=3)])
    )
    assert len(out) == 2
    assert out.items[0].shape[1] == 128


def test_lcs_shapes():
    rng = np.random.default_rng(5)
    img = rng.uniform(0, 1, size=(48, 48, 3)).astype(np.float32)
    out = np.asarray(LCSExtractor(stride=4, subpatch_size=6, subpatches=4).apply(img))
    # span 24 -> 7x7 grid at stride 4; dim = 2 stats * 16 subpatches * 3 ch
    assert out.shape == (49, 96)
    assert np.isfinite(out).all()


def test_hog_shapes():
    rng = np.random.default_rng(6)
    img = rng.uniform(0, 1, size=(64, 64, 3)).astype(np.float32)
    out = np.asarray(HogExtractor(cell_size=8).apply(img))
    # 8x8 cells -> 6x6 interior feature cells, 32 features each
    assert out.shape == (6 * 6, 32)
    assert np.isfinite(out).all()
    # orientation features bounded by 0.4 (0.5·Σ of four ≤0.2 norms);
    # the 4 texture-energy features can reach ~0.85
    assert out[:, :27].max() <= 0.4 + 1e-5
    assert out.max() <= 1.0
    # truncation feature is identically zero
    assert np.all(out[:, 31] == 0.0)


def test_daisy_shapes_and_norm():
    img = gray_image(80, 80, seed=7)
    out = np.asarray(DaisyExtractor(stride=8).apply(img))
    # pixelBorder 16 -> keypoints 16..63 step 8 = 6 per axis;
    # dim (1+3*8)*8 = 200
    assert out.shape == (36, 200)
    # each 8-bin histogram is L2-normalized SEPARATELY (the reference's
    # normalize() per getHist call, DaisyExtractor.scala:161-200)
    hists = out.reshape(36, 25, 8)
    norms = np.linalg.norm(hists, axis=-1)
    np.testing.assert_allclose(norms[norms > 1e-6], 1.0, atol=1e-4)


def test_hog_orientation_selectivity():
    """Known-value property: a pure vertical-edge grating has all its
    gradient energy in one orientation; HOG's dominant orientation bin
    must carry (nearly) all the per-cell contrast energy (the reference
    validates descriptors against known images,
    test/scala/nodes/images/HogExtractorSuite)."""
    # vertical stripes -> horizontal gradients, constant orientation
    x = np.arange(64, dtype=np.float32)
    img = np.tile(np.sin(x * np.pi / 4)[None, :, None], (64, 1, 3)) * 0.5 + 0.5
    out = np.asarray(HogExtractor(cell_size=8).apply(img))
    # contrast-insensitive block (features 18..27): one dominant bin
    interior = out.reshape(6, 6, 32)[1:5, 1:5].reshape(-1, 32)
    ci = interior[:, 18:27]
    dominant = ci.max(axis=1)
    total = ci.sum(axis=1)
    assert np.all(dominant / np.maximum(total, 1e-8) > 0.45)
    # rotating the image 90 deg moves the energy to a different bin
    out_r = np.asarray(HogExtractor(cell_size=8).apply(img.transpose(1, 0, 2)))
    ci_r = out_r.reshape(6, 6, 32)[1:5, 1:5].reshape(-1, 32)[:, 18:27]
    assert not np.allclose(ci.mean(axis=0).argmax(), ci_r.mean(axis=0).argmax())


def test_daisy_constant_image_interior_is_zero():
    """A constant image has zero gradients in the interior, so interior
    histograms are zeroed by the norm threshold (normalization must not
    divide by zero). Near the borders the reference's zero-padding conv2D
    manufactures gradient energy, so only keypoints whose every sample +
    blur support stays interior are asserted zero."""
    img = np.full((96, 96, 3), 0.5, np.float32)
    out = np.asarray(DaisyExtractor().apply(img))
    assert np.isfinite(out).all()
    hists = out.reshape(-1, 25, 8)
    norms = np.linalg.norm(hists, axis=-1)
    # every histogram is either zeroed or exactly unit-norm
    assert ((norms < 1e-6) | (np.abs(norms - 1.0) < 1e-4)).all()
    # central keypoint: samples within +-7, blur support 13+3 taps, all
    # far from the zero-padded border -> all 25 histograms zero
    n = int(round(np.sqrt(hists.shape[0])))
    center = hists.reshape(n, n, 25, 8)[n // 2, n // 2]
    assert np.abs(center).max() < 1e-6


def test_lcs_constant_image_stats():
    """LCS on a constant image: sub-patch means equal the constant and
    stds are zero (LCSExtractor.scala:25-130 semantics)."""
    img = np.full((64, 64, 3), 0.25, np.float32)
    out = np.asarray(LCSExtractor().apply(img))
    assert np.isfinite(out).all()
    # keypoints at the image boundary see zero-padded sub-patches, so
    # check an interior keypoint: all means == constant, all stds == 0
    g = int(round(np.sqrt(out.shape[0])))
    center = out.reshape(g, g, -1)[g // 2, g // 2]
    nz = center[np.abs(center) > 1e-6]
    assert np.allclose(nz, 0.25, atol=1e-5)
    assert (np.abs(center) > 1e-6).sum() == center.size // 2  # stds are 0


def test_host_batch_dispatch_scales_with_buckets(monkeypatch):
    """Variable-size HostDataset images are bucketed by shape: one
    vmapped dispatch per bucket, not per item (VERDICT r1 item 8)."""
    import numpy as np

    from keystone_tpu.data.dataset import HostDataset
    from keystone_tpu.nodes.images.descriptors import LCSExtractor
    from keystone_tpu.utils import batching

    rng = np.random.default_rng(0)
    items = [rng.uniform(size=(40, 40, 3)).astype(np.float32) for _ in range(4)]
    items += [rng.uniform(size=(40, 56, 3)).astype(np.float32) for _ in range(3)]

    calls = []
    orig = batching.map_host_batched

    def counting(its, batch_fn, chunk=256):
        def bf(stacked):
            calls.append(stacked.shape)
            return batch_fn(stacked)

        return orig(its, bf, chunk)

    monkeypatch.setattr(batching, "map_host_batched", counting)
    ext = LCSExtractor(stride=8)
    out = ext.apply_batch(HostDataset(items))
    assert len(calls) == 2, calls  # two shape buckets, seven items
    # shape-stable dispatch pads tiny buckets up the power-of-two ladder
    # (3 → 4), so BOTH buckets execute the same leading dim — one
    # compiled program shape instead of one per item count
    assert {c[0] for c in calls} == {4}, calls
    # order-preserving and identical to the per-item path
    for got, img in zip(out.items, items):
        np.testing.assert_allclose(got, np.asarray(ext.apply(img)), atol=1e-5)

    # with padding off the raw bucket sizes dispatch as-is
    from keystone_tpu.workflow.env import config_override

    calls.clear()
    with config_override(pad_chunks=False):
        out2 = ext.apply_batch(HostDataset(items))
    assert {c[0] for c in calls} == {4, 3}, calls
    for a, b in zip(out.items, out2.items):
        np.testing.assert_allclose(a, b, atol=1e-6)
