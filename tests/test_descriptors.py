"""Dense descriptor extractor tests: shapes, determinism, invariance
properties (the reference checks exact VLFeat descriptor counts; we
check the analogous static grid counts and SIFT normalization bounds)."""

import numpy as np

from keystone_tpu import Dataset, HostDataset
from keystone_tpu.nodes.images import (
    DaisyExtractor,
    HogExtractor,
    LCSExtractor,
    SIFTExtractor,
)


def gray_image(h=64, w=64, seed=0):
    rng = np.random.default_rng(seed)
    return rng.uniform(0, 1, size=(h, w, 1)).astype(np.float32)


def test_sift_shapes_and_norm():
    img = gray_image()
    ext = SIFTExtractor(step=4, bin_size=4, num_scales=2)
    out = np.asarray(ext.apply(img))
    assert out.shape[1] == 128
    # per-scale counts: span=16 -> 13x13; span=32 -> 9x9 at step 4
    assert out.shape[0] == 13 * 13 + 9 * 9
    # vlfeat scaling: L2 norm of each descriptor is 512 (before clamping loss)
    norms = np.linalg.norm(out, axis=1)
    assert np.all(norms < 513.0)
    assert np.median(norms) > 400.0


def test_sift_deterministic_and_batch_parity():
    img = gray_image(seed=1)
    ext = SIFTExtractor(step=8, bin_size=4, num_scales=1)
    a = np.asarray(ext.apply(img))
    b = np.asarray(ext.apply(img))
    np.testing.assert_array_equal(a, b)
    batch = ext.apply_batch(Dataset(np.stack([img, img]))).numpy()
    np.testing.assert_allclose(batch[0], a, atol=1e-4)


def test_sift_host_dataset_path():
    out = SIFTExtractor(step=8, num_scales=1).apply_batch(
        HostDataset([gray_image(seed=2), gray_image(seed=3)])
    )
    assert len(out) == 2
    assert out.items[0].shape[1] == 128


def test_lcs_shapes():
    rng = np.random.default_rng(5)
    img = rng.uniform(0, 1, size=(48, 48, 3)).astype(np.float32)
    out = np.asarray(LCSExtractor(stride=4, subpatch_size=6, subpatches=4).apply(img))
    # span 24 -> 7x7 grid at stride 4; dim = 2 stats * 16 subpatches * 3 ch
    assert out.shape == (49, 96)
    assert np.isfinite(out).all()


def test_hog_shapes():
    rng = np.random.default_rng(6)
    img = rng.uniform(0, 1, size=(64, 64, 3)).astype(np.float32)
    out = np.asarray(HogExtractor(cell_size=8).apply(img))
    assert out.shape == (8 * 8, 31)
    assert np.isfinite(out).all()
    # orientation features bounded by 0.4 (0.5·Σ of four ≤0.2 norms);
    # the 4 texture-energy features can reach ~0.85
    assert out[:, :27].max() <= 0.4 + 1e-5
    assert out.max() <= 1.0


def test_daisy_shapes_and_norm():
    img = gray_image(80, 80, seed=7)
    out = np.asarray(DaisyExtractor(stride=8, radius=15).apply(img))
    # margin 16 -> (80-32)//8+1 = 7 per axis; dim (1+3*8)*8 = 200
    assert out.shape == (49, 200)
    norms = np.linalg.norm(out, axis=1)
    np.testing.assert_allclose(norms[norms > 1e-6], 1.0, atol=1e-4)
