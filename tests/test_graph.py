"""Graph data-structure tests (model: reference GraphSuite.scala:41-711)."""

import pytest

from keystone_tpu.workflow import (
    DatasetOperator,
    Graph,
    NodeId,
    SinkId,
    SourceId,
    analysis,
)
from keystone_tpu.workflow.pipeline import Transformer


def op(name="op"):
    return Transformer.from_function(lambda x: x, name=name)


def build_chain():
    """source -> a -> b -> sink"""
    g = Graph()
    g, s = g.add_source()
    g, a = g.add_node(op("a"), [s])
    g, b = g.add_node(op("b"), [a])
    g, k = g.add_sink(b)
    return g, s, a, b, k


def test_add_node_and_views():
    g, s, a, b, k = build_chain()
    assert g.sources == {s}
    assert g.nodes == {a, b}
    assert g.sink_ids == {k}
    assert g.get_dependencies(b) == (a,)
    assert g.get_sink_dependency(k) == b


def test_add_node_rejects_missing_dep():
    g = Graph()
    with pytest.raises(ValueError):
        g.add_node(op(), [NodeId(42)])
    with pytest.raises(ValueError):
        g.add_node(op(), [SourceId(7)])


def test_add_sink_rejects_missing_dep():
    g = Graph()
    with pytest.raises(ValueError):
        g.add_sink(NodeId(0))


def test_remove_node_with_users_fails():
    g, s, a, b, k = build_chain()
    with pytest.raises(ValueError):
        g.remove_node(a)  # b depends on a
    with pytest.raises(ValueError):
        g.remove_node(b)  # sink depends on b


def test_remove_leaf_node():
    g, s, a, b, k = build_chain()
    g = g.remove_sink(k)
    g = g.remove_node(b)
    assert g.nodes == {a}


def test_remove_source_with_users_fails():
    g, s, a, b, k = build_chain()
    with pytest.raises(ValueError):
        g.remove_source(s)


def test_set_operator_and_dependencies():
    g, s, a, b, k = build_chain()
    new_op = op("c")
    g2 = g.set_operator(b, new_op)
    assert g2.get_operator(b) is new_op
    assert g.get_operator(b) is not new_op  # immutability
    g3 = g2.set_dependencies(b, [s])
    assert g3.get_dependencies(b) == (s,)
    with pytest.raises(ValueError):
        g.set_operator(NodeId(99), new_op)


def test_replace_dependency():
    g, s, a, b, k = build_chain()
    g2 = g.replace_dependency(b, a)  # sink now points at a
    assert g2.get_sink_dependency(k) == a


def test_immutability_of_mutators():
    g, s, a, b, k = build_chain()
    g.add_node(op(), [a])
    assert g.nodes == {a, b}  # original untouched


def test_add_graph_remaps_ids():
    g1, s1, a1, b1, k1 = build_chain()
    g2, s2, a2, b2, k2 = build_chain()
    merged, smap, kmap = g1.add_graph(g2)
    assert len(merged.nodes) == 4
    assert len(merged.sources) == 2
    assert len(merged.sink_ids) == 2
    assert smap[s2] != s1
    # remapped deps preserved
    new_b = kmap[k2]
    dep = merged.get_sink_dependency(new_b)
    assert merged.get_dependencies(dep)[0] in merged.nodes


def test_connect_graph_splices_source():
    g1, s1, a1, b1, k1 = build_chain()
    g2, s2, a2, b2, k2 = build_chain()
    merged, kmap = g1.connect_graph(g2, {s2: b1})
    # g2's source is gone; its first node now depends on g1's b
    assert len(merged.sources) == 1
    spliced_tail = merged.get_sink_dependency(kmap[k2])
    head = merged.get_dependencies(spliced_tail)[0]
    assert merged.get_dependencies(head) == (b1,)


def test_replace_nodes():
    g, s, a, b, k = build_chain()
    # replacement: one node consuming one source
    r = Graph()
    r, rs = r.add_source()
    r, rn = r.add_node(op("r"), [rs])
    r, rk = r.add_sink(rn)
    g2 = g.replace_nodes([b], r, {rs: a}, {b: rk})
    assert b not in g2.nodes
    tail = g2.get_sink_dependency(k)
    assert g2.get_operator(tail).label == "r"
    assert g2.get_dependencies(tail) == (a,)


def test_linearize_deterministic_topo_order():
    g, s, a, b, k = build_chain()
    order = analysis.linearize(g, k)
    assert order.index(s) < order.index(a) < order.index(b) < order.index(k)


def test_ancestors_descendants_children_parents():
    g, s, a, b, k = build_chain()
    assert analysis.ancestors(g, k) == {s, a, b}
    assert analysis.descendants(g, s) == {a, b, k}
    assert analysis.children(g, a) == {b}
    assert analysis.parents(g, b) == [a]


def test_to_dot_contains_all_vertices():
    g, s, a, b, k = build_chain()
    dot = g.to_dot()
    assert f"source_{s.id}" in dot and f"sink_{k.id}" in dot


# ---- mutator failure sweep (reference GraphSuite.scala exercises every
# ---- `require` branch of Graph.scala:110-434; each has a ValueError here)


def test_add_node_rejects_missing_source_dep():
    g = Graph()
    with pytest.raises(ValueError):
        g.add_node(op(), [SourceId(99)])


def test_add_node_rejects_bad_dep_type():
    g = Graph()
    with pytest.raises(TypeError):
        g.add_node(op(), [SinkId(0)])


def test_set_operator_missing_node():
    g, s, a, b, k = build_chain()
    with pytest.raises(ValueError):
        g.set_operator(NodeId(99), op())


def test_set_dependencies_missing_node():
    g, s, a, b, k = build_chain()
    with pytest.raises(ValueError):
        g.set_dependencies(NodeId(99), [s])


def test_set_dependencies_missing_dep():
    g, s, a, b, k = build_chain()
    with pytest.raises(ValueError):
        g.set_dependencies(a, [NodeId(99)])


def test_set_sink_dependency_missing_sink():
    g, s, a, b, k = build_chain()
    with pytest.raises(ValueError):
        g.set_sink_dependency(SinkId(99), a)


def test_set_sink_dependency_missing_dep():
    g, s, a, b, k = build_chain()
    with pytest.raises(ValueError):
        g.set_sink_dependency(k, NodeId(99))


def test_remove_node_missing():
    g, s, a, b, k = build_chain()
    with pytest.raises(ValueError):
        g.remove_node(NodeId(99))


def test_remove_source_missing():
    g, s, a, b, k = build_chain()
    with pytest.raises(ValueError):
        g.remove_source(SourceId(99))


def test_remove_sink_missing():
    g, s, a, b, k = build_chain()
    with pytest.raises(ValueError):
        g.remove_sink(SinkId(99))


def test_remove_sink_then_node_succeeds():
    g, s, a, b, k = build_chain()
    g = g.remove_sink(k)
    g = g.remove_node(b)
    assert b not in g.nodes and k not in g.sink_ids


def test_replace_dependency_missing_new():
    g, s, a, b, k = build_chain()
    with pytest.raises(ValueError):
        g.replace_dependency(a, NodeId(99))


def test_connect_graph_rejects_nonsource_splice_key():
    g, s, a, b, k = build_chain()
    other = Graph()
    other, os_ = other.add_source()
    other, on = other.add_node(op(), [os_])
    other, ok_ = other.add_sink(on)
    with pytest.raises(ValueError):
        g.connect_graph(other, {SourceId(57): a})


def test_replace_nodes_rejects_empty_set():
    g, s, a, b, k = build_chain()
    repl = Graph()
    repl, rs = repl.add_source()
    repl, rn = repl.add_node(op(), [rs])
    repl, rk = repl.add_sink(rn)
    with pytest.raises(ValueError):
        g.replace_nodes([], repl, {rs: s}, {})


def test_replace_nodes_rejects_missing_node():
    g, s, a, b, k = build_chain()
    repl = Graph()
    repl, rs = repl.add_source()
    repl, rn = repl.add_node(op(), [rs])
    repl, rk = repl.add_sink(rn)
    with pytest.raises(ValueError):
        g.replace_nodes([NodeId(99)], repl, {rs: s}, {NodeId(99): rk})


def test_replace_nodes_rejects_sink_splice_mismatch():
    g, s, a, b, k = build_chain()
    repl = Graph()
    repl, rs = repl.add_source()
    repl, rn = repl.add_node(op(), [rs])
    repl, rk = repl.add_sink(rn)
    # sink splice covers b but nodes_to_remove is {a}
    with pytest.raises(ValueError):
        g.replace_nodes([a], repl, {rs: s}, {b: rk})


def test_replace_nodes_rejects_removed_splice_target():
    g, s, a, b, k = build_chain()
    repl = Graph()
    repl, rs = repl.add_source()
    repl, rn = repl.add_node(op(), [rs])
    repl, rk = repl.add_sink(rn)
    # source splice targets a, which is being removed
    with pytest.raises(ValueError):
        g.replace_nodes([a, b], repl, {rs: a}, {a: rk, b: rk})


# ---- GraphSuite.scala:41-110 accessor failure cases -----------------------


def test_get_operator_missing_node_raises():
    g, s, a, b, k = build_chain()
    with pytest.raises(KeyError):
        g.get_operator(NodeId(99))


def test_get_dependencies_missing_node_raises():
    g, s, a, b, k = build_chain()
    with pytest.raises(KeyError):
        g.get_dependencies(NodeId(99))


def test_get_sink_dependency_missing_sink_raises():
    g, s, a, b, k = build_chain()
    with pytest.raises(KeyError):
        g.get_sink_dependency(SinkId(99))


# ---- GraphSuite.scala:625-644 connectGraph argument checks ----------------


def test_connect_graph_rejects_dangling_splice_target():
    """Splice values must be vertices of self (the reference rejects
    splice maps naming sinks/sources that do not exist)."""
    g, s, a, b, k = build_chain()
    other = Graph()
    other, os_ = other.add_source()
    other, on = other.add_node(op(), [os_])
    other, ok_ = other.add_sink(on)
    with pytest.raises(ValueError):
        g.connect_graph(other, {os_: NodeId(99)})
    with pytest.raises(ValueError):
        g.connect_graph(other, {os_: SourceId(99)})


def test_connect_graph_partial_splice_keeps_source():
    """Unspliced sources of `other` survive as sources of the result —
    connectGraph (unlike replaceNodes) does not require binding all."""
    g, s, a, b, k = build_chain()
    other = Graph()
    other, o1 = other.add_source()
    other, o2 = other.add_source()
    other, on = other.add_node(op(), [o1, o2])
    other, ok_ = other.add_sink(on)
    g2, sink_map = g.connect_graph(other, {o1: b})
    assert len(g2.sources) == 2  # original s + remapped unspliced o2


# ---- GraphSuite.scala:711-790 replaceNodes argument checks ----------------


def _repl_two_sources():
    repl = Graph()
    repl, r1 = repl.add_source()
    repl, r2 = repl.add_source()
    repl, rn = repl.add_node(op(), [r1, r2])
    repl, rk = repl.add_sink(rn)
    return repl, r1, r2, rn, rk


def test_replace_nodes_rejects_unbound_replacement_source():
    """Must attach ALL of the replacement's sources."""
    g, s, a, b, k = build_chain()
    repl, r1, r2, rn, rk = _repl_two_sources()
    with pytest.raises(ValueError):
        g.replace_nodes([b], repl, {r1: s}, {b: rk})  # r2 unbound


def test_replace_nodes_rejects_unattached_replacement_sink():
    """Must attach ALL of the replacement's sinks."""
    g, s, a, b, k = build_chain()
    repl = Graph()
    repl, rs = repl.add_source()
    repl, rn = repl.add_node(op(), [rs])
    repl, rk1 = repl.add_sink(rn)
    repl, rk2 = repl.add_sink(rn)  # second sink, never attached
    with pytest.raises(ValueError):
        g.replace_nodes([b], repl, {rs: s}, {b: rk1})


def test_replace_nodes_rejects_dangling_source_splice_target():
    """May only connect replacement sources to existing vertices
    (reference: SourceId(-42) case)."""
    g, s, a, b, k = build_chain()
    repl = Graph()
    repl, rs = repl.add_source()
    repl, rn = repl.add_node(op(), [rs])
    repl, rk = repl.add_sink(rn)
    with pytest.raises(ValueError):
        g.replace_nodes([b], repl, {rs: SourceId(-42)}, {b: rk})
    with pytest.raises(ValueError):
        g.replace_nodes([b], repl, {rs: NodeId(99)}, {b: rk})


def test_replace_nodes_happy_path_two_nodes():
    """Positive case at the same shape as the failure matrix: replace the
    {a, b} chain with a single-node subgraph; sink rewires to it."""
    g, s, a, b, k = build_chain()
    repl = Graph()
    repl, rs = repl.add_source()
    repl, rn = repl.add_node(op("r"), [rs])
    repl, rk = repl.add_sink(rn)
    g2 = g.replace_nodes([a, b], repl, {rs: s}, {a: rk, b: rk})
    assert a not in g2.operators and b not in g2.operators
    new_dep = g2.get_sink_dependency(k)
    assert isinstance(new_dep, NodeId) and new_dep in g2.operators
    assert g2.get_dependencies(new_dep) == (s,)


def test_remove_node_with_sink_user_still_fails():
    """A node referenced only by a sink still counts as having
    dependents (GraphSuite removeNode)."""
    g, s, a, b, k = build_chain()
    with pytest.raises(ValueError):
        g.remove_node(b)


# ---- AnalysisUtilsSuite.scala:39-287: topology queries on a diamond -------


def build_diamond():
    """source -> a -> {b, c} -> d(gather) -> sink1; b -> sink2.
    Exercises multi-child, multi-parent, and sink-bearing vertices like
    the reference's 19-node fixture does."""
    g = Graph()
    g, s = g.add_source()
    g, a = g.add_node(op("a"), [s])
    g, b = g.add_node(op("b"), [a])
    g, c = g.add_node(op("c"), [a])
    g, d = g.add_node(op("d"), [b, c])
    g, k1 = g.add_sink(d)
    g, k2 = g.add_sink(b)
    return g, s, a, b, c, d, k1, k2


def test_children_per_vertex_kind():
    g, s, a, b, c, d, k1, k2 = build_diamond()
    assert analysis.children(g, s) == {a}
    assert analysis.children(g, a) == {b, c}
    assert analysis.children(g, b) == {d, k2}  # node AND sink children
    assert analysis.children(g, d) == {k1}
    assert analysis.children(g, k1) == set()  # sinks have no children


def test_parents_per_vertex_kind():
    g, s, a, b, c, d, k1, k2 = build_diamond()
    assert analysis.parents(g, a) == [s]
    assert set(analysis.parents(g, d)) == {b, c}
    assert analysis.parents(g, k1) == [d]  # sink's parent is its dep
    assert analysis.parents(g, k2) == [b]
    assert analysis.parents(g, s) == []  # sources have no parents


def test_descendants_include_sinks():
    g, s, a, b, c, d, k1, k2 = build_diamond()
    assert analysis.descendants(g, s) == {a, b, c, d, k1, k2}
    assert analysis.descendants(g, b) == {d, k1, k2}
    assert analysis.descendants(g, c) == {d, k1}
    assert analysis.descendants(g, d) == {k1}


def test_ancestors_include_sources():
    g, s, a, b, c, d, k1, k2 = build_diamond()
    assert analysis.ancestors(g, k1) == {s, a, b, c, d}
    assert analysis.ancestors(g, k2) == {s, a, b}
    assert analysis.ancestors(g, d) == {s, a, b, c}
    assert analysis.ancestors(g, a) == {s}
    assert analysis.ancestors(g, s) == set()


def test_linearize_respects_dependencies_and_is_deterministic():
    g, s, a, b, c, d, k1, k2 = build_diamond()
    order = analysis.linearize(g)
    pos = {v: i for i, v in enumerate(order)}
    for node in (a, b, c, d):
        for dep in g.get_dependencies(node):
            assert pos[dep] < pos[node]
    assert order == analysis.linearize(g)  # deterministic
    # repeated builds of the same topology linearize identically
    g2 = build_diamond()[0]
    assert [type(v).__name__ for v in analysis.linearize(g2)] == [
        type(v).__name__ for v in order
    ]
