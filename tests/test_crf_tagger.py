"""Linear-chain CRF tagger at corpus scale (VERDICT r4 item 5).

The reference wraps Epic's pretrained broad-coverage CRF taggers
(POSTagger.scala:24-36, NER.scala:20-32). Zero egress rules out model
downloads, so scale comes from the deterministic grammar generator:
these tests train the jitted CRF on a ≥50k-token corpus (≈100× the
bundled mini-corpora the perceptron tests use), hold out a test split,
and require the CRF to beat-or-match the structured perceptron trained
on the same data.
"""

import numpy as np
import pytest

from keystone_tpu.nodes.nlp import (
    LinearChainCRFTagger,
    generate_ner_corpus,
    generate_pos_corpus,
)
from keystone_tpu.nodes.nlp.perceptron_tagger import StructuredPerceptronTagger


def _accuracy(tagger_out, gold):
    n = c = 0
    for pred, g in zip(tagger_out, gold):
        for p, t in zip(pred, g):
            n += 1
            c += p == t
    return c / n


@pytest.fixture(scope="module")
def pos_splits():
    corpus = generate_pos_corpus(4500, seed=0)
    assert sum(len(s) for s in corpus) > 45_000  # ≥100× the 124-line bundle
    return corpus[:4000], corpus[4000:]


@pytest.fixture(scope="module")
def pos_crf(pos_splits):
    train, _ = pos_splits
    return LinearChainCRFTagger(max_iter=50).train(train)


def test_crf_pos_scale_accuracy(pos_splits, pos_crf):
    _, test = pos_splits
    toks = [[w for w, _ in s] for s in test]
    gold = [[t for _, t in s] for s in test]
    acc = _accuracy(pos_crf.predict_batch(toks), gold)
    # the grammar task is learnable but ambiguous (noun/verb homographs,
    # unseen CD numerals in test); near-ceiling accuracy means the model
    # genuinely uses context + shape features
    assert acc > 0.97, acc


def test_crf_beats_or_matches_structured_perceptron(pos_splits, pos_crf):
    """Same train data, same held-out split: exact CRF training must do
    at least as well as the perceptron (the VERDICT r4 quality bar). The
    perceptron gets a smaller slice (its pure-python Viterbi train loop
    is ~100× slower than the CRF's one jitted program — which is the
    point of the TPU-native design)."""
    train, test = pos_splits
    toks = [[w for w, _ in s] for s in test]
    gold = [[t for _, t in s] for s in test]
    crf_acc = _accuracy(pos_crf.predict_batch(toks), gold)

    perc = StructuredPerceptronTagger().train(train[:600], n_iter=3)
    perc_acc = _accuracy([perc(t) for t in toks], gold)
    small_crf = LinearChainCRFTagger(max_iter=50).train(train[:600])
    small_crf_acc = _accuracy(small_crf.predict_batch(toks), gold)
    # like-for-like at 600 sentences, and full-data CRF beats both
    assert small_crf_acc >= perc_acc - 0.005, (small_crf_acc, perc_acc)
    assert crf_acc >= max(perc_acc, small_crf_acc), (
        crf_acc, perc_acc, small_crf_acc)


def test_crf_ner_bio(pos_splits):
    corpus = generate_ner_corpus(2500, seed=1)
    train, test = corpus[:2200], corpus[2200:]
    crf = LinearChainCRFTagger(max_iter=50).train(train)
    toks = [[w for w, _ in s] for s in test]
    gold = [[t for _, t in s] for s in test]
    preds = crf.predict_batch(toks)
    assert _accuracy(preds, gold) > 0.97
    # BIO structure: I-X never follows O or start in predictions —
    # transition weights must encode the scheme without hand-coded
    # constraints
    for pred in preds:
        prev = "O"
        for t in pred:
            if t.startswith("I-"):
                assert prev in (t, "B-" + t[2:]), (prev, t, pred)
            prev = t


def test_crf_decode_throughput(pos_crf, pos_splits):
    """Batched jitted Viterbi must beat the host perceptron's per-token
    python Viterbi loop on the same machine (relative bound — absolute
    numbers go in PERF.md from the live bench)."""
    import time

    train, test = pos_splits
    toks = [[w for w, _ in s] for s in test]
    n = sum(len(t) for t in toks)
    pos_crf.predict_batch(toks)  # warm/compile
    t0 = time.perf_counter()
    pos_crf.predict_batch(toks)
    crf_rate = n / (time.perf_counter() - t0)

    perc = StructuredPerceptronTagger().train(train[:100], n_iter=1)
    sub = toks[:50]
    n_sub = sum(len(t) for t in sub)
    t0 = time.perf_counter()
    for t in sub:
        perc(t)
    perc_rate = n_sub / (time.perf_counter() - t0)
    assert crf_rate > 3 * perc_rate, (crf_rate, perc_rate)


def test_crf_save_load_roundtrip(tmp_path, pos_crf):
    path = str(tmp_path / "crf.npz")
    pos_crf.save(path)
    loaded = LinearChainCRFTagger.load(path)
    sent = ["the", "company", "reported", "a", "strong", "profit", "."]
    assert loaded(sent) == pos_crf(sent)
    assert loaded.tags == pos_crf.tags


def test_crf_empty_and_single(pos_crf):
    assert pos_crf.predict([]) == []
    out = pos_crf.predict(["the"])
    assert len(out) == 1 and out[0] in pos_crf.tags


def test_postagger_crf_hook():
    """POSTagger/NER integrate the CRF via the same model= hook as the
    perceptron (annotators.py crf_tagger trains once per process)."""
    from keystone_tpu.nodes.nlp import POSTagger
    from keystone_tpu.nodes.nlp.annotators import crf_tagger

    tagger = POSTagger(model=crf_tagger("pos", n_sentences=300, max_iter=25))
    pairs = tagger.apply(["the", "manager", "approved", "the", "plan", "."])
    assert [w for w, _ in pairs] == ["the", "manager", "approved", "the",
                                     "plan", "."]
    tags = [t for _, t in pairs]
    assert tags[0] == "DT" and tags[1] == "NN"
