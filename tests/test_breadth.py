"""Tests for the breadth wave: weighted solvers, kernel methods,
classifiers, NLP stack, sparse features, MAP/augmented evaluators."""

import os

import numpy as np
import pytest

from keystone_tpu import Dataset, HostDataset
from keystone_tpu.evaluation import (
    MulticlassClassifierEvaluator,
    AugmentedExamplesEvaluator,
    MeanAveragePrecisionEvaluator,
)
from keystone_tpu.nodes.learning import (
    BlockWeightedLeastSquaresEstimator,
    GaussianKernelTransformer,
    KernelRidgeRegression,
    LinearDiscriminantAnalysis,
    LinearMapEstimator,
    LogisticRegressionEstimator,
    NaiveBayesEstimator,
    PerClassWeightedLeastSquares,
)
from keystone_tpu.nodes.nlp import (
    NGramsHashingTF,
    HashingTF,
    NaiveBitPackIndexer,
    NGramsCounts,
    NGramsFeaturizer,
    StupidBackoffEstimator,
    Tokenizer,
    WordFrequencyEncoder,
)
from keystone_tpu.nodes.util import (
    AllSparseFeatures,
    ClassLabelIndicatorsFromInt,
    CommonSparseFeatures,
)
from keystone_tpu.nodes.nlp.text import TermFrequency


# ------------------------------------------------------------- weighted LS


def test_bwls_mixture_zero_equals_unweighted():
    """mixtureWeight=0 → every class uses uniform 1/n weights → matches
    plain ridge (cross-implementation agreement,
    BlockWeightedLeastSquaresSuite.scala:115)."""
    rng = np.random.default_rng(0)
    n, d, k = 160, 12, 3
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.integers(0, k, n)
    Y = (2.0 * np.eye(k, dtype=np.float32)[y] - 1.0)
    lam = 1.0
    bw = BlockWeightedLeastSquaresEstimator(d, 12, lam, mixture_weight=0.0).fit(
        Dataset(X), Dataset(Y)
    )
    # unweighted ridge on 1/n-scaled objective: (XᵀX/n + λI) W = XᵀYc/n
    xm, ym = X.mean(0), Y.mean(0)
    Xc, Yc = X - xm, Y - ym
    Wref = np.linalg.solve(Xc.T @ Xc / n + lam * np.eye(d), Xc.T @ Yc / n)
    np.testing.assert_allclose(np.asarray(bw.W), Wref, atol=2e-2, rtol=5e-2)


def test_bwls_zero_gradient():
    """Weighted normal equations hold at the solution (the reference's
    zero-gradient check, BlockWeightedLeastSquaresSuite.scala:142-166)."""
    rng = np.random.default_rng(1)
    n, d, k = 120, 10, 3
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.integers(0, k, n)
    Y = (2.0 * np.eye(k, dtype=np.float32)[y] - 1.0)
    lam, mw = 0.5, 0.7
    model = BlockWeightedLeastSquaresEstimator(5, 25, lam, mw).fit(
        Dataset(X), Dataset(Y)
    )
    W = np.asarray(model.W)
    b = np.asarray(model.b)
    for c in range(k):
        member = (Y[:, c] > 0).astype(np.float64)
        wts = mw * member / member.sum() + (1 - mw) / n
        resid = X @ W[:, c] + b[c] - Y[:, c]
        grad = X.T @ (wts * resid) + lam * W[:, c]
        assert np.abs(grad).max() < 5e-3, f"class {c}"


def test_per_class_weighted_delegates():
    rng = np.random.default_rng(2)
    X = rng.normal(size=(64, 6)).astype(np.float32)
    y = rng.integers(0, 2, 64)
    Y = 2.0 * np.eye(2, dtype=np.float32)[y] - 1.0
    model = PerClassWeightedLeastSquares(0.1, 0.5).fit(Dataset(X), Dataset(Y))
    assert np.asarray(model.W).shape == (6, 2)


# ------------------------------------------------------------------ kernels


def test_gaussian_kernel_values():
    X = np.array([[0.0, 0.0], [1.0, 0.0]], np.float32)
    t = GaussianKernelTransformer(X, gamma=0.5)
    K = np.asarray(t.apply_batch(Dataset(X)).numpy())
    np.testing.assert_allclose(np.diag(K), 1.0, atol=1e-5)
    np.testing.assert_allclose(K[0, 1], np.exp(-0.5), atol=1e-5)


def test_krr_learns_xor():
    """XOR learnability (KernelModelSuite.scala:13-39)."""
    X = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32)
    X = np.tile(X, (16, 1)) + 0.05 * np.random.default_rng(3).normal(
        size=(64, 2)
    ).astype(np.float32)
    y = (np.round(X[:, 0]) != np.round(X[:, 1])).astype(int)
    Y = 2.0 * np.eye(2, dtype=np.float32)[y] - 1.0
    model = KernelRidgeRegression(gamma=2.0, lam=0.01, block_size=16, num_epochs=4).fit(
        Dataset(X), Dataset(Y)
    )
    preds = np.argmax(model.apply_batch(Dataset(X)).numpy(), axis=1)
    assert (preds == y).mean() > 0.95


def test_krr_blocked_equals_unblocked():
    """blocked == unblocked (KernelModelSuite.scala:29-39)."""
    rng = np.random.default_rng(4)
    X = rng.normal(size=(48, 3)).astype(np.float32)
    Y = rng.normal(size=(48, 2)).astype(np.float32)
    full = KernelRidgeRegression(1.0, 0.5, block_size=48, num_epochs=8).fit(
        Dataset(X), Dataset(Y)
    )
    blocked = KernelRidgeRegression(1.0, 0.5, block_size=12, num_epochs=8).fit(
        Dataset(X), Dataset(Y)
    )
    pred_f = full.apply_batch(Dataset(X)).numpy()
    pred_b = blocked.apply_batch(Dataset(X)).numpy()
    np.testing.assert_allclose(pred_f, pred_b, atol=5e-2)


# -------------------------------------------------------------- classifiers


def test_naive_bayes_separates_counts():
    X = np.array(
        [[5, 0, 1], [4, 1, 0], [0, 5, 1], [1, 4, 0]], np.float32
    )
    y = np.array([0, 0, 1, 1], np.int32)
    model = NaiveBayesEstimator(2).fit(Dataset(X), Dataset(y))
    scores = model.apply_batch(Dataset(X)).numpy()
    assert (np.argmax(scores, axis=1) == y).all()


def test_logistic_regression_linearly_separable():
    rng = np.random.default_rng(5)
    X = np.concatenate(
        [rng.normal(-2, 0.5, (60, 2)), rng.normal(2, 0.5, (60, 2))]
    ).astype(np.float32)
    y = np.array([0] * 60 + [1] * 60, np.int32)
    model = LogisticRegressionEstimator(2, lam=1e-3, num_iters=40).fit(
        Dataset(X), Dataset(y)
    )
    preds = np.asarray(model.apply_batch(Dataset(X)).numpy())
    assert (preds == y).mean() > 0.98


def test_lda_projects_classes_apart():
    rng = np.random.default_rng(6)
    X = np.concatenate(
        [rng.normal([0, 0, 0], 1, (80, 3)), rng.normal([5, 5, 0], 1, (80, 3))]
    ).astype(np.float32)
    y = np.array([0] * 80 + [1] * 80)
    proj = LinearDiscriminantAnalysis(1).fit(Dataset(X), Dataset(y.astype(np.int32)))
    Z = proj.apply_batch(Dataset(X)).numpy().ravel()
    gap = abs(Z[:80].mean() - Z[80:].mean())
    spread = Z[:80].std() + Z[80:].std()
    assert gap > 2 * spread


# ---------------------------------------------------------------------- NLP


def test_tokenize_ngrams_counts():
    tok = Tokenizer()
    toks = tok.apply("the cat sat on the mat")
    ngrams = NGramsFeaturizer([1, 2]).apply(toks)
    assert ("the",) in ngrams and ("the", "cat") in ngrams
    counted = NGramsCounts("default").apply_batch(HostDataset([ngrams, ngrams]))
    pairs = dict(counted.items[0])
    assert pairs[("the",)] == 4  # 2 occurrences x 2 docs


def test_hashing_tf_and_term_frequency():
    v = HashingTF(16).apply(["a", "b", "a"])
    assert v.sum() == 3.0 and v.shape == (16,)
    tf = dict(TermFrequency().apply(["a", "b", "a"]))
    assert tf["a"] == 2


def test_word_frequency_encoder_rank_and_oov():
    enc = WordFrequencyEncoder().fit(
        HostDataset([["a", "b", "a", "c"], ["a", "b"]])
    )
    assert enc.apply(["a", "b", "c", "zzz"]) == [0, 1, 2, -1]


def test_bitpack_indexer_roundtrip():
    idx = NaiveBitPackIndexer()
    packed = idx.pack([3, 7, 11])
    assert idx.unpack(packed) == [3, 7, 11]
    assert idx.unpack(idx.remove_far_left_word(packed)) == [7, 11]


def test_stupid_backoff_scores():
    from collections import Counter

    counts = Counter(
        {("the", "cat"): 2, ("the", "dog"): 1, ("the",): 3, ("cat",): 2, ("dog",): 1}
    )
    model = StupidBackoffEstimator().fit(HostDataset([counts]))
    assert abs(model.score(("the", "cat")) - 2 / 3) < 1e-9
    # unseen bigram backs off to alpha * unigram freq
    assert abs(model.score(("cat", "dog")) - 0.4 * (1 / 6)) < 1e-9


def test_sparse_features_topk_and_vectorize():
    docs = [[("a", 1.0), ("b", 2.0)], [("a", 1.0), ("c", 3.0)], [("a", 1.0)]]
    vec = CommonSparseFeatures(2).fit(HostDataset(docs))
    out = vec.apply_batch(HostDataset(docs))
    assert out.dim == 2
    assert out.matrix.shape == (3, 2)
    all_vec = AllSparseFeatures().fit(HostDataset(docs))
    assert all_vec.apply_batch(HostDataset(docs)).dim == 3


# --------------------------------------------------------------- evaluators


def test_map_evaluator_perfect_and_reverse():
    scores = np.array([[0.9, 0.1], [0.8, 0.2], [0.1, 0.9]])
    actuals = [[0], [0], [1]]
    aps = MeanAveragePrecisionEvaluator(2)(scores, actuals)
    np.testing.assert_allclose(aps, [1.0, 1.0], atol=1e-9)


def test_augmented_examples_evaluator_averages():
    ids = ["a", "a", "b", "b"]
    scores = np.array([[0.6, 0.4], [0.0, 1.0], [0.9, 0.1], [0.8, 0.2]])
    actuals = [1, 1, 0, 0]
    m = AugmentedExamplesEvaluator(2)(ids, scores, actuals)
    # 'a' mean = [0.3, 0.7] -> 1 correct; 'b' -> 0 correct
    assert m.accuracy == 1.0


def test_bitpack_rejects_overflow_and_roundtrips_max():
    from keystone_tpu.nodes.nlp.indexers import MAX_WORD

    idx = NaiveBitPackIndexer()
    assert idx.unpack(idx.pack([MAX_WORD, 0]))[0] == MAX_WORD
    with pytest.raises(ValueError):
        idx.pack([MAX_WORD + 1])


def test_sparse_vectorizer_single_batch_duplicate_parity():
    from keystone_tpu.nodes.util import AllSparseFeatures

    docs = [[("a", 1.0), ("a", 2.0)]]
    vec = AllSparseFeatures().fit(HostDataset(docs))
    single = vec.apply(docs[0]).toarray().ravel()
    batch = vec.apply_batch(HostDataset(docs)).matrix.toarray().ravel()
    np.testing.assert_allclose(single, batch)
    assert single[0] == 3.0


def test_bwls_single_class():
    """Degenerate one-class problem must not NaN or diverge
    (BlockWeightedLeastSquaresSuite.scala:168)."""
    rng = np.random.default_rng(5)
    n, d = 48, 6
    X = rng.normal(size=(n, d)).astype(np.float32)
    Y = np.ones((n, 1), np.float32)  # every example positive, k=1
    m = BlockWeightedLeastSquaresEstimator(d, 4, lam=1.0, mixture_weight=0.3).fit(
        Dataset(X), Dataset(Y)
    )
    W = np.asarray(m.W)
    assert np.all(np.isfinite(W))
    assert np.linalg.norm(W) < 1e3  # bounded, not merely finite
    preds = X @ W + np.asarray(m.b)
    # every training label is +1: the ridge fit must predict positive
    assert np.all(preds > 0)


def test_bwls_nondivisible_blocksize():
    """d % block_size != 0 pads the trailing block
    (BlockWeightedLeastSquaresSuite.scala:188): result must agree with
    the single-block solve."""
    rng = np.random.default_rng(6)
    n, d, k = 160, 10, 3  # block 4 -> blocks of 4,4,2
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.integers(0, k, n)
    Y = 2.0 * np.eye(k, dtype=np.float32)[y] - 1.0
    blocked = BlockWeightedLeastSquaresEstimator(4, 20, 1.0, mixture_weight=0.2).fit(
        Dataset(X), Dataset(Y)
    )
    single = BlockWeightedLeastSquaresEstimator(d, 20, 1.0, mixture_weight=0.2).fit(
        Dataset(X), Dataset(Y)
    )
    np.testing.assert_allclose(
        np.asarray(blocked.W), np.asarray(single.W), atol=5e-2, rtol=5e-2
    )


def test_bwls_count_smaller_than_shards():
    """n < mesh shards leaves some shards all-padding (the reference's
    empty-partition case, BlockWeightedLeastSquaresSuite.scala:72)."""
    rng = np.random.default_rng(7)
    n, d, k = 5, 4, 2  # 8-device mesh -> shards with zero valid rows
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.integers(0, k, n)
    Y = 2.0 * np.eye(k, dtype=np.float32)[y] - 1.0
    m = BlockWeightedLeastSquaresEstimator(d, 2, 1.0, mixture_weight=0.0).fit(
        Dataset(X), Dataset(Y)
    )
    assert np.all(np.isfinite(np.asarray(m.W)))


def test_ngrams_hashing_tf_equivalence():
    """NGramsHashingTF ≡ NGramsFeaturizer ∘ HashingTF — the reference
    proves its rolling hash matches the composed pair
    (NGramsHashingTF.scala:25-118)."""
    tokens = "the quick brown fox jumps over the lazy dog the quick".split()
    fused = NGramsHashingTF([1, 2, 3], 64).apply(tokens)
    composed = HashingTF(64).apply(NGramsFeaturizer([1, 2, 3]).apply(tokens))
    np.testing.assert_array_equal(fused, composed)


def test_multiclass_summary_pretty_printer():
    """Mahout-style summary block (MulticlassClassifierEvaluator.scala:
    123-167): spot-check headline metrics appear."""
    preds = Dataset(np.array([0, 1, 2, 1, 0], np.int32))
    actual = Dataset(np.array([0, 1, 1, 1, 0], np.int32))
    s = MulticlassClassifierEvaluator(3).evaluate(preds, actual).summary()
    assert "Confusion matrix" in s and "accuracy" in s.lower()


def test_kernel_apply_is_single_dispatch(monkeypatch):
    # the blocked kernel apply must be ONE jitted scan, not one dispatch
    # per train block (VERDICT r1: per-block host dispatch on a ~69 ms
    # RTT link dominates the apply)
    from keystone_tpu.nodes.learning import kernels as K

    rng = np.random.default_rng(5)
    Xtr = rng.normal(size=(50, 3)).astype(np.float32)  # pads to 4 blocks of 16
    alpha = rng.normal(size=(50, 2)).astype(np.float32)
    Xte = rng.normal(size=(20, 3)).astype(np.float32)

    calls = []
    orig = K._kernel_apply_scan

    def counting(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    monkeypatch.setattr(K, "_kernel_apply_scan", counting)
    mapper = K.KernelBlockLinearMapper(Xtr, alpha, gamma=0.7, block_size=16)
    out = np.asarray(mapper.apply_batch(Dataset(Xte)).numpy())
    assert len(calls) == 1

    # correctness vs the unblocked dense product
    D = ((Xte[:, None, :] - Xtr[None, :, :]) ** 2).sum(-1)
    expect = np.exp(-0.7 * D) @ alpha
    np.testing.assert_allclose(out, expect, atol=1e-4)


def test_block_mapper_apply_and_evaluate():
    # incremental per-block eval (BlockLinearMapper.scala:96-137): one
    # scan dispatch, last partial == full apply
    from keystone_tpu.nodes.learning.block_ls import BlockLinearMapper

    rng = np.random.default_rng(6)
    X = rng.normal(size=(30, 10)).astype(np.float32)
    W = rng.normal(size=(10, 3)).astype(np.float32)
    b = rng.normal(size=(3,)).astype(np.float32)
    mapper = BlockLinearMapper(W, b, block_size=4)  # 3 blocks (last padded)
    ds = Dataset(X)

    evals = list(mapper.apply_and_evaluate(ds, lambda d: np.asarray(d.numpy())))
    assert len(evals) == 3
    full = np.asarray(mapper.apply_batch(ds).numpy())
    np.testing.assert_allclose(evals[-1], full, atol=1e-5)
    # first partial uses only the first feature block
    np.testing.assert_allclose(evals[0], X[:, :4] @ W[:4] + b, atol=1e-5)
    assert not np.allclose(evals[0], full)


def test_apply_and_evaluate_chunked_matches_unchunked():
    # chunked scans (memory-bounded dispatch groups) must yield the same
    # partial-prediction sequence as one block per dispatch
    from keystone_tpu.nodes.learning.block_ls import BlockLinearMapper

    rng = np.random.default_rng(8)
    X = rng.normal(size=(20, 12)).astype(np.float32)
    W = rng.normal(size=(12, 2)).astype(np.float32)
    mapper = BlockLinearMapper(W, block_size=3)  # 4 blocks
    ds = Dataset(X)
    grab = lambda d: np.asarray(d.numpy())
    one = list(mapper.apply_and_evaluate(ds, grab, blocks_per_dispatch=1))
    big = list(mapper.apply_and_evaluate(ds, grab, blocks_per_dispatch=3))
    assert len(one) == len(big) == 4
    for a, b in zip(one, big):
        np.testing.assert_allclose(a, b, atol=1e-5)


# ------------------------------------------------- reference aMat/bMat fixtures
# (the exact 15x12 / 15x3 matrices the reference's BWLS suite loads —
# BlockWeightedLeastSquaresSuite.scala:63-223)


def _load_amat_bmat(a="aMat.csv", b="bMat.csv"):
    base = os.path.join(os.path.dirname(__file__), "resources")
    A = np.loadtxt(os.path.join(base, a), delimiter=",").astype(np.float32)
    B = np.loadtxt(os.path.join(base, b), delimiter=",").astype(np.float32)
    if B.ndim == 1:
        B = B[:, None]
    return A, B


def test_bwls_reference_fixture_zero_gradient():
    """The reference's exact zero-gradient configuration: aMat/bMat,
    blockSize=4, numIter=10, lambda=0.1, mixtureWeight=0.3, |grad|<1e-2
    (BlockWeightedLeastSquaresSuite.scala:142-166)."""
    A, B = _load_amat_bmat()
    n, k = B.shape
    lam, mw = 0.1, 0.3
    model = BlockWeightedLeastSquaresEstimator(4, 10, lam, mw).fit(
        Dataset(A), Dataset(B)
    )
    W = np.asarray(model.W, np.float64)
    b = np.asarray(model.b, np.float64)
    A64, B64 = A.astype(np.float64), B.astype(np.float64)
    grad_norm2 = 0.0
    for c in range(k):
        member = (B64[:, c] > 0).astype(np.float64)
        wts = mw * member / member.sum() + (1 - mw) / n
        resid = A64 @ W[:, c] + b[c] - B64[:, c]
        grad = A64.T @ (wts * resid) + lam * W[:, c]
        grad_norm2 += float(grad @ grad)
    assert np.sqrt(grad_norm2) < 1e-2


def test_bwls_reference_fixture_per_class_matches_blockweighted():
    """Per-class delegate ≈ BlockWeighted on the reference fixture
    (BlockWeightedLeastSquaresSuite.scala:115-140)."""
    A, B = _load_amat_bmat()
    lam, mw = 0.1, 0.3
    bw = BlockWeightedLeastSquaresEstimator(4, 10, lam, mw).fit(
        Dataset(A), Dataset(B)
    )
    pc = PerClassWeightedLeastSquares(lam, mw).fit(Dataset(A), Dataset(B))
    np.testing.assert_allclose(
        np.asarray(bw.W), np.asarray(pc.W), atol=5e-2, rtol=5e-2
    )


def test_bwls_reference_fixture_single_class():
    """1-class fixture satisfies its weighted normal equations
    (BlockWeightedLeastSquaresSuite.scala:168-186). With one class the
    per-example weights collapse to mw/n_c + (1-mw)/n = 1/n."""
    A, B = _load_amat_bmat("aMat-1class.csv", "bMat-1class.csv")
    n, k = B.shape
    lam, mw = 0.1, 0.3
    model = BlockWeightedLeastSquaresEstimator(4, 10, lam, mw).fit(
        Dataset(A), Dataset(B)
    )
    W = np.asarray(model.W, np.float64)
    b = np.asarray(model.b, np.float64)
    assert W.shape == (A.shape[1], k)
    A64, B64 = A.astype(np.float64), B.astype(np.float64)
    for c in range(k):
        member = (B64[:, c] > 0).astype(np.float64)
        wts = mw * member / max(member.sum(), 1.0) + (1 - mw) / n
        resid = A64 @ W[:, c] + b[c] - B64[:, c]
        grad = A64.T @ (wts * resid) + lam * W[:, c]
        assert np.abs(grad).max() < 1e-2, f"class {c}: {np.abs(grad).max()}"


def test_bwls_reference_fixture_nondivisible_blocksize():
    """nFeatures=12 not divisible by blockSize=5
    (BlockWeightedLeastSquaresSuite.scala:188-223): same solution as a
    divisible blocking."""
    A, B = _load_amat_bmat()
    lam, mw = 0.1, 0.3
    m5 = BlockWeightedLeastSquaresEstimator(5, 12, lam, mw).fit(
        Dataset(A), Dataset(B)
    )
    m4 = BlockWeightedLeastSquaresEstimator(4, 12, lam, mw).fit(
        Dataset(A), Dataset(B)
    )
    np.testing.assert_allclose(
        np.asarray(m5.W), np.asarray(m4.W), atol=5e-2, rtol=5e-2
    )


def test_lda_iris_matches_published_eigenvectors():
    """The reference's iris fixture (LinearDiscriminantAnalysisSuite.
    scala:13-38): LDA(2) on standardized iris must reproduce the
    published discriminant directions (Raschka's LDA tutorial), up to
    sign and scale — the reference normalizes to unit length."""
    path = os.path.join(os.path.dirname(__file__), "resources", "iris.data")
    rows = [l.strip() for l in open(path) if l.strip()]
    X = np.array([[float(v) for v in r.split(",")[:4]] for r in rows],
                 np.float64)
    name_to_label = {"Iris-setosa": 1, "Iris-versicolor": 2,
                     "Iris-virginica": 3}
    y = np.array([name_to_label[r.split(",")[-1]] for r in rows], np.int32)
    Xs = ((X - X.mean(0)) / X.std(0, ddof=1)).astype(np.float32)

    model = LinearDiscriminantAnalysis(2).fit(Dataset(Xs), Dataset(y))
    W = np.asarray(model.components, np.float64)
    major = np.array([-0.1498, -0.1482, 0.8511, 0.4808])
    minor = np.array([0.0095, 0.3272, -0.5748, 0.75])
    for col, want in ((W[:, 0], major), (W[:, 1], minor)):
        got = col / np.linalg.norm(col)
        err = min(np.abs(got - want).max(), np.abs(got + want).max())
        assert err < 1e-3, (got, want)


def test_stupid_backoff_reference_corpus_exact_scores():
    """The reference suite's exact corpus and score assertions
    (StupidBackoffSuite.scala:15-79): 'Winter is coming' / 'Finals are
    coming' / 'Summer is coming really soon', n-grams of orders 2-5 via
    the node chain, separate unigram counts fed to the estimator."""
    from collections import Counter

    data = ["Winter is coming", "Finals are coming",
            "Summer is coming really soon"]
    tok = Tokenizer()
    ngrams = Counter()
    unigrams = Counter()
    for s in data:
        toks = tok.apply(s)
        for ng in NGramsFeaturizer(range(2, 6)).apply(toks):
            ngrams[tuple(ng)] += 1
        for ng in NGramsFeaturizer([1]).apply(toks):
            unigrams[ng[0]] += 1

    lm = StupidBackoffEstimator(unigram_counts=dict(unigrams)).fit(
        HostDataset([ngrams])
    )
    num_tokens = sum(unigrams.values())  # 11
    assert abs(lm.score(("is", "coming")) - 2.0 / 2.0) < 1e-12
    assert abs(lm.score(("is", "coming", "really")) - 1.0 / 2.0) < 1e-12
    # backed off once AND current word unseen -> 0
    assert lm.score(("is", "unseen-coming")) == 0.0
    # backed off once, current word seen -> alpha * count/numTokens
    assert abs(
        lm.score(("is-unseen", "coming")) - lm.alpha * 3.0 / num_tokens
    ) < 1e-12


def test_packed_stupid_backoff_matches_recursive_model():
    """PackedStupidBackoffModel (sorted bit-packed arrays, iterative
    vectorized scoring, InitialBigramPartitioner-style first-two-words
    grouping) reproduces the recursive dict model's scores on every
    query class: seen trigram, backed-off bigram, double-backoff,
    OOV members, and bare unigrams. Also pins the reference suite's
    exact values and the 12-bytes/ngram memory bound."""
    from collections import Counter

    from keystone_tpu.nodes.nlp import (
        PackedStupidBackoffEstimator,
        StupidBackoffEstimator,
    )

    rng = np.random.default_rng(0)
    vocab = [f"t{i}" for i in range(300)]
    docs = [
        [vocab[j] for j in rng.zipf(1.4, size=40) % 300]
        for _ in range(200)
    ]
    packed = PackedStupidBackoffEstimator().fit(HostDataset(docs))

    ngrams = Counter()
    unigrams = Counter()
    for toks in docs:
        for o in (2, 3):
            for i in range(len(toks) - o + 1):
                ngrams[tuple(toks[i:i + o])] += 1
        for w in toks:
            unigrams[w] += 1
    ref = StupidBackoffEstimator(unigram_counts=dict(unigrams)).fit(
        HostDataset([ngrams]))

    queries = []
    for toks in docs[:40]:
        for i in range(len(toks) - 2):
            queries.append(tuple(toks[i:i + 3]))
    queries += [
        ("t1", "t2"), ("t5",), ("oov-x", "t2", "t3"),
        ("t1", "oov-x", "t3"), ("t1", "t2", "oov-x"), ("oov-x",),
    ]
    got = packed.score_batch(queries)
    want = np.array([ref.score(q) for q in queries])
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12)

    # memory bound: 12 bytes per distinct 2/3-gram + unigram vector
    n_types = len(packed.keys)
    assert packed.nbytes <= 12 * n_types + 8 * len(packed.unigram) + 64

    # reference suite exact values through the packed path
    data = ["Winter is coming", "Finals are coming",
            "Summer is coming really soon"]
    pk = PackedStupidBackoffEstimator().fit(
        HostDataset([s.split() for s in data]))
    assert abs(pk.score(("is", "coming")) - 1.0) < 1e-12
    assert pk.score(("is", "unseen-coming")) == 0.0
    assert abs(pk.score(("is-unseen", "coming")) - 0.4 * 3.0 / 11) < 1e-12
