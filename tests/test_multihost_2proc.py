"""True multi-process multihost test: two OS processes, 4 virtual CPU
devices each, joined into one 8-device job via jax.distributed (Gloo
over localhost ≈ DCN). The reference has no analog — its multi-node
behavior is delegated to Spark and never tested beyond local mode
(SURVEY §4) — so this goes beyond reference density on purpose: the
multi-host claim in parallel/multihost.py is executed, not just
unit-tested in a single process.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "multihost_worker.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_device_config_supported() -> bool:
    """Capability probe: the workers size their virtual-device mesh via
    ``jax.config.update("jax_num_cpu_devices", ...)``, which older
    jaxlib (< 0.5) does not expose (and XLA_FLAGS cannot replace once
    the flag must apply inside `jax.distributed`-initialized workers).
    The parent shares the workers' jax install, so probing here mirrors
    exactly the call that would fail in the subprocess."""
    import jax

    return hasattr(jax.config, "jax_num_cpu_devices")


def test_two_process_job_dataset_and_solver():
    if not _worker_device_config_supported():
        pytest.skip(
            "jax.config has no jax_num_cpu_devices option on this "
            "jax/jaxlib; multihost workers cannot size their device mesh"
        )
    # bounded by the shared 240 s reap deadline below
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets device count via jax.config
    env.pop("PALLAS_AXON_POOL_IPS", None)  # hermetic: never register the
    # axon PJRT plugin in CPU-only workers — backend discovery through a
    # wedged device tunnel hangs the worker past the reap deadline
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(i), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for i in range(2)
    ]
    import time

    outs = ["", ""]
    deadline = time.monotonic() + 240  # shared budget across both reaps
    timed_out = False
    for i, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=max(1.0, deadline - time.monotonic()))
            outs[i] = out
        except subprocess.TimeoutExpired as e:
            outs[i] = (e.stdout or "") + "\n<worker timed out>"
            timed_out = True
    if timed_out:
        for p in procs:
            if p.poll() is None:
                p.kill()
                out, _ = p.communicate()  # reap; collect partial output
                outs[procs.index(p)] += out or ""
        pytest.fail(
            "multihost workers timed out:\n"
            + "\n".join(f"--- worker {i}:\n{o}" for i, o in enumerate(outs))
        )
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} rc={p.returncode}\n{out}"
        assert "MULTIHOST_OK" in out, f"worker {i} output:\n{out}"
