"""Cross-implementation numeric goldens for the dense descriptors.

A real image (gantrycrane.png — the same public test image the
reference uses for its VLFeat golden, VLFeatSuite.scala:15-40) is run
through both the library's jitted XLA extractors and the independent
numpy implementations in `descriptor_reference_impls`. These catch
indexing/padding/binning divergence that shape- and norm-only tests
cannot (VERDICT r1 item 3).
"""

import os

import numpy as np
import pytest

import descriptor_reference_impls as ref

RESOURCE = os.path.join(os.path.dirname(__file__), "resources", "gantrycrane.png")


@pytest.fixture(scope="module")
def real_image():
    from PIL import Image

    img = np.asarray(Image.open(RESOURCE), dtype=np.float32) / 255.0
    # a crop keeps the pure-python reference loops fast while staying a
    # real natural image
    return img[40:160, 60:220, :]  # (120, 160, 3)


@pytest.fixture(scope="module")
def gray(real_image):
    return real_image @ np.asarray([0.299, 0.587, 0.114], np.float32)


def test_dense_sift_matches_vl_dsift_oracle(gray):
    """sift.py (direct conv formulation) vs the literal scalar-loop
    vl_dsift fast-mode oracle (transposed image + descriptor transpose,
    exactly VLFeat.cxx's pipeline) on a real image. Acceptance mirrors
    the reference's own VLFeatSuite.scala:15-40 criterion against its
    matlab golden: >=99.5% of entries within 1 quantization level; on
    top of that we bound the max deviation (quantized units, 0..255).
    Measured on this image/crop: 99.98% exact integer match, 0% off by
    more than 1, max deviation 1 level (f32 conv vs f64 loops flipping
    floor(512*v) at bin edges)."""
    from keystone_tpu.nodes.images.sift import SIFTExtractor

    ext = SIFTExtractor(step=3, bin_size=4, num_scales=2, scale_step=0)
    got = np.asarray(ext.apply(gray))
    want = ref.vl_dsift_multiscale(gray, step=3, bin_size=4, num_scales=2,
                                   scale_step=0)
    assert got.shape == want.shape
    diff = np.abs(got - want)
    frac_off = float(np.mean(diff > 1.0))
    assert frac_off < 0.005, f"{frac_off:.4%} of entries off by more than 1"
    # Measured max deviation is 1 quantization level (f32-vs-f64 flips at
    # floor(512·v) bin edges); 2 is an intentional guard band so benign
    # compiler/platform reassociation doesn't flake the suite. The
    # frac_off bound above is the tight fidelity assertion.
    assert diff.max() <= 2.0, diff.max()
    # and they genuinely vary across the image (not a degenerate match)
    assert np.std(want) > 1.0


def test_dense_sift_contrast_threshold_zeroing():
    """A (near-)constant image has descriptor norms below the 0.005
    contrast threshold, so every descriptor is zeroed — both in the
    oracle and the XLA path (VLFeat.cxx:63,140-147)."""
    from keystone_tpu.nodes.images.sift import SIFTExtractor

    flat = np.full((48, 48), 0.5, np.float32)
    got = np.asarray(SIFTExtractor(step=4, bin_size=4, num_scales=1,
                                   scale_step=0).apply(flat))
    assert got.shape[0] > 0 and np.all(got == 0.0)
    want = ref.vl_dsift_multiscale(flat, step=4, bin_size=4, num_scales=1,
                                   scale_step=0)
    assert want.shape == got.shape and np.all(want == 0.0)


def test_dense_sift_reference_config_counts(gray):
    """Exact descriptor-count parity with the vl_dsift frame geometry at
    the reference's VLFeatSuite configuration (step 3, bin 4, 4 scales,
    scaleStep 0): frames span [off, dim-1] with footprint 3*binSize+1,
    off = (1+2*numScales)-3*scale (VLFeat.cxx:77-99)."""
    from keystone_tpu.nodes.images.sift import SIFTExtractor

    h, w = gray.shape
    expected = 0
    for s in range(4):
        bs = 4 + 2 * s
        off = 9 - 3 * s
        span = 3 * bs + 1
        n_r = max(((h - 1) - span + 1 - off) // 3 + 1, 0)
        n_c = max(((w - 1) - span + 1 - off) // 3 + 1, 0)
        expected += n_r * n_c
    out = SIFTExtractor(step=3, bin_size=4, num_scales=4, scale_step=0).apply(gray)
    assert out.shape == (expected, 128)


def test_hog_matches_numpy_reference(real_image):
    from keystone_tpu.nodes.images.descriptors import HogExtractor

    got = np.asarray(HogExtractor(cell_size=8).apply(real_image))
    want = ref.hog(real_image, cell_size=8)
    assert got.shape == want.shape
    # per-pixel argmax over channel gradient energy can tie (equal
    # gradients in two channels of a real image); jax and numpy may
    # break ties differently, perturbing a handful of cells slightly
    diff = np.abs(got - want)
    assert np.mean(diff > 1e-3) < 1e-3, f"{np.mean(diff > 1e-3):%} cells differ"
    assert diff.max() < 0.02, diff.max()
    assert np.std(want) > 0.01


def test_daisy_matches_reference_oracle(gray):
    """XLA DAISY vs the scalar-structure oracle of
    DaisyExtractor.scala:28-201 semantics (conv2D gradients, incremental
    un-normalized Gaussian levels, (t−1) ring-angle phase, per-histogram
    normalization) on a real-image crop."""
    from keystone_tpu.nodes.images.descriptors import DaisyExtractor

    got = np.asarray(DaisyExtractor().apply(gray))
    want = ref.daisy(gray)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=5e-5)
    assert np.std(want) > 0.01


def test_daisy_matches_matlab_golden_sums():
    """The reference suite's own golden (DaisyExtractorSuite.scala:20-30):
    MATLAB-computed first-keypoint and full-feature sums on the FULL
    gantrycrane gray image, at the reference's first-keypoint tolerance
    (1e-5) and a f32-relaxed full-sum tolerance (reference asserts 1e-7
    in f64; the f64 oracle hits rel 1.2e-6 / 6.2e-8 on both)."""
    from PIL import Image

    from keystone_tpu.nodes.images.descriptors import DaisyExtractor

    img = np.asarray(Image.open(RESOURCE), np.float64)
    g = 0.2989 * img[:, :, 0] + 0.5870 * img[:, :, 1] + 0.1140 * img[:, :, 2]
    out = np.asarray(DaisyExtractor().apply(g.astype(np.float32)))
    assert out.shape == (5336, 200)
    first_kp = float(out[0].sum())
    full = float(out.sum())
    matlab_first = 55.127217737738533
    matlab_full = 3.240635661296463e5
    assert abs(first_kp - matlab_first) / matlab_first < 1e-5
    assert abs(full - matlab_full) / matlab_full < 1e-6


def test_lcs_matches_numpy_reference(real_image):
    from keystone_tpu.nodes.images.descriptors import LCSExtractor

    got = np.asarray(LCSExtractor(stride=6).apply(real_image))
    want = ref.lcs(real_image, stride=6, subpatch_size=6, subpatches=4)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=2e-4)
    assert np.std(want) > 0.01
