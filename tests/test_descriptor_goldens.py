"""Cross-implementation numeric goldens for the dense descriptors.

A real image (gantrycrane.png — the same public test image the
reference uses for its VLFeat golden, VLFeatSuite.scala:15-40) is run
through both the library's jitted XLA extractors and the independent
numpy implementations in `descriptor_reference_impls`. These catch
indexing/padding/binning divergence that shape- and norm-only tests
cannot (VERDICT r1 item 3).
"""

import os

import numpy as np
import pytest

import descriptor_reference_impls as ref

RESOURCE = os.path.join(os.path.dirname(__file__), "resources", "gantrycrane.png")


@pytest.fixture(scope="module")
def real_image():
    from PIL import Image

    img = np.asarray(Image.open(RESOURCE), dtype=np.float32) / 255.0
    # a crop keeps the pure-python reference loops fast while staying a
    # real natural image
    return img[40:160, 60:220, :]  # (120, 160, 3)


@pytest.fixture(scope="module")
def gray(real_image):
    return real_image @ np.asarray([0.299, 0.587, 0.114], np.float32)


def test_dense_sift_matches_numpy_reference(gray):
    from keystone_tpu.nodes.images.sift import SIFTExtractor

    ext = SIFTExtractor(step=5, bin_size=4, num_scales=2)
    got = np.asarray(ext.apply(gray))
    want = np.concatenate(
        [
            ref.dense_sift_one_scale(gray, 4, 5, 4 / 3.0),
            ref.dense_sift_one_scale(gray, 8, 5, 8 / 3.0),
        ]
    )
    assert got.shape == want.shape
    # descriptors live on [0, 512]; f32 conv vs f64 loops
    np.testing.assert_allclose(got, want, atol=0.5)
    # and they genuinely vary across the image (not a degenerate match)
    assert np.std(want) > 1.0


def test_hog_matches_numpy_reference(real_image):
    from keystone_tpu.nodes.images.descriptors import HogExtractor

    got = np.asarray(HogExtractor(cell_size=8).apply(real_image))
    want = ref.hog(real_image, cell_size=8)
    assert got.shape == want.shape
    # per-pixel argmax over channel gradient energy can tie (equal
    # gradients in two channels of a real image); jax and numpy may
    # break ties differently, perturbing a handful of cells slightly
    diff = np.abs(got - want)
    assert np.mean(diff > 1e-3) < 1e-3, f"{np.mean(diff > 1e-3):%} cells differ"
    assert diff.max() < 0.02, diff.max()
    assert np.std(want) > 0.01


def test_daisy_matches_numpy_reference(gray):
    from keystone_tpu.nodes.images.descriptors import DaisyExtractor

    ext = DaisyExtractor(stride=8, radius=15)
    got = np.asarray(ext.apply(gray))
    want = ref.daisy(gray, stride=8, radius=15, rings=3, ring_points=8,
                     num_orientations=8)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=2e-4)
    assert np.std(want) > 0.01


def test_lcs_matches_numpy_reference(real_image):
    from keystone_tpu.nodes.images.descriptors import LCSExtractor

    got = np.asarray(LCSExtractor(stride=6).apply(real_image))
    want = ref.lcs(real_image, stride=6, subpatch_size=6, subpatches=4)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=2e-4)
    assert np.std(want) > 0.01
