"""Overlapped execution engine (utils/batching.py + workflow streaming).

Contracts under test:
  - the overlapped dispatcher returns results in the original item
    order across shape buckets, identical (allclose) to the serial path
    and the per-item path;
  - a producer-thread exception propagates to the caller (no hang, no
    leaked blocked thread);
  - the bounded queue caps peak host memory at O(depth × chunk) items;
  - forced Expressions stream per-chunk results to chunk-capable
    consumers (downstream work starts before the upstream stage has
    materialized);
  - the serial fallback fires for single-chunk inputs and when the
    config flag is off.
"""

import threading
import time

import numpy as np
import pytest

from keystone_tpu.utils import batching
from keystone_tpu.workflow.env import (
    execution_config,
    overlap_override,
    set_execution_config,
)


def _mixed_shape_items(rng, n_a=9, n_b=7):
    items = [rng.uniform(size=(8, 6)).astype(np.float32) for _ in range(n_a)]
    items += [rng.uniform(size=(5, 4)).astype(np.float32) for _ in range(n_b)]
    # interleave the buckets so ordering is non-trivial
    order = rng.permutation(len(items))
    return [items[i] for i in order]


def test_overlapped_matches_serial_across_shape_buckets():
    rng = np.random.default_rng(0)
    items = _mixed_shape_items(rng)
    fn = lambda x: np.asarray(x) * 2.0 + 1.0

    with overlap_override(False):
        serial = batching.map_host_batched(items, fn, chunk=4)
    with overlap_override(True):
        overlapped = batching.map_host_batched(items, fn, chunk=4)
    assert len(serial) == len(overlapped) == len(items)
    for s, o, x in zip(serial, overlapped, items):
        np.testing.assert_allclose(o, s)
        np.testing.assert_allclose(o, x * 2.0 + 1.0, rtol=1e-6)


def test_overlapped_two_chunk_smoke():
    """Fast smoke: the overlapped path with a minimal 2-chunk input
    (the smallest input that actually exercises the producer thread)."""
    items = [np.full((3, 3), i, np.float32) for i in range(4)]
    with overlap_override(True, prefetch_depth=1):
        out = batching.map_host_batched(items, lambda x: np.asarray(x) + 1, chunk=2)
    for i, r in enumerate(out):
        np.testing.assert_allclose(r, np.full((3, 3), i + 1, np.float32))


def test_single_chunk_input_takes_serial_path(monkeypatch):
    """Nothing to overlap for one chunk: the dispatcher must not spawn a
    producer thread."""
    spawned = []
    orig = threading.Thread

    class Spy(orig):
        def __init__(self, *a, **kw):
            spawned.append(kw.get("name"))
            super().__init__(*a, **kw)

    monkeypatch.setattr(threading, "Thread", Spy)
    items = [np.ones((2, 2), np.float32) for _ in range(5)]
    with overlap_override(True):
        out = batching.map_host_batched(items, lambda x: np.asarray(x), chunk=8)
    assert len(out) == 5
    assert not any(n and n.startswith("keystone-") for n in spawned)


def test_producer_exception_propagates_without_hang():
    class Cursed:
        shape = (2, 2)

        def __array__(self, dtype=None):
            raise ValueError("corrupt item (simulated)")

    items = [np.ones((2, 2), np.float32) for _ in range(6)] + [Cursed()]
    with overlap_override(True, prefetch_depth=1):
        t0 = time.monotonic()
        with pytest.raises(ValueError, match="corrupt item"):
            batching.map_host_batched(items, lambda x: np.asarray(x), chunk=2)
        assert time.monotonic() - t0 < 30.0  # propagated, did not hang


def test_consumer_exception_cancels_producer():
    """A batch_fn failure must re-raise promptly and release the
    producer thread (bounded put is cancellable, never blocked forever)."""
    items = [np.ones((2, 2), np.float32) * i for i in range(40)]

    def fn(x):
        if float(np.asarray(x)[0, 0, 0]) >= 4.0:
            raise RuntimeError("device rejected batch (simulated)")
        return np.asarray(x)

    before = threading.active_count()
    with overlap_override(True, prefetch_depth=2):
        with pytest.raises(RuntimeError, match="rejected batch"):
            batching.map_host_batched(items, fn, chunk=2)
    deadline = time.monotonic() + 30.0
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before


def test_bounded_queue_caps_peak_host_memory():
    """With the consumer blocked, the producer may stage at most
    queue(depth) + 1 chunks — peak host memory O(depth × chunk) items,
    not O(n)."""
    depth, chunk, n_chunks = 2, 4, 12
    converted = []
    release = threading.Event()
    entered = threading.Event()

    class Tracked:
        shape = (2, 2)

        def __init__(self, i):
            self.i = i

        def __array__(self, dtype=None):
            converted.append(self.i)
            return np.full((2, 2), self.i, np.float32)

    items = [Tracked(i) for i in range(chunk * n_chunks)]

    def fn(x):
        entered.set()
        release.wait(timeout=60.0)
        return np.asarray(x)

    def consume():
        with overlap_override(True, prefetch_depth=depth):
            return batching.map_host_batched(items, fn, chunk=chunk)

    out = [None]
    t = threading.Thread(target=lambda: out.__setitem__(0, consume()))
    t.start()
    assert entered.wait(timeout=30.0)
    time.sleep(0.5)  # let the producer run as far as the queue allows
    # producer staged ≤ depth queued + 1 being stacked + ≤ (depth + 1)
    # chunks handed to the (blocked) dispatch window
    cap = (2 * depth + 2) * chunk
    staged = len(converted)
    assert staged <= cap, (staged, cap)
    assert staged < len(items)  # strictly bounded, not all-at-once
    release.set()
    t.join(timeout=60.0)
    assert not t.is_alive()
    for i, r in enumerate(out[0]):
        np.testing.assert_allclose(r, np.full((2, 2), i, np.float32))


def test_prefetch_iterator_order_exception_and_early_close():
    with overlap_override(True, prefetch_depth=2):
        assert list(batching.prefetch_iterator(iter(range(20)))) == list(range(20))

        def broken():
            yield 1
            raise OSError("short read (simulated)")

        it = batching.prefetch_iterator(broken())
        assert next(it) == 1
        with pytest.raises(OSError, match="short read"):
            list(it)

        produced = []

        def slow_gen():
            for i in range(1000):
                produced.append(i)
                yield i

        it = batching.prefetch_iterator(slow_gen(), depth=2)
        assert next(it) == 0
        it.close()  # early break must cancel the producer
        time.sleep(0.2)
        assert len(produced) < 1000

    with overlap_override(False):  # disabled: plain passthrough
        assert list(batching.prefetch_iterator(iter("abc"))) == ["a", "b", "c"]


def test_execution_config_env_and_override(monkeypatch):
    monkeypatch.setenv("KEYSTONE_OVERLAP", "0")
    monkeypatch.setenv("KEYSTONE_PREFETCH_DEPTH", "5")
    set_execution_config(None)
    try:
        cfg = execution_config()
        assert cfg.overlap is False and cfg.prefetch_depth == 5
        with overlap_override(True, prefetch_depth=3) as inner:
            assert inner.overlap is True and inner.prefetch_depth == 3
            assert execution_config().overlap is True
        assert execution_config().overlap is False
    finally:
        set_execution_config(None)


# --------------------------------------------------------------------------
# Workflow streaming: forced Expressions yield per-chunk results


def _stream_stage(tag, log, fn):
    """A chunkable per-item transformer that records when items pass."""
    from keystone_tpu.workflow.pipeline import Transformer

    def apply(x):
        log.append(tag)
        return fn(x)

    return Transformer.from_function(apply, name=tag)


def test_pipeline_streams_chunks_between_host_stages():
    """With overlap on, a chunk-capable downstream stage must start
    consuming before the upstream host-batched stage has finished every
    chunk — observable as interleaved per-item work."""
    from keystone_tpu.data.dataset import HostDataset
    from keystone_tpu.nodes.images.descriptors import LCSExtractor

    rng = np.random.default_rng(1)
    items = [rng.uniform(size=(40, 40, 3)).astype(np.float32) for _ in range(8)]
    ext = LCSExtractor(stride=8)

    log = []
    post = _stream_stage("post", log, lambda d: np.asarray(d).sum())
    pipe = ext >> post

    with overlap_override(True, prefetch_depth=1):
        import keystone_tpu.utils.batching as b

        orig = b.map_host_batched_stream

        def chunked(its, fn, chunk=256):
            for part, results in orig(its, fn, chunk=2):
                log.append(("chunk", tuple(part)))
                yield part, results

        b.map_host_batched_stream, saved = chunked, orig
        try:
            streamed = pipe(HostDataset(items)).get()
        finally:
            b.map_host_batched_stream = saved

    with overlap_override(False):
        serial = pipe(HostDataset(items)).get()

    # equality with the serial path, original order
    for s, o in zip(serial.items, streamed.items):
        np.testing.assert_allclose(np.asarray(s), np.asarray(o), rtol=1e-5)
    # interleaving: downstream "post" work appears BETWEEN chunk markers,
    # not after all of them (the stage did not materialize first)
    chunk_marks = [i for i, e in enumerate(log) if isinstance(e, tuple)]
    post_marks = [i for i, e in enumerate(log) if e == "post"]
    assert len(chunk_marks) >= 2
    assert min(post_marks) < max(chunk_marks), log


def test_pipeline_result_stream_api():
    """PipelineResult.stream() yields (indices, items) chunks whose
    union reassembles the full result; .get() afterwards is the memo."""
    from keystone_tpu.data.dataset import HostDataset
    from keystone_tpu.nodes.images.sift import SIFTExtractor

    rng = np.random.default_rng(2)
    items = [rng.uniform(size=(32, 32)).astype(np.float32) for _ in range(6)]
    ext = SIFTExtractor(step=8, num_scales=1)

    with overlap_override(True, prefetch_depth=1):
        res = ext(HostDataset(items))
        seen = {}
        n_chunks = 0
        for idxs, payload in res.stream():
            assert idxs is not None
            n_chunks += 1
            for i, item in zip(idxs, payload):
                seen[i] = item
        assert sorted(seen) == list(range(len(items)))
        full = res.get()  # memoized assembly of the same chunks
        for i, item in seen.items():
            np.testing.assert_allclose(
                np.asarray(full.items[i]), np.asarray(item))

    with overlap_override(False):
        serial = ext(HostDataset(items)).get()
    for i in range(len(items)):
        np.testing.assert_allclose(
            np.asarray(serial.items[i]), np.asarray(seen[i]), rtol=1e-5)


def test_streaming_preserves_non_host_pipelines():
    """Device-Dataset pipelines and non-chunkable stages take the
    whole-value fallback chunk — same results, same types."""
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.workflow.pipeline import Transformer

    double = Transformer.from_function(lambda x: x * 2.0, name="double")
    X = np.arange(12, dtype=np.float32).reshape(6, 2)
    with overlap_override(True):
        out = double(Dataset(X)).get()
        assert isinstance(out, Dataset)
        np.testing.assert_allclose(np.asarray(out.array)[:6], X * 2.0)
        chunks = list(double(Dataset(X)).stream())
        assert len(chunks) == 1 and chunks[0][0] is None


@pytest.mark.slow
def test_bench_overlap_tier_record_shape():
    """The featurize_overlap bench tier end-to-end at toy scale
    (timing-sensitive: real wall-clocks, compile + threads; tier-1
    excludes it via -m 'not slow')."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    res = bench._flagship_overlap(n=48, chunk=12, num_filters=8,
                                  block=16, iters=1)
    assert res["n_chunks"] == 4
    assert res["serial_seconds"] > 0 and res["overlapped_seconds"] > 0
    assert res["speedup"] == pytest.approx(
        res["serial_seconds"] / res["overlapped_seconds"], rel=1e-2)


def test_partial_stream_drain_never_rewinds_the_producer():
    """Breaking out of .stream() then forcing .get() must RESUME the
    producer, not re-run it: each chunk is dispatched exactly once, and
    the final value includes the chunks consumed before the break."""
    from keystone_tpu.data.dataset import HostDataset
    from keystone_tpu.workflow.pipeline import Transformer
    from keystone_tpu.utils import batching

    items = [np.full((2, 2), i, np.float32) for i in range(8)]
    dispatched = []

    class Chunky(Transformer):
        chunkable = True

        def apply(self, x):
            return np.asarray(x) + 1.0

        def apply_batch_stream(self, data):
            def fn(stacked):
                dispatched.append(np.asarray(stacked).shape[0])
                return np.asarray(stacked) + 1.0

            return batching.map_host_batched_stream(data.items, fn, chunk=2)

    with overlap_override(True, prefetch_depth=1):
        res = Chunky()(HostDataset(items))
        stream = res.stream()
        idxs0, payload0 = next(stream)  # consume ONE chunk, then abandon
        stream.close()
        full = res.get()
    assert sum(dispatched) == len(items), dispatched  # no chunk re-dispatched
    for i, r in enumerate(full.items):
        np.testing.assert_allclose(r, np.full((2, 2), i + 1, np.float32))
    # the chunk consumed before the break is the same object the final
    # assembly used (memoized prefix, not a recompute)
    for i, item in zip(idxs0, payload0):
        np.testing.assert_allclose(full.items[i], item)


def test_failed_stream_stays_failed_on_reforce():
    """A producer exception mid-stream is STICKY: forcing the same
    (executor-memoized) expression again must re-raise, never silently
    assemble the truncated prefix as the complete value."""
    from keystone_tpu.workflow.expressions import StreamingDatasetExpression

    calls = {"n": 0}

    def chunks():
        calls["n"] += 1
        yield [0, 1], ["a", "b"]
        raise ValueError("producer died (simulated)")

    expr = StreamingDatasetExpression(chunks)
    with pytest.raises(ValueError, match="producer died"):
        for _ in expr.iter_chunks():
            pass
    with pytest.raises(ValueError, match="producer died"):
        expr.get
    with pytest.raises(ValueError, match="producer died"):
        list(expr.iter_chunks())
    assert calls["n"] == 1  # the dead producer was never re-run
    assert not expr.is_forced
