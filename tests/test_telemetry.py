"""Unified runtime telemetry (keystone_tpu/telemetry/).

Covers the telemetry contract documented in OBSERVABILITY.md: span
nesting/parent attribution, Chrome-trace schema validity, the overlap
engine's documented residency bound surfacing as gauge high-water marks,
exception-path span closure (including the profiler's
elapsed-time-on-failure fix), autocache greedy decisions being stable on
telemetry-derived profiles, and the static-vs-observed memory
reconciliation loop end-to-end.
"""

import json
import time as _time

import numpy as np
import pytest

from keystone_tpu import Dataset, HostDataset, Pipeline, PipelineEnv, Transformer
from keystone_tpu.telemetry import (
    load_trace,
    registry,
    span,
    summarize,
    trace_run,
)
from keystone_tpu.utils.batching import map_host_batched
from keystone_tpu.workflow.env import overlap_override


@pytest.fixture(autouse=True)
def fresh_metrics():
    registry().reset()
    yield
    registry().reset()


# ----------------------------------------------------------- span basics


def test_span_nesting_and_parent_attribution():
    with trace_run() as tr:
        with span("outer", cat="phase", k=1):
            with span("inner_a", cat="step"):
                pass
            with span("inner_b", cat="step"):
                pass
    by_name = {s.name: s for s in tr.spans}
    root = by_name["pipeline_run"]
    outer = by_name["outer"]
    assert outer.parent == root.sid
    assert by_name["inner_a"].parent == outer.sid
    assert by_name["inner_b"].parent == outer.sid
    assert by_name["inner_a"].sid != by_name["inner_b"].sid
    assert outer.args["k"] == 1
    # children close before parents, so their intervals nest
    assert outer.t0 <= by_name["inner_a"].t0
    assert outer.t0 + outer.dur >= by_name["inner_b"].t0 + by_name["inner_b"].dur


def test_span_noop_without_tracer():
    # no tracer installed: the context manager is the shared no-op
    ctx = span("nothing", cat="node")
    with ctx as rec:
        assert rec is None


def test_exception_path_closes_spans():
    with pytest.raises(ValueError, match="boom"):
        with trace_run() as tr:
            with span("will_fail", cat="step"):
                raise ValueError("boom")
    failed = next(s for s in tr.spans if s.name == "will_fail")
    assert failed.error and failed.dur >= 0.0
    root = next(s for s in tr.spans if s.name == "pipeline_run")
    assert root.error  # the run itself is marked failed
    # the tracer's thread stack fully unwound: a new span is a root again
    with trace_run() as tr2:
        with span("fresh"):
            pass
    fresh = next(s for s in tr2.spans if s.name == "fresh")
    assert fresh.parent == next(
        s for s in tr2.spans if s.name == "pipeline_run").sid


def test_profiler_failure_keeps_elapsed_time_and_counts():
    """Satellite fix: a thunk that raises must not lose its elapsed time
    or force count (try/finally), and bumps a failure counter."""
    from keystone_tpu.utils.profiling import ExecutionProfiler
    from keystone_tpu.workflow.expressions import Expression

    prof = ExecutionProfiler()

    def bad_thunk():
        _time.sleep(0.05)
        raise RuntimeError("solver died")

    expr = prof.wrap("exploding", Expression(bad_thunk))
    with pytest.raises(RuntimeError, match="solver died"):
        expr.get
    p = prof.profiles["exploding"]
    assert p.forced == 1 and p.failures == 1
    assert p.seconds >= 0.04  # elapsed time survived the raise
    assert p.bytes == 0.0


# ----------------------------------------------------- trace JSON schema


def _run_traced_pipeline(tmp_path, n=48, dim=12):
    """A pipeline exercising all three runtime layers: a streaming
    host-batched stage (chunk spans), node forces, and a BCD solver fit
    (step spans)."""
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
    from keystone_tpu.nodes.util import (
        ClassLabelIndicatorsFromInt,
        MaxClassifier,
    )

    class StreamScale(Transformer):
        chunkable = True

        def apply(self, x):
            return x * 2.0  # eval_shape-traceable: the analyzer's
            # spec_pass resolves this stage statically

        def apply_batch_stream(self, data):
            from keystone_tpu.utils import batching

            return batching.map_host_batched_stream(
                data.items, lambda X: X * 2.0, chunk=8)

    class ToDevice(Transformer):
        def apply(self, x):
            return x

        def batch_transform(self, inputs):
            items = inputs[0].items if isinstance(inputs[0], HostDataset) \
                else list(inputs[0])
            return Dataset.from_numpy(np.stack(
                [np.asarray(x, np.float32) for x in items]))

    rng = np.random.default_rng(7)
    X = [rng.normal(size=(dim,)).astype(np.float32) for _ in range(n)]
    y = rng.integers(0, 3, size=n).astype(np.int32)
    labels = ClassLabelIndicatorsFromInt(3)(Dataset.from_numpy(y)).get()

    path = str(tmp_path / "trace.json")
    with overlap_override(True, prefetch_depth=2):
        with trace_run(path):
            featurizer = StreamScale().to_pipeline() >> ToDevice()
            predictor = featurizer.and_then(
                BlockLeastSquaresEstimator(8, num_iter=2, lam=0.1),
                HostDataset(X),
                labels,
            ) >> MaxClassifier()
            predictor(HostDataset(X)).get()
    return path


def test_trace_json_is_valid_chrome_trace(tmp_path):
    path = _run_traced_pipeline(tmp_path)
    trace = load_trace(path)  # raises on a non-trace object
    events = trace["traceEvents"]
    assert isinstance(events, list) and events
    for e in events:
        assert isinstance(e, dict)
        assert "name" in e and "ph" in e and "pid" in e
        if e["ph"] == "X":
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
    # round-trips through json
    json.loads(json.dumps(trace))
    # the three runtime hierarchy levels are all present
    cats = {e.get("cat") for e in events if e.get("ph") == "X"}
    assert {"node", "chunk", "step"} <= cats, cats
    # and they nest: every step/chunk span links to a parent
    linked = [e for e in events
              if e.get("ph") == "X" and e.get("cat") in ("step", "chunk")]
    assert linked and all(
        "parent_id" in e.get("args", {}) or e.get("cat") == "chunk"
        for e in linked)
    # prefetch/queue metrics made it into the export
    metrics = trace["keystone"]["metrics"]
    assert "prefetch.consumer_wait_s" in metrics["histograms"]
    assert metrics["counters"]["executor.node_forces"]["value"] > 0


def test_cli_summary_includes_memory_reconciliation(tmp_path):
    path = _run_traced_pipeline(tmp_path)
    out = summarize(load_trace(path))
    assert "top node forces by self-time" in out
    assert "solver iterations" in out and "bcd_epoch" in out
    assert "static vs observed memory" in out
    # the solver-side nodes appear in the reconciliation table
    assert "BlockLeastSquaresEstimator" in out or "DelegatingOperator" in out
    # and the module runs as a CLI
    from keystone_tpu.telemetry.__main__ import main as cli_main

    assert cli_main([path]) == 0


def test_reconciliation_static_matches_observed_for_solver_output(tmp_path):
    """The static KP2xx model and the observed bytes agree exactly for
    dense fixed-shape outputs (the solver-adjacent nodes) — the
    reconciliation loop's base case."""
    from keystone_tpu.analysis.reconcile import reconcile_trace

    path = _run_traced_pipeline(tmp_path)
    rec = reconcile_trace(load_trace(path))
    both = [r for r in rec["rows"] if r["rel_error"] is not None]
    assert both, "no node had both static and observed bytes"
    exact = [r for r in both if abs(r["rel_error"]) < 1e-6]
    assert exact, f"no exact reconciliation rows: {both}"
    assert rec["observed_peak_bytes"] and rec["observed_peak_bytes"] > 0


def test_streamed_stage_gets_node_span_and_bytes():
    """Review regression: a chunkable chain drains the upstream stage
    through iter_chunks() — the memoized thunk never runs — yet the
    stage must still appear in spans, bytes, and live-set accounting
    (instrumented at the chunk generator, marked ``streamed``)."""

    class StreamDouble(Transformer):
        chunkable = True

        def apply(self, x):
            return x * 2.0

        def apply_batch_stream(self, data):
            from keystone_tpu.utils import batching

            return batching.map_host_batched_stream(
                data.items, lambda X: X * 2.0, chunk=8)

    X = [np.ones((4,), np.float32) * i for i in range(32)]
    with overlap_override(True, prefetch_depth=2):
        with trace_run() as tr:
            pipe = StreamDouble().to_pipeline() >> Transformer.from_function(
                lambda x: x + 1.0, name="inc")
            out = pipe(HostDataset(X)).get()
    np.testing.assert_allclose(np.stack(out.items), np.stack(X) * 2.0 + 1.0)
    node_spans = {s.name: s for s in tr.spans if s.cat == "node"}
    assert "force StreamDouble" in node_spans, sorted(node_spans)
    assert "force Fn[inc]" in node_spans or "force inc" in node_spans \
        or any("inc" in n for n in node_spans)
    up = node_spans["force StreamDouble"]
    assert up.args.get("streamed") is True
    assert up.args.get("out_bytes") == 32 * 4 * 4  # real bytes, not 64B
    # the span covers the actual drain window: ts is the FIRST-pull
    # timestamp (not the completion time the record is written at),
    # dur stays the cumulative pull time, and drain_window_s carries
    # the full first-pull→exhaustion extent (≥ dur: the consumer's
    # between-chunk work is excluded from dur but inside the window)
    window = up.args.get("drain_window_s")
    assert window is not None
    assert window + 2e-6 >= up.dur
    assert 0.0 <= up.t0 <= up.t0 + window <= tr.now() + 2e-6


def test_observed_live_peak_is_per_run():
    """Review regression: the reconciliation's observed peak must be
    scoped to the traced run, not the process-cumulative gauge."""
    data = Dataset.from_numpy(np.ones((16, 8), np.float32))

    def one_run():
        PipelineEnv.reset()
        with trace_run() as tr:
            Transformer.from_function(lambda x: x * 2.0)(data).get()
        return tr.metadata.get("observed_live_peak_bytes", 0.0)

    first = one_run()
    second = one_run()
    assert first > 0
    assert second == pytest.approx(first)  # no carry-over between runs


# ------------------------------------------------- overlap engine bounds


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_queue_depth_gauge_obeys_documented_bound(depth):
    """utils/batching.py documents ≤ 2·prefetch_depth + 2 chunks resident
    per stage; the gauges' high-water marks must respect it."""
    items = [np.full((4,), i, np.float32) for i in range(64)]
    with overlap_override(True, prefetch_depth=depth):
        out = map_host_batched(items, lambda X: X * 2.0, chunk=4)
    np.testing.assert_allclose(
        np.stack(out), np.stack(items) * 2.0)
    reg = registry()
    assert reg.gauge("prefetch.queue_depth").max <= depth + 1
    assert reg.gauge("overlap.inflight_results").max <= depth + 1
    assert reg.gauge("overlap.resident_chunks").max <= 2 * depth + 2
    assert reg.counter("overlap.chunks_dispatched").value == 16
    assert reg.counter("overlap.bytes_pulled").value > 0


def test_producer_exception_still_records_metrics_and_raises():
    items = [np.ones((4,), np.float32)] * 32

    def exploding(X):
        raise RuntimeError("device fell over")

    with overlap_override(True, prefetch_depth=2):
        with pytest.raises(RuntimeError, match="device fell over"):
            map_host_batched(items, exploding, chunk=4)
    # gauges exist and the failure did not wedge accounting below zero
    assert registry().gauge("prefetch.queue_depth").max >= 0


# -------------------------------------------------- autocache consistency


class _SlowShared(Transformer):
    def apply(self, x):
        _time.sleep(0.12)
        return x * 2.0

    def apply_batch(self, data):
        _time.sleep(0.12)
        return data.map_batches(lambda a: a * 2.0)


class _Cheap(Transformer):
    def apply(self, x):
        return x + 1.0

    def apply_batch(self, data):
        return data.map_batches(lambda a: a + 1.0)


def _shared_slow_graph():
    """data -> slow -> {a, b}: the slow node is demanded twice, the
    classic cache-me shape (reference AutocCacheRuleSuite)."""
    from keystone_tpu.workflow.graph import Graph
    from keystone_tpu.workflow.operators import DatasetOperator

    g = Graph()
    g, data = g.add_node(
        DatasetOperator(Dataset.from_numpy(np.ones((64, 4), np.float32))), [])
    g, slow = g.add_node(_SlowShared(), [data])
    g, a = g.add_node(_Cheap(), [slow])
    g, b = g.add_node(_Cheap(), [slow])
    g, _ = g.add_sink(a)
    g, _ = g.add_sink(b)
    return g, slow


def test_autocache_greedy_identical_on_telemetry_profiles(monkeypatch):
    """Greedy decisions fed by telemetry-derived profiles: the shared
    slow node is cached, and replaying the rule on the captured profiles
    makes the identical decision (cache choices and user-facing reports
    draw from the same span data, so they cannot disagree)."""
    import keystone_tpu.workflow.autocache as ac
    from keystone_tpu.workflow.autocache import AutoCacheRule, CacheMarker

    PipelineEnv.reset()
    g, slow = _shared_slow_graph()
    candidates = AutoCacheRule._candidates(g)
    assert slow in candidates
    profiles = ac.profile_nodes(g, candidates, scales=(2, 4))
    # telemetry attribution: the 120 ms sleep lands on the slow node
    assert profiles[slow].ns > 100e6
    assert profiles[slow].mem_bytes > 0

    def cached_parents(graph):
        return {
            graph.get_operator(graph.get_dependencies(n)[0]).label
            for n in graph.nodes
            if isinstance(graph.get_operator(n), CacheMarker)
        }

    live_rule = AutoCacheRule(strategy="greedy", mem_budget_bytes=1 << 20)
    g_live, _ = live_rule.apply((g, {}))
    decisions_live = cached_parents(g_live)
    assert "_SlowShared" in decisions_live

    # identical decisions when the rule replays the SAME telemetry-derived
    # profiles without re-measuring
    monkeypatch.setattr(ac, "profile_nodes", lambda *a, **k: profiles)
    replay_rule = AutoCacheRule(strategy="greedy", mem_budget_bytes=1 << 20)
    g_replay, _ = replay_rule.apply((g, {}))
    assert cached_parents(g_replay) == decisions_live


def test_profile_execution_report_still_works():
    """Public API preserved: profile_execution + report() rows."""
    from keystone_tpu.utils.profiling import profile_execution

    PipelineEnv.reset()
    data = Dataset.from_numpy(np.ones((16, 4), np.float32))
    pipe = Transformer.from_function(lambda x: x * 3.0, name="tripler").to_pipeline()
    with profile_execution() as prof:
        pipe(data).get()
    report = prof.report()
    assert "tripler" in report and "seconds" in report
    assert any(p.forced for p in prof.profiles.values())


# ------------------------------------------------------- executor counters


def test_memo_and_prefix_counters_count_reuse():
    from keystone_tpu.utils.profiling import profile_execution

    PipelineEnv.reset()
    rng = np.random.default_rng(0)
    data = Dataset.from_numpy(rng.normal(size=(32, 4)).astype(np.float32))
    with profile_execution():
        p = Pipeline.gather([
            Transformer.from_function(lambda x: x * 2.0),
            Transformer.from_function(lambda x: x + 1.0),
        ])
        p(data).get()
    assert registry().counter("executor.node_forces").value > 0


# --------------------------------------------------- per-process dimension


def test_per_process_dispatch_dimension(monkeypatch):
    """Under a multi-host mesh every dispatch also lands on a
    per-process counter (dispatch.programs_executed.p<i>), and the
    shared dispatch/compile summaries render the breakdown; single-host
    jobs get no duplicate counter."""
    from keystone_tpu.telemetry import instrument

    # single-host: no per-process counter
    monkeypatch.setattr(instrument, "_proc_dim_cache", "")
    before = registry().counter("dispatch.programs_executed").value
    instrument.record_dispatch()
    assert registry().counter("dispatch.programs_executed").value == before + 1
    assert not any(k.startswith("dispatch.programs_executed.p")
                   for k in registry().counters)

    # simulated process 1 of a multi-host job
    monkeypatch.setattr(instrument, "_proc_dim_cache", "p1")
    instrument.record_dispatch(3)
    assert registry().counter("dispatch.programs_executed.p1").value == 3

    from keystone_tpu.telemetry.export import dispatch_summary

    trace = {"traceEvents": [], "keystone": {"metrics": registry().snapshot()}}
    line = dispatch_summary(trace)
    assert line is not None and "per-process: p1=3" in line


def test_per_process_compile_summary_breakdown():
    from keystone_tpu.telemetry.export import compile_summary

    trace = {"traceEvents": [], "keystone": {"metrics": {
        "counters": {
            "dispatch.programs_compiled": {"value": 5},
            "dispatch.programs_compiled.p0": {"value": 3},
            "dispatch.programs_compiled.p1": {"value": 2},
            "dispatch.compile_cache_hits": {"value": 0},
        },
        "histograms": {},
    }}}
    line = compile_summary(trace)
    assert "5 cold" in line and "per-process: p0=3 p1=2" in line
