"""Static roofline analyzer (KP8xx) acceptance suite — PR 12.

Covers the contract the tier exists for:

  - the jaxpr FLOP walk prices the canonical primitives exactly
    (GEMM 2mnk, conv 2·out·k·k·cin, elementwise at out-size, scan ×
    trips) and distinguishes movement bytes from compute;
  - the jaxpr walk is the SOURCE OF TRUTH, with the backend's
    `Lowered.cost_analysis()` as a cross-check: the two agree within
    2× on a GEMM stage whenever the backend provides an analysis
    (pytest-pinned — the capability-probe fallback satellite);
  - `stage_cost` is exactly ``max(flops/peak_flops, bytes/peak_bw)``
    and classification flips at the machine balance;
  - `roofline_pass` prices the example pipelines, flags ≥1 KP801
    Pallas candidate on the featurize-heavy RandomPatchCifar, KP802 on
    a movement-dominated stage, KP804 on an underfilled megafused
    scan, and the KP803 plan re-pricing is present;
  - the CLI gate: ``--explain-roofline --json`` succeeds over all 7
    examples with per-stage flops/bytes/intensity/predicted-seconds;
  - reconciliation: a traced MnistRandomFFT run embeds the per-stage
    predictions (``keystone.roofline``), `reconcile_roofline` joins
    them against observed span seconds, the drift report carries the
    flops residual, and ``--ledger`` renders without crashing when
    spans are missing.
"""

import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu import PipelineEnv
from keystone_tpu.analysis import SpecDataset, as_source_spec, validate_graph
from keystone_tpu.analysis.examples import EXAMPLES, build_example
from keystone_tpu.analysis.propagate import spec_pass
from keystone_tpu.analysis.roofline import (
    DISPATCH_OVERHEAD_S,
    Machine,
    body_counts,
    chain_predicted_seconds,
    default_machine,
    jaxpr_counts,
    roofline_pass,
    stage_cost,
    xla_cost_analysis,
)

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _fresh_env():
    PipelineEnv.reset()
    yield
    PipelineEnv.reset()


def _sds(shape, dtype=np.float32):
    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


# ------------------------------------------------------------ FLOP walk


def test_gemm_flops_exact():
    m, k, n = 64, 32, 16
    jx = jax.make_jaxpr(lambda a, b: a @ b)(_sds((m, k)), _sds((k, n)))
    flops, movement = jaxpr_counts(jx)
    assert flops == 2.0 * m * k * n
    assert movement == 0.0


def test_conv_flops_exact():
    # NHWC x HWIO, VALID: out (1, 6, 6, 8), kernel 3x3, cin 2
    jx = jax.make_jaxpr(
        lambda x, w: jax.lax.conv_general_dilated(
            x, w, (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC")))(
        _sds((1, 8, 8, 2)), _sds((3, 3, 2, 8)))
    flops, _ = jaxpr_counts(jx)
    assert flops == 2.0 * (6 * 6 * 8) * (3 * 3) * 2


def test_elementwise_and_reduce_flops():
    jx = jax.make_jaxpr(lambda x: jnp.tanh(x * 2.0).sum())(_sds((128,)))
    flops, movement = jaxpr_counts(jx)
    # mul (128) + tanh (128) + reduce_sum (128); broadcasts are movement
    assert flops >= 3 * 128
    assert movement >= 0.0


def test_movement_bytes_counted_not_flops():
    jx = jax.make_jaxpr(
        lambda x: jnp.transpose(x, (1, 0)).reshape(-1))(_sds((16, 8)))
    flops, movement = jaxpr_counts(jx)
    assert flops == 0.0
    # transpose reads+writes 512B, reshape reads+writes 512B
    assert movement == 4 * 16 * 8 * 4


def test_scan_multiplies_by_trip_count():
    def body(c, x):
        return c + x @ x, None

    def run(xs):
        return jax.lax.scan(body, jnp.zeros((8, 8), jnp.float32), xs)

    jx = jax.make_jaxpr(run)(_sds((10, 8, 8)))
    flops, _ = jaxpr_counts(jx)
    per_trip = 2 * 8 * 8 * 8 + 8 * 8  # GEMM + add
    assert flops >= 10 * per_trip


def test_fft_flops_scale_n_log_n():
    jx = jax.make_jaxpr(
        lambda x: jnp.fft.rfft(x, n=256, axis=-1))(_sds((4, 256)))
    flops, _ = jaxpr_counts(jx)
    assert flops == pytest.approx(5.0 * 256 * 8 * 4)  # 5·n·log2(n)·batch


def test_body_counts_is_abstract_and_host_code_safe():
    counts = body_counts(lambda x: jnp.exp(x), _sds((32,)))
    assert counts is not None and counts[0] >= 32
    # host code the tracer cannot enter answers None, never raises
    assert body_counts(lambda x: str(x).split(), _sds((4,))) is None


# -------------------------------------------- cost_analysis cross-check


def test_jaxpr_walk_agrees_with_backend_cost_analysis_on_gemm():
    """Capability-probe satellite: where the backend provides
    `cost_analysis`, the jaxpr FLOP walk agrees within 2× on a GEMM
    stage; where it doesn't, the walk is the source of truth and this
    test documents the fallback."""
    fn = lambda x: x @ jnp.ones((64, 32), jnp.float32)  # noqa: E731
    elem = _sds((128, 64))
    backend = xla_cost_analysis(fn, elem)
    jx_flops, _ = jaxpr_counts(jax.make_jaxpr(fn)(elem))
    assert jx_flops == 2.0 * 128 * 64 * 32
    if backend is None:
        pytest.skip("backend provides no cost_analysis — jaxpr walk is "
                    "the (only) source of truth")
    ratio = backend["flops"] / jx_flops
    assert 0.5 <= ratio <= 2.0, (backend, jx_flops)


def test_xla_cost_analysis_rejects_partial_results():
    # a host-code body cannot lower: the probe answers None, not a crash
    assert xla_cost_analysis(lambda x: str(x), _sds((4,))) is None


# ------------------------------------------------------------ time model


def test_stage_cost_is_max_of_the_two_rates():
    m = Machine(peak_flops=1e10, peak_bw=1e9)
    assert stage_cost(1e10, 0, m) == 1.0
    assert stage_cost(0, 1e9, m) == 1.0
    assert stage_cost(1e10, 2e9, m) == 2.0  # bytes side dominates
    assert stage_cost(None, None, m) == 0.0


def test_classification_flips_at_machine_balance():
    m = Machine(peak_flops=1e12, peak_bw=1e10)  # balance 100 FLOP/B
    from keystone_tpu.nodes.stats import NormalizeRows

    pipe = NormalizeRows().to_pipeline()
    applied = pipe.apply(SpecDataset((64,), count=128))
    specs, _ = spec_pass(applied.graph, {})
    est, _ = roofline_pass(applied.graph, specs, machine=m)
    assert est.stages, "NormalizeRows did not price"
    st = next(iter(est.stages.values()))
    assert st.bound == "bandwidth"  # ~2 FLOP/B << 100
    est2, _ = roofline_pass(applied.graph, specs,
                            machine=Machine(1e12, 1e13))  # balance 0.1
    st2 = next(iter(est2.stages.values()))
    assert st2.bound == "compute"
    assert st2.intensity == pytest.approx(st.intensity)


def test_default_machine_reads_calibration_plumbing():
    from keystone_tpu.nodes.learning.calibrate import (
        CostWeights,
        machine_rates,
    )

    m = default_machine()
    pf, pb = machine_rates()
    assert (m.peak_flops, m.peak_bw) == (pf, pb)
    assert m.balance > 0
    # CostWeights derives peaks from weight reciprocals unless told
    w = CostWeights(1e-12, 1e-11, 1e-11)
    assert w.peak_flops == pytest.approx(1e12)
    assert w.peak_bw == pytest.approx(1e11)
    w2 = CostWeights(1e-12, 1e-11, 1e-11, peak_flops=3.0, peak_bw=4.0)
    assert (w2.peak_flops, w2.peak_bw) == (3.0, 4.0)


def test_machine_rates_honest_on_cpu_backend():
    """The CPU backend must not claim v5e analytic peaks: the machine
    balance would be ~100× off and every stage would misclassify."""
    from keystone_tpu.nodes.learning import cost_model
    from keystone_tpu.nodes.learning.calibrate import (
        CPU_PEAK_BW,
        CPU_PEAK_FLOPS,
        machine_rates,
    )

    pf, pb = machine_rates()
    if cost_model._live_platform_no_init() == "cpu" and (
            float(cost_model.CPU_WEIGHT)
            == cost_model.ANALYTIC_CPU_WEIGHT):
        assert (pf, pb) == (CPU_PEAK_FLOPS, CPU_PEAK_BW)
    assert pf < 1e15 and pb < 1e13  # sanity whatever the resolution


# ------------------------------------------------------------ graph pass


def test_roofline_pass_prices_examples_and_flags_kp801():
    pipe, spec = build_example("RandomPatchCifar")
    specs, _ = spec_pass(pipe.graph, {pipe.source: as_source_spec(spec)})
    est, diags = roofline_pass(pipe.graph, specs)
    assert est.stages and est.plan_seconds > 0
    rules = {d.rule for d in diags}
    assert "KP801" in rules and "KP803" in rules
    assert est.candidates, "the featurize chain must be a candidate"
    cand = est.candidates[0]
    assert cand["n_stages"] >= 2
    assert cand["boundary_bytes"] > 0 and cand["seconds_saved"] > 0
    # the known bandwidth-bound featurize members are in the chain
    names = {s for c in est.candidates for s in c["stages"]}
    assert {"SymmetricRectifier", "Pooler"} & names, names


def test_kp802_flags_movement_dominated_stage():
    from keystone_tpu import Transformer

    layout = Transformer.from_function(
        lambda x: jnp.transpose(x.reshape(8, 8), (1, 0)).reshape(-1),
        name="LayoutChurn")
    applied = layout.to_pipeline().apply(SpecDataset((64,), count=256))
    specs, _ = spec_pass(applied.graph, {})
    est, diags = roofline_pass(applied.graph, specs)
    kp802 = [d for d in diags if d.rule == "KP802"]
    assert kp802 and "LayoutChurn" in kp802[0].label


def _megafused_graph(shape, count):
    """A one-vertex megafused plan over a SpecDataset input — the shape
    `MegafusionRule` produces for a whole-plan fitted chain, built
    directly so the test controls the trip arithmetic."""
    from keystone_tpu.nodes.stats import NormalizeRows, SignedHellingerMapper
    from keystone_tpu.workflow.fusion_rule import MegafusedPlanOperator

    pipe = NormalizeRows().to_pipeline() >> SignedHellingerMapper()
    applied = pipe.apply(SpecDataset(shape, count=count))
    graph = applied.graph
    # collapse the two stage vertices into one megafused operator, as
    # MegafusionRule would for the fitted whole-plan chain
    head = next(n for n in graph.operators
                if isinstance(graph.get_operator(n), NormalizeRows))
    tail = next(n for n in graph.operators
                if isinstance(graph.get_operator(n),
                              SignedHellingerMapper))
    data_dep = graph.get_dependencies(head)[0]
    mega = MegafusedPlanOperator(
        [NormalizeRows(), SignedHellingerMapper()])
    graph = graph.set_operator(head, mega)
    graph = graph.replace_dependency(tail, head)
    graph = graph.set_dependencies(head, (data_dep,))
    graph = graph.set_dependencies(tail, ())
    graph = graph.remove_node(tail)
    return graph


def test_kp804_flags_underfilled_megafused_scan():
    graph = _megafused_graph((4,), count=8)
    specs, _ = spec_pass(graph, {})
    est, diags = roofline_pass(graph, specs, chunk_rows=8)
    kp804 = [d for d in diags if d.rule == "KP804"]
    assert kp804, [str(d) for d in diags]
    assert "chunk_size" in kp804[0].message
    # a fat chunk amortizes: the lint stays quiet
    graph2 = _megafused_graph((1 << 14,), count=1 << 16)
    specs2, _ = spec_pass(graph2, {})
    _, diags2 = roofline_pass(graph2, specs2, chunk_rows=1 << 16)
    assert not [d for d in diags2 if d.rule == "KP804"]


def test_fused_chain_trail_is_priced_per_stage():
    from keystone_tpu.nodes.stats import NormalizeRows, SignedHellingerMapper
    from keystone_tpu.workflow.fusion_rule import NodeFusionRule

    pipe = (NormalizeRows().to_pipeline() >> SignedHellingerMapper())
    applied = pipe.apply(SpecDataset((64,), count=128))
    graph, _ = NodeFusionRule().apply((applied.graph, {}))
    specs, _ = spec_pass(graph, {})
    est, _ = roofline_pass(graph, specs)
    fused = [s for s in est.stages.values() if s.trail]
    assert fused, "the fused chain must carry a per-stage trail"
    st = fused[0]
    assert len(st.trail) == 2
    assert all(r["predicted_seconds"] > 0 for r in st.trail)
    assert st.internal_boundary_bytes > 0
    assert st.flops == pytest.approx(
        sum(r["flops"] for r in st.trail))


def test_validate_full_carries_roofline():
    pipe, spec = build_example("MnistRandomFFT")
    report = pipe.validate(spec, level="full", raise_on_error=False)
    assert report.roofline is not None
    assert report.roofline.stages
    assert report.by_rule("KP803")
    # level below full has no roofline
    lite = pipe.validate(spec, level="memory", raise_on_error=False)
    assert lite.roofline is None


def test_chain_predicted_seconds_on_bound_graph():
    from keystone_tpu import Dataset
    from keystone_tpu.nodes.stats import NormalizeRows

    applied = NormalizeRows().to_pipeline().apply(
        Dataset.from_numpy(np.ones((32, 8), np.float32)))
    nodes = sorted(applied.graph.operators, key=lambda n: n.id)
    seconds = chain_predicted_seconds(applied.graph, nodes)
    assert seconds is not None and seconds > 0
    # an unpriceable chain answers None, never raises
    assert chain_predicted_seconds(applied.graph, []) is None


# ------------------------------------------------------------------- CLI


@pytest.mark.lint
def test_explain_roofline_cli_json_all_examples():
    import os

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "keystone_tpu.analysis",
         "--explain-roofline", "--json"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["machine"]["balance"] > 0
    examples = payload["examples"]
    assert {e["example"] for e in examples} == set(EXAMPLES)
    candidates = 0
    for e in examples:
        assert "build_error" not in e, e
        assert not [f for f in e["findings"] if f["severity"] == "ERROR"]
        for s in e["stages"]:
            assert s["flops"] >= 0 and s["hbm_bytes"] > 0
            assert s["bound"] in ("compute", "bandwidth")
            assert s["predicted_seconds"] > 0
            assert "intensity" in s
        candidates += len(e["candidates"])
    assert candidates >= 1, "no KP801 candidate on any example"


# --------------------------------------------------------- reconciliation


def _traced_mnist_run(tmp_path):
    """One MnistRandomFFT fit+apply run with the trace armed; returns
    the parsed trace."""
    from keystone_tpu.dispatch_bench import EXAMPLES as BENCH_EXAMPLES
    from keystone_tpu.telemetry import trace_run

    path = tmp_path / "mnist_roofline.json"
    with trace_run(str(path)):
        predictor, train, test = BENCH_EXAMPLES["MnistRandomFFT"]()
        predictor(train).get()
        predictor(test).get()
    return json.loads(path.read_text())


def test_trace_embeds_roofline_and_reconciles(tmp_path):
    from keystone_tpu.analysis.reconcile import (
        cost_model_drift,
        reconcile_roofline,
    )

    trace = _traced_mnist_run(tmp_path)
    roof = trace["keystone"].get("roofline")
    assert roof and roof["per_node"], "executor did not embed roofline"
    assert roof["peak_flops"] > 0 and roof["peak_bw"] > 0
    assert roof["plan_predicted_seconds"] > 0
    for rec in roof["per_node"].values():
        assert rec["predicted_seconds"] > 0
        assert rec["bound"] in ("compute", "bandwidth")

    rr = reconcile_roofline(trace)
    assert rr["stages_joined"] > 0, rr
    joined = [r for r in rr["rows"] if r["residual"] is not None]
    assert joined
    for r in joined:
        assert r["predicted_seconds"] is not None
        assert r["observed_seconds"] > 0
    assert rr["flops_residual_seconds"] == pytest.approx(
        rr["predicted_seconds"] - rr["observed_seconds"])

    # the drift report carries the flops residual + an implied cpu bound
    drift = cost_model_drift(trace)
    assert drift["roofline"] is not None
    assert drift["roofline"]["stages_joined"] == rr["stages_joined"]
    cpu_row = next(r for r in drift["rows"] if r["weight"] == "cpu_weight")
    assert cpu_row["implied"] is not None and cpu_row["implied"] > 0


def test_reconcile_roofline_tolerates_missing_sides():
    from keystone_tpu.analysis.reconcile import (
        cost_model_drift,
        format_drift,
        reconcile_roofline,
    )

    # no roofline metadata, no spans: empty join, no crash
    empty = reconcile_roofline({"traceEvents": []})
    assert empty["stages_joined"] == 0 and empty["rows"] == []
    assert empty["flops_residual_seconds"] is None
    # prediction with no matching span stays visible with residual=None
    one_sided = reconcile_roofline({
        "traceEvents": [],
        "keystone": {"roofline": {"per_node": {
            "3:Stage": {"label": "Stage", "vertex": 3, "flops": 10.0,
                        "bound": "compute", "predicted_seconds": 1e-6},
        }}},
    })
    assert one_sided["rows"][0]["residual"] is None
    # and the drift report renders either way
    text = format_drift(cost_model_drift({"traceEvents": []}))
    assert "cost-model drift" in text and "flops residual" not in text


def test_ledger_cli_renders_drift_with_roofline(tmp_path):
    """--ledger over a run whose trace embeds roofline metadata renders
    the flops-residual line; a run with NO spans still renders."""
    from keystone_tpu.telemetry import ledger
    from keystone_tpu.telemetry.__main__ import main as telemetry_main

    ledger.clear_session()
    trace = _traced_mnist_run(tmp_path)
    # write the trace back as the --ledger artifact (decision-carrying)
    art = tmp_path / "run_trace.json"
    art.write_text(json.dumps(trace))
    rc = telemetry_main(["--ledger", str(art)])
    assert rc == 0
    # spans stripped: the join is empty but rendering must not crash
    bare = dict(trace)
    bare["traceEvents"] = []
    art2 = tmp_path / "run_no_spans.json"
    art2.write_text(json.dumps(bare))
    assert telemetry_main(["--ledger", str(art2)]) == 0


def test_fusion_decisions_record_predicted_seconds(tmp_path):
    from keystone_tpu.telemetry import ledger, trace_run

    ledger.clear_session()
    mark = ledger.session_mark()
    from keystone_tpu import Dataset
    from keystone_tpu.nodes.stats import NormalizeRows, SignedHellingerMapper

    with trace_run(str(tmp_path / "t.json")):
        pipe = (NormalizeRows().to_pipeline() >> SignedHellingerMapper())
        pipe(Dataset.from_numpy(
            np.abs(np.random.rand(64, 8)).astype(np.float32))).get()
    recs = [d for d in ledger.session_since(mark)
            if d["kind"] in ("fusion", "megafusion")]
    assert recs
    assert any("predicted_seconds" in d["predicted"] for d in recs), recs
    for d in recs:
        ps = d["predicted"].get("predicted_seconds")
        if ps is not None:
            assert ps > 0


def test_kp804_constant_is_sane():
    assert 1e-6 < DISPATCH_OVERHEAD_S < 1e-3
