"""Unified plan optimizer tests (keystone_tpu/analysis/plan_ir.py +
workflow.optimizer.UnifiedPlannerRule).

The acceptance contract: the joint {placement × dtype × chunk × cache}
plan scores ≤ the sequential PR-13 composition in predicted seconds on
the example pipelines (same scoring function on both sides), strictly <
on at least 2; ``KEYSTONE_UNIFIED_PLANNER=0`` — and each legacy kill
switch under it — reproduces the PR-13 plan bit-for-bit (same vertices,
operators, deps, tags); joint-finds-no-win cases are strict no-ops;
joint-on outputs stay allclose-identical to serial unfused at multiple
AND ragged counts; the jointly chosen chunk size lifts megafused scan
trips above the KP804 dispatch floor without tripping KP600; and every
enforced joint decision has a matching ledger record naming the unified
planner.
"""

import json

import numpy as np
import pytest

from keystone_tpu.analysis import as_source_spec
from keystone_tpu.analysis.examples import build_example
from keystone_tpu.analysis.plan_ir import (
    CHUNK_LADDER,
    machine_from_weights,
    plan_unified,
)
from keystone_tpu.analysis.precision import precision_pass
from keystone_tpu.analysis.propagate import spec_pass
from keystone_tpu.analysis.roofline import roofline_pass
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
from keystone_tpu.nodes.learning.calibrate import CostWeights
from keystone_tpu.nodes.stats import (
    LinearRectifier,
    PaddedFFT,
    RandomSignNode,
)
from keystone_tpu.nodes.util import ClassLabelIndicatorsFromInt, MaxClassifier
from keystone_tpu.telemetry import ledger
from keystone_tpu.workflow import PipelineEnv, Transformer
from keystone_tpu.workflow.autocache import CacheMarker
from keystone_tpu.workflow.env import (
    config_override,
    planned_chunk_size,
    resolved_chunk_size,
)
from keystone_tpu.workflow.optimizer import DefaultOptimizer
from keystone_tpu.workflow.operators import DatasetOperator


def _predictor(data, labels_ds, dim=64, classes=4):
    featurizer = (RandomSignNode(dim).to_pipeline() >> PaddedFFT()
                  >> LinearRectifier(0.0))
    labels = ClassLabelIndicatorsFromInt(classes)(labels_ds)
    return featurizer.and_then(
        BlockLeastSquaresEstimator(32, num_iter=1, lam=1e-3),
        data, labels) >> MaxClassifier()


def _data(n, dim=64, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, dim).astype(np.float32),
            rng.randint(0, classes, size=n).astype(np.int32))


def _optimized_graph(applied):
    return applied.executor.optimized_graph


def _graph_shape(g):
    out = []
    for vid in sorted(g.operators, key=lambda v: v.id):
        op = g.get_operator(vid)
        out.append((vid.id, type(op).__name__,
                    tuple(d.id if hasattr(d, "id") else d
                          for d in g.get_dependencies(vid)),
                    getattr(op, "planned_out_spec", None),
                    getattr(op, "planned_precision", None)))
    return out


# ----------------------------------------------------------- the decision


def test_joint_beats_sequential_on_examples():
    """The lint.sh unified-audit gate asserted in-tree: the joint plan
    never prices worse than the sequential composition (same scorer on
    both sides), strictly wins on at least 2 of the examples, and the
    chosen dtype policies stay KP7xx-clean."""
    strict = 0
    for name in ("MnistRandomFFT", "LinearPixels", "RandomPatchCifar",
                 "TimitPipeline"):
        pipeline, source_spec = build_example(name)
        specs, _ = spec_pass(
            pipeline.graph, {pipeline.source: as_source_spec(source_spec)})
        uplan = plan_unified(pipeline.graph, specs)
        assert uplan is not None, name
        assert uplan.joint_seconds <= uplan.sequential_seconds, name
        if uplan.improved:
            strict += 1
            assert uplan.changed_kinds(), name
        if uplan.boundary_precision is not None:
            diags = precision_pass(pipeline.graph, specs,
                                   uplan.boundary_precision)
            assert not [d for d in diags if d.rule == "KP701"], (name,
                                                                 diags)
    assert strict >= 2, f"strict wins on only {strict} example(s)"


def test_sequential_is_always_a_scored_candidate():
    """The product menu the solver scores always contains the
    sequential composition — the ≤ guarantee is structural, not a
    post-hoc clamp alone — and the joint optimum entry matches the
    plan's own score."""
    pipeline, source_spec = build_example("MnistRandomFFT")
    specs, _ = spec_pass(
        pipeline.graph, {pipeline.source: as_source_spec(source_spec)})
    uplan = plan_unified(pipeline.graph, specs)
    entries = {c["entry"]: c for c in uplan.scored_candidates}
    assert "sequential" in entries
    assert entries["sequential"]["predicted_seconds"] == pytest.approx(
        uplan.sequential_seconds)
    assert "joint_optimum" in entries
    assert entries["joint_optimum"]["predicted_seconds"] == pytest.approx(
        uplan.joint_seconds)


def test_recalibrated_weights_change_the_machine():
    """A `CostWeights` (the `drift_cost_weights` shape) recalibrates
    the time model's peaks: scoring under a 10× slower memory system
    scales the bandwidth-bound predictions up."""
    pipeline, source_spec = build_example("MnistRandomFFT")
    specs, _ = spec_pass(
        pipeline.graph, {pipeline.source: as_source_spec(source_spec)})
    base = plan_unified(pipeline.graph, specs)
    slow = CostWeights(cpu_weight=1.0 / 5.0e10, mem_weight=10.0 / 2.0e10,
                       network_weight=1e-11)
    m = machine_from_weights(slow)
    assert m.peak_bw == pytest.approx(2.0e9)
    recal = plan_unified(pipeline.graph, specs, weights=slow)
    assert recal.sequential_seconds > base.sequential_seconds


# ------------------------------------------------------------ kill switch


@pytest.mark.parametrize("legacy", [
    {},
    {"megafusion": False},
    {"sharding_planner": False},
    {"precision_planner": False},
    {"megafusion": False, "sharding_planner": False,
     "precision_planner": False},
])
def test_kill_switch_matrix_reproduces_pr13_plan_bit_for_bit(legacy):
    """KEYSTONE_UNIFIED_PLANNER=0 (config channel), combined with each
    legacy kill switch, yields exactly the PR-13 plan the pre-unified
    optimizer constructs under the same switches: same vertices, same
    operator classes, same dependencies, same tags, no cache markers,
    no planned chunk."""
    X, y = _data(256)

    def optimize(optimizer=None):
        PipelineEnv.reset()
        if optimizer is not None:
            PipelineEnv.get().set_optimizer(optimizer)
        data = Dataset.from_numpy(X)
        labels = Dataset.from_numpy(y)
        applied = _predictor(data, labels)(data)
        return _optimized_graph(applied)

    try:
        with config_override(unified_planner=False,
                             unified_min_savings_seconds=0.0, **legacy):
            g_off = optimize()
        # the pre-unified optimizer construction must agree with the
        # kill switch exactly
        with config_override(unified_planner=True,
                             unified_min_savings_seconds=0.0, **legacy):
            g_ctor = optimize(DefaultOptimizer(unified_planner=False))
    finally:
        PipelineEnv.reset()

    off = _graph_shape(g_off)
    assert off == _graph_shape(g_ctor)
    assert not any(t[1] == "CacheMarker" for t in off)
    assert planned_chunk_size() is None


def test_unified_on_enforces_and_kill_switch_removes_it():
    """Sanity that the matrix above is comparing against a live
    deviation: with the floor dropped the unified planner enforces
    cache points (the graphs differ), and the kill switch removes every
    one of them."""
    X, y = _data(256)
    try:
        PipelineEnv.reset()
        with config_override(unified_min_savings_seconds=0.0):
            data = Dataset.from_numpy(X)
            labels = Dataset.from_numpy(y)
            g_on = _optimized_graph(_predictor(data, labels)(data))
        PipelineEnv.reset()
        with config_override(unified_planner=False,
                             unified_min_savings_seconds=0.0):
            data = Dataset.from_numpy(X)
            labels = Dataset.from_numpy(y)
            g_off = _optimized_graph(_predictor(data, labels)(data))
    finally:
        PipelineEnv.reset()
    on_markers = [v for v in g_on.operators
                  if isinstance(g_on.get_operator(v), CacheMarker)]
    assert on_markers, "unified planner enforced no cache point"
    assert not [v for v in g_off.operators
                if isinstance(g_off.get_operator(v), CacheMarker)]


def test_no_win_is_strict_noop():
    """A plan with no fan-out, no recompute weight, counts at the
    chunk size, and one device gives the joint solver nothing to win:
    the optimized graph is bit-for-bit the PR-13 one even with the
    enforcement floor dropped."""
    X = np.arange(64, dtype=np.float32).reshape(16, 4)
    pipe = (Transformer.from_function(lambda x: x * 2.0).to_pipeline()
            >> Transformer.from_function(lambda x: x + 1.0))

    def optimize(**cfg):
        PipelineEnv.reset()
        with config_override(unified_min_savings_seconds=0.0, **cfg):
            applied = pipe(Dataset.from_numpy(X))
            return _optimized_graph(applied)

    try:
        g_on = optimize()
        g_off = optimize(unified_planner=False)
    finally:
        PipelineEnv.reset()
    assert _graph_shape(g_on) == _graph_shape(g_off)
    assert planned_chunk_size() is None


@pytest.mark.parametrize("count", [64, 43])
def test_unified_on_outputs_allclose_serial_unfused(count):
    """Joint-on outputs (floor dropped, enforcement live) are allclose
    to serial unfused execution at a multiple AND a ragged count."""
    X, y = _data(count)
    try:
        PipelineEnv.reset()
        with config_override(unified_min_savings_seconds=0.0):
            data = Dataset.from_numpy(X)
            labels = Dataset.from_numpy(y)
            out = np.asarray(_predictor(data, labels)(data).get().numpy())
        PipelineEnv.reset()
        PipelineEnv.get().set_optimizer(DefaultOptimizer(
            fuse=False, sharding_planner=False, precision_planner=False,
            unified_planner=False))
        with config_override(megafusion=False, overlap=False,
                             concurrent_dispatch=False):
            data = Dataset.from_numpy(X)
            labels = Dataset.from_numpy(y)
            ref = np.asarray(_predictor(data, labels)(data).get().numpy())
    finally:
        PipelineEnv.reset()
    assert out.shape == ref.shape == (count,)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------- chunk / KP804


def _megafusable_predictor(n_train=64, d=64, k=4, seed=3):
    """The canonical megafusable apply shape (test_megafusion's
    featurize → scaler-fit → linear-fit → argmax), sized so the scan's
    per-trip work at a deliberately underfilled chunk sits below the
    KP804 dispatch floor while a full-count chunk clears it."""
    from keystone_tpu.nodes.learning import LinearMapEstimator
    from keystone_tpu.nodes.stats import NormalizeRows, StandardScaler

    rng = np.random.default_rng(seed)
    X = np.abs(rng.normal(size=(n_train, d))).astype(np.float32) + 1.0
    y = rng.integers(0, k, n_train).astype(np.int32)
    train = Dataset.from_numpy(X)
    labels = ClassLabelIndicatorsFromInt(k)(Dataset.from_numpy(y)).get()
    pipe = (NormalizeRows().to_pipeline()
            .and_then(StandardScaler(), train)
            .and_then(LinearMapEstimator(0.1), train, labels)
            >> MaxClassifier())
    return pipe, train


def test_kp804_closure_joint_chunk_lifts_scan_trips():
    """On the bench-shaped megafusable example forced to an underfilled
    chunk size, the jointly chosen chunk lifts the megafused scan's
    per-trip work above the KP804 dispatch floor without tripping the
    KP600 budget — the roofline report pins it: KP804 fires at the
    manual knob, is silent at the chosen chunk, and no budget finding
    appears."""
    from keystone_tpu.workflow.fusion_rule import MegafusedPlanOperator

    n_test, d = 1024, 64
    rng = np.random.default_rng(7)
    Xt = np.abs(rng.normal(size=(n_test, d))).astype(np.float32) + 1.0
    try:
        PipelineEnv.reset()
        with config_override(unified_min_savings_seconds=0.0,
                             chunk_size=32,
                             hbm_budget_bytes=1 << 30):
            pipe, train = _megafusable_predictor(d=d)
            pipe(train).get()  # fit run
            applied = pipe(Dataset.from_numpy(Xt))
            g = _optimized_graph(applied)
            mega = [v for v in g.operators
                    if isinstance(g.get_operator(v),
                                  MegafusedPlanOperator)]
            assert mega, "apply plan did not megafuse"
            chosen = resolved_chunk_size()
            assert chosen > 32, chosen
            assert chosen in CHUNK_LADDER
            specs, _ = spec_pass(g, {})
            _, at_knob = roofline_pass(g, specs, chunk_rows=32)
            _, at_chosen = roofline_pass(g, specs, chunk_rows=chosen)
            assert [d_ for d_ in at_knob if d_.rule == "KP804"], \
                "manual knob did not underfill — scenario is vacuous"
            assert not [d_ for d_ in at_chosen if d_.rule == "KP804"]
            # KP600 not tripped: the chunk decision respected the budget
            from keystone_tpu.analysis.memory import memory_pass

            _, mem_diags = memory_pass(g, specs)
            assert not [d_ for d_ in mem_diags if d_.rule == "KP600"]
            applied.get()  # forces under the planned chunk
    finally:
        PipelineEnv.reset()
    assert planned_chunk_size() is None  # reset cleared the decision


def test_host_only_pipeline_clears_stale_chunk_override():
    """Every path through UnifiedPlannerRule re-decides the chunk knob:
    a host-only plan (no device dataset) optimized after a chunk-
    enforcing plan must not inherit the previous pipeline's override."""
    from keystone_tpu import HostDataset
    from keystone_tpu.workflow.env import set_planned_chunk_size

    try:
        PipelineEnv.reset()
        set_planned_chunk_size(512)  # a previous plan's decision
        assert resolved_chunk_size() == 512
        pipe = Transformer.from_function(lambda x: x * 2.0).to_pipeline()
        host = HostDataset([np.ones((4,), np.float32)] * 3)
        pipe(host).get()
        assert planned_chunk_size() is None
        assert resolved_chunk_size() == 256
    finally:
        PipelineEnv.reset()


def test_unified_ownership_survives_tagfree_enforcement():
    """The sequential rules stand down on a graph the unified planner
    owns even when enforcement produced NO tagged operator copies (a
    joint win can revert the sequential placement to the defaults or
    turn a trail off) — the ownership registry, not the tag scan, is
    the signal."""
    from keystone_tpu.workflow.optimizer import (
        _UNIFIED_OWNED,
        unified_enforced,
    )

    X = np.ones((8, 4), np.float32)
    pipe = Transformer.from_function(lambda x: x * 2.0).to_pipeline()
    try:
        PipelineEnv.reset()
        applied = pipe(Dataset.from_numpy(X))
        g = _optimized_graph(applied)
        assert not unified_enforced(g)
        _UNIFIED_OWNED.add(g)
        assert unified_enforced(g)  # no tags anywhere, still owned
        assert not any(getattr(op, "planned_by_unified", False)
                       for op in g.operators.values())
    finally:
        _UNIFIED_OWNED.discard(g)
        PipelineEnv.reset()


def test_constructor_optout_clears_stale_chunk_override():
    """`DefaultOptimizer(unified_planner=False)` (the constructor
    channel, env switch untouched) must not execute under a previous
    plan's enforced chunk: the opt-out batch clears the override at
    the same point the unified rule would have re-decided it."""
    from keystone_tpu.workflow.env import set_planned_chunk_size

    X = np.ones((8, 4), np.float32)
    try:
        PipelineEnv.reset()
        set_planned_chunk_size(2048)  # a previous plan's decision
        assert resolved_chunk_size() == 2048
        PipelineEnv.get().set_optimizer(
            DefaultOptimizer(unified_planner=False))
        pipe = Transformer.from_function(lambda x: x * 2.0).to_pipeline()
        pipe(Dataset.from_numpy(X)).get()
        assert planned_chunk_size() is None
        assert resolved_chunk_size() == 256
    finally:
        PipelineEnv.reset()


def test_planned_chunk_respects_kill_switch():
    """A live planned chunk is invisible the moment the unified planner
    is switched off — KEYSTONE_UNIFIED_PLANNER=0 restores the config
    knob bit-for-bit, stale overrides included."""
    from keystone_tpu.workflow.env import set_planned_chunk_size

    try:
        set_planned_chunk_size(512)
        assert resolved_chunk_size() == 512
        with config_override(unified_planner=False):
            assert planned_chunk_size() is None
            assert resolved_chunk_size() == 256
        assert resolved_chunk_size() == 512
    finally:
        set_planned_chunk_size(None)


# ------------------------------------------------------------- the ledger


def test_enforced_joint_decisions_have_ledger_records():
    """Every enforced joint decision kind emits a ledger record naming
    the unified planner, with the product menu as its alternatives and
    predicted seconds in the shared units."""
    X, y = _data(256)
    try:
        PipelineEnv.reset()
        mark = ledger.session_mark()
        with config_override(unified_min_savings_seconds=0.0):
            data = Dataset.from_numpy(X)
            labels = Dataset.from_numpy(y)
            g = _optimized_graph(_predictor(data, labels)(data))
        decisions = [d for d in ledger.session_since(mark)
                     if d["rule"] == "UnifiedPlannerRule"]
        assert decisions, "enforcement recorded no unified decision"
        cache_vertices = {v.id for v in g.operators
                          if isinstance(g.get_operator(v), CacheMarker)}
        assert cache_vertices
        for d in decisions:
            assert d["enforced"]
            assert d["kind"] in ("placement", "precision", "chunk",
                                 "cache")
            assert d["predicted"]["seconds_saved"] > 0
            entries = {a.get("entry") for a in d["alternatives"]}
            assert "sequential" in entries, entries
        cached_recorded = set()
        for d in decisions:
            if d["kind"] == "cache":
                cached_recorded.update(int(v) for v in d["vertices"])
        # the ledger's cache record covers the vertices that were
        # actually cached (ids recorded pre-splice)
        assert cached_recorded
    finally:
        PipelineEnv.reset()


def test_diff_names_unified_planner_kill_switch():
    """--diff between a unified-on and a unified-off run names
    KEYSTONE_UNIFIED_PLANNER as the suspect for every removed joint
    decision (chunk and cache kinds included)."""
    from keystone_tpu.telemetry.ledger import diff_runs

    header_on = {"ledger_version": 1,
                 "config": {"unified_planner": True, "megafusion": True},
                 "config_env": dict(ledger.CONFIG_ENV)}
    header_off = {"ledger_version": 1,
                  "config": {"unified_planner": False,
                             "megafusion": True},
                  "config_env": dict(ledger.CONFIG_ENV)}

    def rec(kind, labels):
        return {"kind": kind, "rule": "UnifiedPlannerRule",
                "vertices": [1], "labels": labels,
                "chosen": {"entry": "joint_optimum"},
                "alternatives": [{"entry": "sequential"}],
                "predicted": {"seconds_saved": 1e-3}, "enforced": True}

    run_a = {"header": header_on, "headers": [header_on],
             "decisions": [rec("cache", ["Cache[x]"]),
                           rec("chunk", []),
                           rec("placement", ["Fused[x]"])]}
    run_b = {"header": header_off, "headers": [header_off],
             "decisions": []}
    diff = diff_runs(run_a, run_b)
    assert any(f["env"] == "KEYSTONE_UNIFIED_PLANNER"
               for f in diff["config_flips"])
    removed = {d["kind"]: d for d in diff["decisions_removed"]}
    assert set(removed) == {"cache", "chunk", "placement"}
    for d in removed.values():
        assert d["suspect_env"] == "KEYSTONE_UNIFIED_PLANNER", d


def test_autocache_greedy_emits_cache_records():
    """Satellite: `AutoCacheRule` cache-placement choices emit
    kind=``cache`` decision records with the greedy loop's own priced
    menu as the alternatives — cache points were the last unaudited
    optimizer decision."""
    from keystone_tpu.workflow.optimizer import AutoCachingOptimizer

    X, y = _data(128)
    try:
        PipelineEnv.reset()
        PipelineEnv.get().set_optimizer(AutoCachingOptimizer("greedy"))
        mark = ledger.session_mark()
        with config_override(unified_planner=False):
            data = Dataset.from_numpy(X)
            labels = Dataset.from_numpy(y)
            _predictor(data, labels)(data).get()
        cache_decs = [d for d in ledger.session_since(mark)
                      if d["kind"] == "cache"
                      and d["rule"] == "AutoCacheRule"]
        assert cache_decs, "greedy caching recorded no decision"
        for d in cache_decs:
            assert d["chosen"]["strategy"] == "greedy"
            assert d["chosen"]["mem_bytes"] >= 0
            assert d["alternatives"], d
            assert d["labels"], d
    finally:
        PipelineEnv.reset()


# ----------------------------------------------- calibration round-trip


def test_emit_calibration_round_trip(tmp_path, monkeypatch):
    """Satellite: ``--ledger <run> --emit-calibration <path>`` persists
    the drift-implied CostWeights in the tpu_calibration.json schema,
    and `machine_rates()` prefers the emitted file when
    KEYSTONE_COST_CALIBRATION points at it and the platform matches."""
    from keystone_tpu.nodes.learning import cost_model
    from keystone_tpu.nodes.learning.calibrate import machine_rates
    from keystone_tpu.telemetry.__main__ import main as telemetry_main

    # a minimal run: a ledger JSONL + a trace with one node span whose
    # seconds/out_bytes imply a mem_weight
    trace_path = tmp_path / "run.json"
    ledger_path = tmp_path / "run.ledger.jsonl"
    trace = {
        "traceEvents": [
            {"ph": "X", "cat": "node", "name": "stage", "pid": 1,
             "tid": 1, "ts": 0, "dur": 1000,
             "args": {"seconds": 0.5, "out_bytes": 1e9}},
        ],
        "keystone": {"metrics": {"counters": {}}},
    }
    trace_path.write_text(json.dumps(trace))
    header = {"ledger_version": 1, "pid": 1, "wall_epoch": 0.0,
              "trace_path": str(trace_path), "platform": "cpu",
              "config": {}, "config_env": {}}
    ledger_path.write_text(json.dumps(header) + "\n")

    out = tmp_path / "drift_calibration.json"
    rc = telemetry_main(["--ledger", str(ledger_path),
                         "--emit-calibration", str(out)])
    assert rc == 0
    payload = json.loads(out.read_text())
    # observed 0.5 s over 1e9 bytes -> implied mem_weight 5e-10
    assert payload["mem_weight"] == pytest.approx(5e-10)
    assert payload["provenance"]["source"] == "drift_cost_weights"
    assert payload["provenance"]["platform"] == "cpu"

    # the round trip: pointing the env knob at the file recalibrates
    # machine_rates (same platform), and the cache re-resolves
    monkeypatch.setenv("KEYSTONE_COST_CALIBRATION", str(out))
    monkeypatch.setattr(cost_model, "_weights_cache", None)
    peak_flops, peak_bw = machine_rates()
    assert peak_bw == pytest.approx(1.0 / payload["mem_weight"])
    assert peak_flops == pytest.approx(1.0 / payload["cpu_weight"])
    monkeypatch.setenv("KEYSTONE_COST_CALIBRATION", "analytic")
    monkeypatch.setattr(cost_model, "_weights_cache", None)
    assert machine_rates() != (peak_flops, peak_bw)
    monkeypatch.setattr(cost_model, "_weights_cache", None)
