"""Worker for the 2-process multihost test (spawned by
test_multihost_2proc.py). Each process owns 4 virtual CPU devices; the
pair forms one 8-device job connected via jax.distributed (Gloo over
localhost — the CPU stand-in for DCN).

Exercises the real multi-host code paths, not the single-process noop:
`global_data_mesh` (model axis within a host, data axis across hosts),
`dataset_from_process_local` (per-host loader splits → one global
Dataset), a cross-host collective, and a full distributed solver fit
checked against the host closed form (SURVEY §2.7 comm backend).
"""

import sys

proc_id = int(sys.argv[1])
port = sys.argv[2]

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 4)

jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=2,
    process_id=proc_id,
)

import os
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from keystone_tpu.parallel import multihost
from keystone_tpu.parallel.mesh import use_mesh

assert jax.device_count() == 8 and jax.local_device_count() == 4

mesh = multihost.global_data_mesh(model_shards=2)
assert dict(mesh.shape) == {"data": 4, "model": 2}

# --- global dataset from per-host rows + cross-host reduction ----------
rows = (
    np.arange(proc_id * 8, proc_id * 8 + 8, dtype=np.float32).reshape(8, 1)
    * np.ones((1, 4), np.float32)
)
ds = multihost.dataset_from_process_local(rows, mesh=mesh)
total = float(jax.jit(lambda x: x.sum())(ds.array))
want = float(np.arange(16, dtype=np.float32).sum() * 4)
assert abs(total - want) < 1e-3, (total, want)

# --- distributed solver fit vs host closed form ------------------------
rng = np.random.default_rng(0)  # same seed on both hosts: same problem
n_global, d, k, lam = 64, 6, 3, 1e-2
X = rng.normal(size=(n_global, d)).astype(np.float32)
W_true = rng.normal(size=(d, k)).astype(np.float32)
Y = (X @ W_true + 0.01 * rng.normal(size=(n_global, k))).astype(np.float32)

lo, hi = proc_id * (n_global // 2), (proc_id + 1) * (n_global // 2)
with use_mesh(mesh):
    Xds = multihost.dataset_from_process_local(X[lo:hi], mesh=mesh)
    Yds = multihost.dataset_from_process_local(Y[lo:hi], mesh=mesh)

    from keystone_tpu.nodes.learning import LinearMapEstimator

    model = LinearMapEstimator(lam=lam, fit_intercept=False).fit(Xds, Yds)
    W = np.asarray(model.W)

W_ref = np.linalg.solve(X.T @ X + lam * np.eye(d), X.T @ Y)
err = np.abs(W - W_ref).max() / max(np.abs(W_ref).max(), 1e-9)
assert err < 5e-3, err

# --- BCD block solver across hosts (scan + psum over the DCN link) -----
with use_mesh(mesh):
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator

    bcd = BlockLeastSquaresEstimator(block_size=3, num_iter=25, lam=lam).fit(
        Xds, Yds
    )
    Wb = np.asarray(bcd.W)[:d]  # strip intercept row if present
err_b = np.abs(Wb - W_ref).max() / max(np.abs(W_ref).max(), 1e-9)
assert err_b < 5e-2, err_b

# --- class-weighted BCD across hosts (SURVEY §2.7 class-partition row) --
# One-hot ±1 labels with mixture_weight=0.5 so the per-class weighted
# Gram path (class counts, per-class covariance blend) really runs;
# the cross-host fit must match a single-host fit of the same global
# problem (sharding changes layout, not math).
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.learning import BlockWeightedLeastSquaresEstimator
from keystone_tpu.parallel.mesh import make_mesh

cls = (np.arange(n_global) % 3)
Yc = (2.0 * np.eye(3, dtype=np.float32)[cls] - 1.0).astype(np.float32)
with use_mesh(mesh):
    Ycds = multihost.dataset_from_process_local(Yc[lo:hi], mesh=mesh)
    bwls = BlockWeightedLeastSquaresEstimator(
        d, num_iter=8, lam=lam, mixture_weight=0.5
    ).fit(Xds, Ycds)
    Ww = np.asarray(bwls.W)
with use_mesh(make_mesh(jax.local_devices()[:1])):
    bwls1 = BlockWeightedLeastSquaresEstimator(
        d, num_iter=8, lam=lam, mixture_weight=0.5
    ).fit(Dataset(X), Dataset(Yc))
    Ww1 = np.asarray(bwls1.W)
err_w = np.abs(Ww - Ww1).max() / max(np.abs(Ww1).max(), 1e-9)
assert err_w < 1e-3, f"cross-host BWLS diverged from single-host: {err_w}"

# --- distributed PCA (TSQR) across hosts -------------------------------
# The per-shard QR runs on every device of both hosts; the R-combine and
# SVD are replicated. Principal subspace must match the host-side SVD of
# the same global matrix (columns up to the sign convention, which
# _sign_convention pins).
from keystone_tpu.nodes.learning import DistributedPCAEstimator

rng_p = np.random.default_rng(2)
Xp = (rng_p.normal(size=(48, 5)) * np.array([4.0, 2.0, 1.0, 0.5, 0.1])).astype(
    np.float32
)
lo_p, hi_p = proc_id * 24, (proc_id + 1) * 24
with use_mesh(mesh):
    Xpds = multihost.dataset_from_process_local(Xp[lo_p:hi_p], mesh=mesh)
    V = np.asarray(DistributedPCAEstimator(dims=3).fit(Xpds).components)
Xc = Xp - Xp.mean(axis=0)
_, _, Vt_ref = np.linalg.svd(Xc, full_matrices=False)
V_ref = Vt_ref.T[:, :3]
# compare subspaces column-by-column up to sign
for j in range(3):
    dot = abs(float(V[:, j] @ V_ref[:, j]))
    assert dot > 0.999, f"distributed PCA col {j} off: |cos|={dot}"

# --- kernel ridge regression across hosts ------------------------------
# XOR-style task (KernelModelSuite.scala:13-39): linearly inseparable,
# so success requires the kernel path — permuted column blocks, the
# treeReduce-analog psum of K·alpha, and the distributed residual — to
# work over the cross-host data axis.
rng_k = np.random.default_rng(1)
nk = 32
Xk = rng_k.uniform(-1, 1, size=(nk, 2)).astype(np.float32)
Yk = np.where((Xk[:, 0] > 0) ^ (Xk[:, 1] > 0), 1.0, -1.0).astype(
    np.float32
).reshape(-1, 1)
lo_k, hi_k = proc_id * (nk // 2), (proc_id + 1) * (nk // 2)
with use_mesh(mesh):
    from keystone_tpu.nodes.learning import KernelRidgeRegression

    Xkds = multihost.dataset_from_process_local(Xk[lo_k:hi_k], mesh=mesh)
    Ykds = multihost.dataset_from_process_local(Yk[lo_k:hi_k], mesh=mesh)
    krr = KernelRidgeRegression(
        gamma=2.0, lam=1e-2, block_size=8, num_epochs=4
    ).fit(Xkds, Ykds)
    out = krr(Xkds).get().array
    # the global prediction array spans both hosts; reduce to a fully
    # replicated scalar on device instead of fetching non-addressable
    # shards to the host
    import jax.numpy as jnp

    acc = float(
        jax.jit(lambda p, y: (jnp.sign(p) == y).mean())(out, Ykds.array)
    )
assert acc >= 0.9, f"multihost KRR failed to learn XOR: acc={acc}"

# --- the full north-star pipeline across hosts -------------------------
# build_pipeline (PixelScaler → folded-ZCA Convolver → SymmetricRectifier
# → Pooler → StandardScaler → BCD solve → MaxClassifier) fit and applied
# with the training images dp-sharded ACROSS the two processes — the
# multihost analog of the driver's single-process dryrun_multichip.
from keystone_tpu.loaders.cifar_loader import synthetic_cifar
from keystone_tpu.pipelines.random_patch_cifar import (
    RandomPatchCifarConfig,
    build_pipeline,
)

n_img = 64  # per the global job; each process contributes half
# generate on a LOCAL 1-device mesh so the host copy below is
# addressable; same seed on both hosts -> same global data
local_mesh = make_mesh(jax.local_devices()[:1])
tr, _ = synthetic_cifar(n_img, 8, seed=5, mesh=local_mesh)
imgs = np.asarray(tr.data.numpy())
labs = np.asarray(tr.labels.numpy())
lo_i, hi_i = proc_id * (n_img // 2), (proc_id + 1) * (n_img // 2)
with use_mesh(mesh):
    from keystone_tpu.loaders.csv_loader import LabeledData

    tr_ds = LabeledData(
        data=multihost.dataset_from_process_local(imgs[lo_i:hi_i], mesh=mesh),
        labels=multihost.dataset_from_process_local(labs[lo_i:hi_i], mesh=mesh),
    )
    config = RandomPatchCifarConfig(
        num_filters=16, block_size=64, microbatch=32, sample_patches=2000
    )
    predictor = build_pipeline(tr_ds, config)
    pred_arr = predictor(tr_ds.data).get().array
    train_acc = float(
        jax.jit(lambda p, y: (p == y).mean())(pred_arr, tr_ds.labels.array)
    )
# the synthetic default task is separable: the cross-host fit must
# reach high train accuracy or the distributed pipeline is broken
assert train_acc >= 0.9, f"multihost pipeline train acc {train_acc}"

# --- run_fused across hosts --------------------------------------------
# the whole-fit-as-one-XLA-execution path under a cross-host mesh: the
# single program's featurize/scaler/BCD all run SPMD over both
# processes' devices
from keystone_tpu.pipelines.random_patch_cifar import run_fused

with use_mesh(mesh):
    tr_ds2 = LabeledData(
        data=multihost.dataset_from_process_local(imgs[lo_i:hi_i], mesh=mesh),
        labels=multihost.dataset_from_process_local(labs[lo_i:hi_i], mesh=mesh),
    )
    res_fused = run_fused(tr_ds2, tr_ds2, config)
assert res_fused["train_error"] <= 0.1, (
    f"multihost fused fit train_error {res_fused['train_error']}")

# --- dp-sharded sparse iterative L-BFGS across hosts -------------------
# rows shard over the cross-host 'data' axis; every row-space reduction
# (gradient, colsum, line-search inner products) psums over the Gloo
# link — the reference's treeReduce-to-master for sparse gradients
# (LBFGS.scala:97-103) as a true multi-process collective
import scipy.sparse as sp

from keystone_tpu.data.sparse import SparseDataset
from keystone_tpu.nodes.learning import SparseLBFGSwithL2

rng_s = np.random.default_rng(5)  # same seed both hosts: same problem
n_s, d_s, k_s = 600, 32, 2
dense_s = (rng_s.normal(size=(n_s, d_s))
           * (rng_s.random((n_s, d_s)) < 0.15)).astype(np.float32)
Ys = rng_s.normal(size=(n_s, k_s)).astype(np.float32)
with use_mesh(mesh):
    # host CSR + host labels (the sparse fit path is host-input by
    # design; a cross-host Dataset would not be host-fetchable)
    m_sp = SparseLBFGSwithL2(lam=1.0, num_iters=50, method="iterative").fit(
        SparseDataset(sp.csr_matrix(dense_s)), Ys)
xm_s, ym_s = dense_s.mean(0), Ys.mean(0)
Xc_s, Yc_s = dense_s - xm_s, Ys - ym_s
W_sp_ref = np.linalg.solve(Xc_s.T @ Xc_s + np.eye(d_s), Xc_s.T @ Yc_s)
err_sp = np.abs(np.asarray(m_sp.W) - W_sp_ref).max() / max(
    np.abs(W_sp_ref).max(), 1e-9)
assert err_sp < 5e-3, f"multihost sparse L-BFGS diverged: {err_sp}"

multihost.barrier()
print(f"[{proc_id}] MULTIHOST_OK", flush=True)
