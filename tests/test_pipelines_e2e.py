"""End-to-end smoke tests for every example app on synthetic data
(small configs; the CLI registry is exercised too)."""

import numpy as np
import pytest


def test_timit_pipeline():
    from keystone_tpu.pipelines.timit import TimitConfig, run

    r = run(TimitConfig(num_cosines=512, n_synth=1500, synth_dim=128, num_classes=8,
                        block_size=256))
    assert r["test_accuracy"] > 0.9, r["summary"]


def test_newsgroups_pipeline():
    from keystone_tpu.pipelines.text_pipelines import NewsgroupsConfig, run_newsgroups

    r = run_newsgroups(NewsgroupsConfig(n_synth=200))
    assert r["test_accuracy"] > 0.9, r["summary"]


def test_amazon_pipeline():
    from keystone_tpu.pipelines.text_pipelines import AmazonReviewsConfig, run_amazon

    r = run_amazon(AmazonReviewsConfig(n_synth=200))
    assert r["test_accuracy"] > 0.9


def test_stupid_backoff_pipeline():
    from keystone_tpu.pipelines.text_pipelines import (
        StupidBackoffConfig,
        run_stupid_backoff,
    )

    r = run_stupid_backoff(StupidBackoffConfig(n_synth=50))
    assert np.isfinite(r["mean_log_score"])
    assert r["num_trigrams"] > 0


def test_linear_pixels():
    from keystone_tpu.pipelines.cifar_variants import (
        LinearPixelsConfig,
        run_linear_pixels,
    )

    r = run_linear_pixels(LinearPixelsConfig(synth_train=300, synth_test=80))
    assert r["test_accuracy"] > 0.8


def test_random_cifar_kernel():
    from keystone_tpu.pipelines.cifar_variants import (
        RandomPatchCifarKernelConfig,
        run_random_patch_cifar_kernel,
    )

    r = run_random_patch_cifar_kernel(
        RandomPatchCifarKernelConfig(
            synth_train=240, synth_test=60, num_filters=48, sample_patches=5000,
            microbatch=64, kernel_block=128,
        )
    )
    assert r["test_accuracy"] > 0.9


def test_random_patch_cifar_augmented():
    from keystone_tpu.pipelines.cifar_variants import (
        RandomPatchCifarAugmentedConfig,
        run_random_patch_cifar_augmented,
    )

    r = run_random_patch_cifar_augmented(
        RandomPatchCifarAugmentedConfig(
            synth_train=200, synth_test=50, num_filters=48, sample_patches=5000,
            microbatch=64, block_size=512,
        )
    )
    assert r["test_accuracy"] > 0.85


def test_random_patch_cifar_augmented_kernel(tmp_path, monkeypatch):
    """The 13th app (RandomPatchCifarAugmentedKernel.scala:1-190):
    augmented featurization + flips + shuffle + KRR with checkpoint dir
    + flip-augmented test eval."""
    import os

    from keystone_tpu.pipelines.cifar_variants import (
        RandomPatchCifarAugmentedKernelConfig,
        run_random_patch_cifar_augmented_kernel,
    )

    # the solver removes its checkpoint on successful completion, so
    # observe the atomic os.replace publishes to prove --checkpoint-dir
    # was threaded through to the KRR block loop
    writes = []
    real_replace = os.replace
    monkeypatch.setattr(
        os, "replace",
        lambda src, dst: (writes.append(dst), real_replace(src, dst))[1],
    )
    r = run_random_patch_cifar_augmented_kernel(
        RandomPatchCifarAugmentedKernelConfig(
            synth_train=200, synth_test=50, num_filters=48, sample_patches=5000,
            microbatch=64, kernel_block=128, gamma=2e-3, lam=0.1,
            checkpoint_dir=str(tmp_path), blocks_before_checkpoint=2,
        )
    )
    assert r["test_accuracy"] > 0.85
    ckpt_writes = [d for d in writes if str(tmp_path) in str(d)]
    assert ckpt_writes, "KRR wrote no checkpoints under --checkpoint-dir"
    # and the completed fit cleaned its checkpoint up
    assert not any(f.startswith("krr_") for f in os.listdir(tmp_path))


def test_voc_sift_fisher():
    from keystone_tpu.pipelines.voc_sift_fisher import VOCSIFTFisherConfig, run

    r = run(VOCSIFTFisherConfig(n_synth=30, num_classes=4, gmm_k=4, pca_dims=16))
    assert r["map"] > 0.6


def test_imagenet_sift_lcs_fv():
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
        ImageNetSiftLcsFVConfig,
        run,
    )

    r = run(ImageNetSiftLcsFVConfig(n_synth=40, num_classes=5, gmm_k=4, pca_dims=16))
    assert r["test_accuracy"] > 0.6


def test_cli_registry_lists_and_dispatches(capsys):
    from keystone_tpu.__main__ import main

    assert main(["--help"]) == 0
    out = capsys.readouterr().out
    assert "pipelines.images.cifar.RandomPatchCifar" in out
    assert main(["NoSuchPipeline"]) == 2


def test_voc_sideband_model_files(tmp_path):
    """Reference --pcaFile/--gmm*File flags (VOCSIFTFisher.scala:49-67):
    precomputed PCA + GMM load from CSV and skip fitting."""
    import numpy as np

    from keystone_tpu.pipelines.voc_sift_fisher import VOCSIFTFisherConfig, run

    d, p, k = 128, 8, 4  # SIFT dim, PCA dims, GMM components
    rng = np.random.default_rng(0)
    # reference on-disk layouts: PCA is (k x d) (csvread(...).t at
    # VOCSIFTFisher.scala:52), GMM means/vars are dims x clusters
    pca = rng.normal(size=(p, d)).astype(np.float32)
    np.savetxt(tmp_path / "pca.csv", pca, delimiter=",")
    np.savetxt(tmp_path / "m.csv", rng.normal(size=(p, k)), delimiter=",")
    np.savetxt(tmp_path / "v.csv", rng.uniform(0.5, 1.5, size=(p, k)), delimiter=",")
    np.savetxt(tmp_path / "w.csv", np.full(k, 1.0 / k), delimiter=",")

    cfg = VOCSIFTFisherConfig(
        num_classes=3, n_synth=9, gmm_k=k, pca_dims=p,
        pca_file=str(tmp_path / "pca.csv"),
        gmm_mean_file=str(tmp_path / "m.csv"),
        gmm_var_file=str(tmp_path / "v.csv"),
        gmm_wts_file=str(tmp_path / "w.csv"),
    )
    result = run(cfg)
    assert np.isfinite(result["map"])
    assert len(result["aps"]) == 3
