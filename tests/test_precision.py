"""Mixed-precision policy pass tests (keystone_tpu/analysis/precision.py
+ workflow.optimizer.PrecisionPlannerRule).

The acceptance contract (ISSUE 10): the planner's priced boundary bytes
never exceed the all-f32 default on any example and strictly beat it on
≥ 2; policy-on outputs are allclose to the serial unfused f32 reference
within the declared tolerance band at multiple AND ragged counts;
``KEYSTONE_PRECISION_PLANNER=0`` reproduces the PR-9 plan bit-for-bit;
chosen casts are present in the fused/megafused program jaxpr with the
program's visible output dtype unchanged; intolerant solver boundaries
stay f32; the KP2xx/KP600 memory models re-price under the decided
dtypes (bf16 halves exactly the chosen float boundaries — and the
static model reads REAL leaf dtypes, pinned by the uint8
static-vs-observed reconciliation test); and warm runs stay 0-cold
under an enforced policy.
"""

import numpy as np
import pytest
import jax

from keystone_tpu.analysis import SpecDataset, as_source_spec
from keystone_tpu.analysis.diagnostics import Severity
from keystone_tpu.analysis.examples import EXAMPLES, build_example
from keystone_tpu.analysis.memory import memory_pass
from keystone_tpu.analysis.precision import (
    CAST_PENALTY_BYTES,
    DEFAULT_BAND_ATOL,
    DEFAULT_BAND_RTOL,
    EXACT,
    POLICY_BF16,
    POLICY_F32,
    POLICY_F32_BF16,
    TOLERANT,
    PrecisionPlan,
    _PrecisionModel,
    _plan_path,
    plan_precision,
    plan_stage_precision,
    policy_nbytes,
    precision_pass,
    probe_tolerance,
    reprice_memory,
    shrink_to_band,
)
from keystone_tpu.analysis.propagate import spec_pass
from keystone_tpu.analysis.specs import DataSpec, shape_struct
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
from keystone_tpu.nodes.stats import LinearRectifier, RandomSignNode
from keystone_tpu.nodes.stats.normalization import (
    NormalizeRows,
    SignedHellingerMapper,
)
from keystone_tpu.nodes.util import (
    Cacher,
    ClassLabelIndicatorsFromInt,
    MaxClassifier,
)
from keystone_tpu.nodes.util.fusion import FusedBatchTransformer
from keystone_tpu.parallel import mesh as meshlib
from keystone_tpu.workflow import PipelineEnv
from keystone_tpu.workflow.env import config_override
from keystone_tpu.workflow.fusion_rule import (
    FusedChainOperator,
    MegafusedPlanOperator,
)
from keystone_tpu.workflow.graph import NodeId
from keystone_tpu.workflow.optimizer import DefaultOptimizer


def _source(shape, dtype, count):
    return as_source_spec(SpecDataset(shape, dtype, count=count).spec)


def _raw_graph(name):
    pipeline, source_spec = build_example(name)
    graph = pipeline.graph
    specs, _ = spec_pass(graph, {pipeline.source: as_source_spec(source_spec)})
    return graph, specs


def _tolerant_chain_pipeline(count=4, dim=8):
    from keystone_tpu.nodes.stats import LinearRectifier

    pipe = (SignedHellingerMapper().to_pipeline() >> NormalizeRows()
            >> LinearRectifier(0.0))
    graph = pipe.graph
    specs, _ = spec_pass(
        graph, {pipe.source: _source((dim,), np.float32, count)})
    return graph, specs


# -------------------------------------------------------- decision core


def test_policy_nbytes_is_dtype_aware():
    """bf16 storage halves float32 leaves ONLY; uint8 loader stages and
    int32 label stages keep their real 1/4-byte itemsize — the
    dtype-aware KP2xx arithmetic."""
    f32 = DataSpec(element=shape_struct((16,), np.float32), count=10)
    u8 = DataSpec(element=shape_struct((16,), np.uint8), count=10)
    i32 = DataSpec(element=shape_struct((16,), np.int32), count=10)
    assert policy_nbytes(f32, POLICY_F32) == 16 * 4 * 10
    assert policy_nbytes(f32, POLICY_BF16) == 16 * 2 * 10
    assert policy_nbytes(u8, POLICY_F32) == 16 * 1 * 10
    assert policy_nbytes(u8, POLICY_BF16) == 16 * 1 * 10  # never touched
    assert policy_nbytes(i32, POLICY_BF16) == 16 * 4 * 10
    # f32_bf16 is byte-neutral (compute-only concession)
    assert policy_nbytes(f32, "f32_bf16") == policy_nbytes(f32, POLICY_F32)


def test_plan_path_run_economics():
    """The chain DP keeps a maximal bf16 run iff its saved bytes exceed
    the TWO casts the run costs (one down entering, one up leaving)."""
    big = 3 * CAST_PENALTY_BYTES
    # a run worth keeping
    assert _plan_path([big, big], [True, True]) == [True, True]
    # a run not worth two casts
    assert _plan_path([CAST_PENALTY_BYTES], [True]) == [False]
    # an illegal boundary splits runs: each side judged independently
    assert _plan_path([big, None, big], [True, False, True]) == \
        [True, False, True]
    assert _plan_path([CAST_PENALTY_BYTES, None, big],
                      [True, False, True]) == [False, False, True]


def test_probe_tolerance_declared_beats_probe():
    """A declared contract wins outright; an undeclared floating
    elementwise stage probes tolerant; a stage whose trace dies (or
    yields non-float) pins EXACT."""
    elem = shape_struct((8,), np.float32)
    tol, src = probe_tolerance(NormalizeRows(), elem)
    assert (tol, src) == (TOLERANT, "declared")
    tol, src = probe_tolerance(MaxClassifier(), elem)
    assert (tol, src) == (EXACT, "declared")

    from keystone_tpu.workflow import Transformer

    undeclared = Transformer.from_function(lambda x: x * 2.0)
    tol, src = probe_tolerance(undeclared, elem)
    assert (tol, src) == (TOLERANT, "probed")
    to_int = Transformer.from_function(
        lambda x: jax.numpy.argmax(x, axis=-1))
    tol, src = probe_tolerance(to_int, elem)
    assert (tol, src) == (EXACT, "probe-pinned")


def test_small_boundaries_never_beat_the_cast_penalty():
    """A tolerant chain whose total halving is below two casts' worth
    degrades to the all-f32 default (improved=False) — the KP702
    discipline priced into the objective."""
    graph, specs = _tolerant_chain_pipeline(count=4, dim=8)
    plan = plan_precision(graph, specs)
    assert plan is not None and not plan.improved
    assert plan.policies == plan.default_policies
    assert plan.savings_bytes == 0


def test_big_boundaries_choose_bf16_and_strictly_win():
    """The same chain at a real count halves every eligible boundary
    and strictly beats the default's priced bytes."""
    graph, specs = _tolerant_chain_pipeline(count=100_000, dim=64)
    plan = plan_precision(graph, specs)
    assert plan is not None and plan.improved
    changed = plan.changed_vertices()
    assert changed, "no boundary chosen despite clear savings"
    for vid in changed:
        assert plan.policies[vid] == POLICY_BF16
        tol, _ = plan.tolerances[vid]
        assert tol == TOLERANT
    assert plan.planned_cost_bytes < plan.default_cost_bytes
    # chosen policies are KP7xx-clean under the independent lint
    diags = precision_pass(graph, specs, plan)
    assert [d for d in diags if d.severity >= Severity.WARNING] == []


def test_exact_consumer_through_passthrough_pins_producer():
    """A tolerant featurize stage whose bytes flow through a Cacher into
    an exact solver keeps its f32 boundary: the analyzer looks through
    value-preserving plumbing and lets the REAL consumer decide."""
    graph, specs = _raw_graph("RandomPatchCifar")
    plan = plan_precision(graph, specs)
    assert plan is not None and plan.improved
    # ImageVectorizer (tolerant) feeds Cacher -> StandardScaler (exact):
    # its boundary must stay f32 even though the stage itself tolerates
    from keystone_tpu.nodes.images.core import ImageVectorizer

    vec_vids = [v for v in graph.operators
                if isinstance(graph.get_operator(v), ImageVectorizer)]
    assert vec_vids
    for v in vec_vids:
        assert plan.policies.get(v, POLICY_F32) == POLICY_F32
    # while upstream boundaries between tolerant stages went bf16
    assert any(plan.policies[v] == POLICY_BF16
               for v in plan.changed_vertices())


def test_planner_beats_default_on_at_least_two_examples():
    """The static acceptance gate, in tier-1: planner bytes ≤ default on
    every analyzable example, strictly less on ≥ 2, and every chosen
    policy KP7xx-clean (mirrors scripts/lint.sh's precision audit)."""
    strict = 0
    for name in sorted(EXAMPLES):
        graph, specs = _raw_graph(name)
        plan = plan_precision(graph, specs)
        if plan is None:
            continue  # nothing to decide: no tolerant float boundary
        assert plan.planned_cost_bytes <= plan.default_cost_bytes, name
        if plan.planned_cost_bytes < plan.default_cost_bytes:
            strict += 1
        diags = precision_pass(graph, specs, plan)
        gate = [d for d in diags if d.severity >= Severity.WARNING]
        assert gate == [], (name, gate)
    assert strict >= 2, f"strict wins on only {strict} example(s)"


# ------------------------------------------------------------- the lints


def test_kp701_flags_policy_on_intolerant_stage():
    """A hand-written bf16 policy on an exact boundary fails loudly."""
    graph, specs = _raw_graph("RandomPatchCifar")
    from keystone_tpu.nodes.stats.scalers import StandardScalerModel

    exact_vids = [
        v for v in graph.operators
        if getattr(graph.get_operator(v), "precision_tolerance", None)
        == EXACT and isinstance(specs.get(v), DataSpec)
    ]
    assert exact_vids
    vid = exact_vids[0]
    plan = PrecisionPlan(
        policies={vid: POLICY_BF16},
        default_policies={vid: POLICY_F32},
        planned_cost_bytes=0, default_cost_bytes=0)
    diags = precision_pass(graph, specs, plan)
    kp701 = [d for d in diags if d.rule == "KP701"]
    assert kp701 and kp701[0].severity == Severity.ERROR
    assert kp701[0].vertex == vid


def test_kp702_flags_cast_thrash():
    """A bf16 boundary whose every consumer is f32 and whose halving
    does not cover the two casts is cast-thrash: the downcast is undone
    immediately downstream for nothing."""
    graph, specs = _tolerant_chain_pipeline(count=4, dim=8)
    order = sorted((v for v in graph.operators), key=lambda v: v.id)
    first = order[0]  # tiny tolerant boundary, tolerant f32 consumer
    plan = PrecisionPlan(
        policies={first: POLICY_BF16},
        default_policies={first: POLICY_F32},
        planned_cost_bytes=0, default_cost_bytes=0)
    diags = precision_pass(graph, specs, plan)
    kp702 = [d for d in diags if d.rule == "KP702"]
    assert kp702 and kp702[0].severity == Severity.WARNING
    assert kp702[0].vertex == first


def test_kp703_reprices_memory_under_chosen_dtypes():
    """`reprice_memory` re-runs the KP2xx model with the decided storage
    dtypes: every changed f32 stage's residency halves exactly, KP703
    INFO rows name each one, and untouched stages keep their numbers."""
    graph, specs = _raw_graph("RandomPatchCifar")
    plan = plan_precision(graph, specs)
    assert plan is not None and plan.improved
    est0, est1, diags = reprice_memory(graph, specs, plan)
    assert est1.peak_bytes < est0.peak_bytes
    kp703 = {d.vertex for d in diags if d.rule == "KP703"}
    assert kp703
    halved = 0
    for vid in plan.changed_vertices():
        spec = specs.get(vid)
        leaves = jax.tree_util.tree_leaves(spec.element)
        a, b = est0.resident.get(vid), est1.resident.get(vid)
        if a is None or b is None:
            continue
        if all(np.dtype(l.dtype) == np.float32 for l in leaves):
            assert b * 2 == a, (vid, a, b)
            assert vid in kp703
            halved += 1
    assert halved, "no changed f32 stage had a priceable residency pair"
    changed = set(plan.changed_vertices())
    for vid in est0.resident:
        if vid not in changed:
            assert est0.resident[vid] == est1.resident[vid]


def test_kp600_per_device_numbers_halve_under_policy():
    """The dtype-aware KP600 pin: per-device residency (the sharded
    KP2xx picture) halves on a chosen f32 boundary when the per-device
    pass prices the plan's retyped specs."""
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    from keystone_tpu.analysis.sharding import per_device_pass, sharding_pass

    graph, specs = _raw_graph("RandomPatchCifar")
    plan = plan_precision(graph, specs)
    assert plan is not None and plan.improved
    retyped = plan.retyped_specs(specs)

    def per_dev(sp):
        shardings, _, _ = sharding_pass(graph, sp)
        est, _ = memory_pass(graph, sp)
        pd, _ = per_device_pass(graph, sp, shardings, est)
        return pd

    pd0, pd1 = per_dev(specs), per_dev(retyped)
    halved = [
        v for v in plan.changed_vertices()
        if pd0.get(v) and pd1.get(v) and pd1[v] * 2 == pd0[v]
    ]
    assert halved, "no per-device number halved under the chosen policy"


def test_shrink_to_band_reverts_largest_savings_first():
    """The band repair loop discards the most aggressive halving first
    and terminates at the all-f32 default when nothing satisfies."""
    graph, specs = _raw_graph("RandomPatchCifar")
    plan = plan_precision(graph, specs)
    assert plan is not None and len(plan.changed_vertices()) >= 2
    biggest = max(
        plan.changed_vertices(),
        key=lambda v: plan.default_boundary.get(v, 0)
        - plan.planned_boundary.get(v, 0))

    seen = []

    def eval_once(p):
        seen.append(list(p.changed_vertices()))
        return len(p.changed_vertices()) <= len(
            plan.changed_vertices()) - 2

    fixed = shrink_to_band(plan, eval_once)
    assert len(fixed.changed_vertices()) == len(plan.changed_vertices()) - 2
    assert biggest not in fixed.changed_vertices()  # reverted first

    # an evaluator that never passes terminates at the default
    allf32 = shrink_to_band(plan, lambda p: False)
    assert allf32.changed_vertices() == []
    # ...whose cost is the default's own (no orphaned cast penalties)
    assert allf32.planned_cost_bytes == allf32.default_cost_bytes


def test_shrink_to_band_rescore_keeps_cost_exact():
    """With the model's scorer supplied, every partially shrunk plan's
    cost is EXACTLY what scoring its policies yields — cast-penalty
    edges created by splitting a run are accounted, not approximated."""
    graph, specs = _raw_graph("RandomPatchCifar")
    plan = plan_precision(graph, specs)
    assert plan is not None and len(plan.changed_vertices()) >= 2
    model = _PrecisionModel(graph, specs, tolerances=plan.tolerances)

    def eval_once(p):
        return len(p.changed_vertices()) <= len(plan.changed_vertices()) - 2

    fixed = shrink_to_band(plan, eval_once, rescore=model.score)
    obj, _ = model.score(fixed.policies)
    assert fixed.planned_cost_bytes == obj
    assert fixed.default_cost_bytes == plan.default_cost_bytes


def test_kp701_compute_policy_checked_and_consumer_exempt():
    """A hand-written compute-reduced policy (f32_bf16) on an EXACT
    stage fires KP701 — reduced matmul precision degrades the solver
    even though the boundary storage stays f32. On a TOLERANT stage it
    passes even when the downstream consumer is exact: consumers still
    receive full-precision bytes under a compute-only policy."""
    graph, specs = _raw_graph("RandomPatchCifar")
    exact_vids = [
        v for v in graph.operators
        if getattr(graph.get_operator(v), "precision_tolerance", None)
        == EXACT and isinstance(specs.get(v), DataSpec)
    ]
    assert exact_vids
    plan = PrecisionPlan(
        policies={exact_vids[0]: POLICY_F32_BF16},
        default_policies={exact_vids[0]: POLICY_F32},
        planned_cost_bytes=0, default_cost_bytes=0)
    kp701 = [d for d in precision_pass(graph, specs, plan)
             if d.rule == "KP701"]
    assert kp701 and kp701[0].vertex == exact_vids[0]

    # tolerant producer feeding an exact consumer: storage bf16 would
    # flag (the existing KP701 contract), compute-only must not
    from keystone_tpu.nodes.images.core import ImageVectorizer

    vec = next(v for v in graph.operators
               if isinstance(graph.get_operator(v), ImageVectorizer))
    plan2 = PrecisionPlan(
        policies={vec: POLICY_F32_BF16},
        default_policies={vec: POLICY_F32},
        planned_cost_bytes=0, default_cost_bytes=0)
    assert [d for d in precision_pass(graph, specs, plan2)
            if d.rule == "KP701"] == []


# --------------------------------------------- satellite 1: dtype reconcile


def test_uint8_pipeline_static_vs_observed_bytes_exact(tmp_path):
    """The static KP2xx model prices a uint8 source at ONE byte per
    element (a float32-itemsize assumption would read 4x), the fused
    f32 featurize output matches the runtime-observed bytes exactly,
    and the reconcile table carries the propagated dtype column."""
    import json

    from keystone_tpu.analysis.reconcile import (
        format_reconciliation,
        reconcile_trace,
    )
    from keystone_tpu.nodes.images.core import ImageVectorizer, PixelScaler
    from keystone_tpu.telemetry import trace_run

    n, h, w, c = 64, 8, 8, 3
    imgs = np.random.default_rng(0).integers(
        0, 256, size=(n, h, w, c), dtype=np.uint8)
    path = tmp_path / "uint8_trace.json"
    PipelineEnv.reset()
    try:
        with trace_run(str(path)):
            pipe = PixelScaler().to_pipeline() >> ImageVectorizer()
            pipe(Dataset.from_numpy(imgs)).get()
    finally:
        PipelineEnv.reset()
    rec = reconcile_trace(json.load(open(path)))
    rows = {r["label"]: r for r in rec["rows"]}
    src = next(r for label, r in rows.items() if "Dataset" in label)
    assert src["static_bytes"] == n * h * w * c  # 1 byte/elem, not 4
    assert src["dtype"] == "uint8"
    fused = next(r for label, r in rows.items() if "PixelScaler" in label)
    assert fused["dtype"] == "float32"
    assert fused["observed_bytes"] == n * h * w * c * 4
    assert fused["static_bytes"] == fused["observed_bytes"]  # exact
    assert "uint8" in format_reconciliation(rec)


# ------------------------------------------------------------ enforcement


def _enforcement_stages(dim=64):
    return [RandomSignNode(dim), SignedHellingerMapper(), NormalizeRows(),
            LinearRectifier(0.0)]


def test_casts_present_in_fused_jaxpr_output_dtype_restored():
    """A tagged fused program carries the chosen convert_element_type
    casts in its jaxpr, the bare program does not, their cache keys
    differ, the visible output dtype is unchanged, and the bf16 values
    sit inside the declared band."""
    ft = FusedBatchTransformer(_enforcement_stages())
    ft.planned_precision = (None, "bfloat16", "bfloat16", "float32")
    statics, flat, treedef, fns = ft._decompose()
    mesh = meshlib.current_mesh()
    n = 64
    prog = ft._build_program(mesh, 1, n, treedef, fns)
    ds = Dataset.from_numpy(
        np.random.default_rng(0).normal(size=(n, 64)).astype(np.float32))
    jaxpr = str(jax.make_jaxpr(prog)(flat, ds.array, ds.mask))
    assert "convert_element_type" in jaxpr and "bf16" in jaxpr
    out = np.asarray(prog(flat, ds.array, ds.mask))
    assert out.dtype == np.float32  # the program's output dtype never changes

    bare = FusedBatchTransformer(_enforcement_stages())
    bare_prog = bare._build_program(mesh, 1, n, treedef, fns)
    assert "bf16" not in str(jax.make_jaxpr(bare_prog)(flat, ds.array,
                                                       ds.mask))
    ref = np.asarray(bare_prog(flat, ds.array, ds.mask))
    np.testing.assert_allclose(out, ref, rtol=DEFAULT_BAND_RTOL,
                               atol=DEFAULT_BAND_ATOL)
    key_tagged = ft._program_key(statics, flat, treedef, (n, 64),
                                 "float32", n, 1, mesh)
    key_bare = bare._program_key(statics, flat, treedef, (n, 64),
                                 "float32", n, 1, mesh)
    assert key_tagged != key_bare  # planned/unplanned never collide


def test_megafused_jaxpr_carries_casts():
    """materialize() propagates the precision tag from the plan operator
    to the runnable megafused transformer, and the scan-bodied program's
    jaxpr contains the chosen bf16 casts."""
    plan_op = MegafusedPlanOperator(_enforcement_stages())
    plan_op.planned_precision = (None, "bfloat16", "bfloat16", "float32")
    plan_op.planned_matmul_precision = "bfloat16"
    mat = plan_op.materialize([])
    assert mat.planned_precision == plan_op.planned_precision
    assert mat.planned_matmul_precision == "bfloat16"

    statics, flat, treedef, fns = mat._decompose()
    mesh = meshlib.current_mesh()
    n = 64
    prog = mat._build_program(mesh, 1, n, treedef, fns)
    ds = Dataset.from_numpy(
        np.random.default_rng(1).normal(size=(n, 64)).astype(np.float32))
    jaxpr = str(jax.make_jaxpr(prog)(flat, ds.array, ds.mask))
    assert "convert_element_type" in jaxpr and "bf16" in jaxpr
    out = np.asarray(prog(flat, ds.array, ds.mask))
    assert out.dtype == np.float32


def _predictor(classes=4, dim=64):
    featurizer = (RandomSignNode(dim).to_pipeline()
                  >> SignedHellingerMapper() >> NormalizeRows()
                  >> LinearRectifier(0.0) >> Cacher("feat"))

    def build(data, labels_ds):
        labels = ClassLabelIndicatorsFromInt(classes)(labels_ds)
        return featurizer.and_then(
            BlockLeastSquaresEstimator(32, num_iter=1, lam=1e-3),
            data, labels) >> MaxClassifier()

    return build


def _data(n, dim=64, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, dim).astype(np.float32),
            rng.randint(0, classes, size=n).astype(np.int32))


def _run_predictor(n, optimizer=None, **overrides):
    X, y = _data(n)
    PipelineEnv.reset()
    try:
        if optimizer is not None:
            PipelineEnv.get().set_optimizer(optimizer)
        with config_override(**overrides):
            data = Dataset.from_numpy(X)
            labels = Dataset.from_numpy(y)
            applied = _predictor()(data, labels)(data)
            out = np.asarray(applied.get().numpy())
            graph = applied.executor.optimized_graph
        return out, graph
    finally:
        PipelineEnv.reset()


def _tagged_ops(graph):
    return [graph.get_operator(v) for v in graph.operators
            if getattr(graph.get_operator(v), "planned_precision", None)
            is not None]


def test_kill_switch_reproduces_pr9_plan_bit_for_bit():
    """KEYSTONE_PRECISION_PLANNER=0 (config channel) and
    DefaultOptimizer(precision_planner=False) (constructor channel)
    agree exactly: same vertices, same operator classes, same
    dependencies, and no planned_precision tag anywhere — while the
    planner-on run DOES tag (so parity is not vacuous)."""
    _, g_off = _run_predictor(64, precision_planner=False)
    _, g_ctor = _run_predictor(
        64, DefaultOptimizer(precision_planner=False),
        precision_planner=True)
    _, g_on = _run_predictor(64, precision_planner=True,
                             precision_min_savings_bytes=0)

    def shape(g):
        return [
            (vid.id, type(g.get_operator(vid)).__name__,
             tuple(d.id if hasattr(d, "id") else d
                   for d in g.get_dependencies(vid)),
             getattr(g.get_operator(vid), "planned_precision", None))
            for vid in sorted(g.operators, key=lambda v: v.id)
        ]

    off, ctor, on = shape(g_off), shape(g_ctor), shape(g_on)
    assert off == ctor
    assert all(t[3] is None for t in off)
    assert any(t[3] is not None for t in on), \
        "planner-on run enforced nothing; parity check is vacuous"
    # topology identical either way — the policy rides on tagged copies
    assert [t[:3] for t in on] == [t[:3] for t in off]


@pytest.mark.parametrize("n", [64, 43])
def test_policy_on_outputs_in_band_at_multiple_and_ragged_counts(n):
    """Planner-on predictions match the serial unfused f32 reference
    within the declared band at a shard-multiple AND a ragged count,
    with enforcement asserted present (not a vacuous no-op run)."""
    planned, g_on = _run_predictor(n, precision_planner=True,
                                   precision_min_savings_bytes=0)
    serial, _ = _run_predictor(
        n, DefaultOptimizer(fuse=False, sharding_planner=False,
                            precision_planner=False),
        precision_planner=False)
    assert _tagged_ops(g_on), "no policy enforced at count %d" % n
    # argmax outputs: the band degenerates to (near-)equality
    assert planned.shape == serial.shape
    assert np.mean(planned == serial) >= 0.95


def test_intolerant_solver_boundary_stays_f32():
    """In the enforced storage trail, boundaries adjacent to an exact
    stage (the solver's fit slot, the argmax) are never reduced, and
    the final entry restores the PR-9 output dtype."""
    _, g_on = _run_predictor(64, precision_planner=True,
                             precision_min_savings_bytes=0)
    tagged = _tagged_ops(g_on)
    assert tagged
    from keystone_tpu.analysis.precision import stage_tolerance
    from keystone_tpu.nodes.util.fusion import _peephole

    for op in tagged:
        stage_specs = getattr(op, "stage_specs", None)
        stages = _peephole(stage_specs if stage_specs is not None
                           else list(op.stages))
        storage = op.planned_precision
        assert len(storage) == len(stages)
        vid = next(v for v in g_on.operators if g_on.get_operator(v) is op)
        tols = [stage_tolerance(s, g_on, vid) for s in stages]
        for i, st in enumerate(storage[:-1]):
            if st == "bfloat16":
                assert tols[i] == TOLERANT and tols[i + 1] == TOLERANT, (
                    f"bf16 boundary {i} adjacent to an intolerant stage")
                # every kept bf16 run must END in an explicit up-cast:
                # the fused bodies are dtype-following, so a None exit
                # would let bf16 flow into the exact stages downstream
                assert storage[i + 1] is not None, (
                    f"bf16 run through boundary {i} has no restore cast "
                    "at its exit")
        assert storage[-1] in (None, "float32")  # output dtype restored
        assert any(st == "bfloat16" for st in storage[:-1])


def test_warm_run_zero_cold_compiles_under_policy():
    """A rebuilt-from-scratch run under the enforced policy against a
    warm persistent cache performs 0 cold compiles — the planned
    program is cache-keyed and AOT-warmable like any other."""
    from keystone_tpu.compile_bench import measure_example_compiles

    rep = measure_example_compiles("RandomPatchCifar", plan="precision")
    assert rep["plan"] == "precision"
    assert rep["warm_programs_compiled"] == 0, rep
    assert rep["outputs_match_cold"]


def test_dispatch_bench_precision_plan_in_band():
    """The bench surface: the `precision` plan keeps the megafused
    1-program apply shape, its outputs sit inside the declared band
    (the `precision_in_band` verdict finalize_record gates on), and the
    per-plan breakdown row carries the precision column."""
    from keystone_tpu.dispatch_bench import PLANS, dispatch_count_report

    rep = dispatch_count_report(examples=("RandomPatchCifar",))
    assert "precision" in rep["plans"]
    e = rep["examples"]["RandomPatchCifar"]
    assert e["apply_run_programs"]["precision"] == \
        e["apply_run_programs"]["megafused"] == 1
    assert e["precision_in_band"] and rep["precision_in_band"]
    (row,) = rep["plan_breakdown"]
    assert all(p in row for p in PLANS)


def test_finalize_record_fails_on_band_bust():
    """bench.finalize_record turns precision_in_band=False into a loud
    error record, never a silent stale fallback."""
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "bench", Path(__file__).resolve().parent.parent / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    detail = {"platform": "cpu", "images_per_sec": 1.0,
              "dispatch_count": {"precision_in_band": False}}
    rec, ok = bench.finalize_record(detail)
    assert not ok
    assert "band" in rec["error"]

    # in-band (or absent) verdicts do not trip the gate
    detail = {"platform": "cpu", "images_per_sec": 1.0,
              "dispatch_count": {"precision_in_band": True}}
    rec, _ = bench.finalize_record(detail)
    assert "error" not in rec
