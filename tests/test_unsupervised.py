"""PCA / k-means / GMM / FisherVector tests (model: reference PCASuite
distributed≈local checks :85, sketch validity :134-198, KMeans/GMM
suites)."""

import os

import numpy as np
import pytest

from keystone_tpu import Dataset, HostDataset
from keystone_tpu.nodes.learning import (
    ApproximatePCAEstimator,
    ColumnPCAEstimator,
    DistributedPCAEstimator,
    GaussianMixtureModelEstimator,
    KMeansPlusPlusEstimator,
    PCAEstimator,
)
from keystone_tpu.nodes.images import FisherVector, ScalaGMMFisherVectorEstimator
from keystone_tpu.nodes.learning.gmm import GaussianMixtureModel


@pytest.fixture
def correlated_data():
    rng = np.random.default_rng(0)
    # strong low-rank structure in 12 dims
    U = rng.normal(size=(2000, 3)).astype(np.float32)
    A = rng.normal(size=(3, 12)).astype(np.float32)
    return U @ A + 0.05 * rng.normal(size=(2000, 12)).astype(np.float32)


def _subspace_angle(V1, V2):
    """Largest principal angle between column spaces (0 = identical)."""
    q1, _ = np.linalg.qr(V1)
    q2, _ = np.linalg.qr(V2)
    s = np.linalg.svd(q1.T @ q2, compute_uv=False)
    return np.degrees(np.arccos(np.clip(s.min(), -1, 1)))


def test_local_pca_matches_numpy(correlated_data):
    X = correlated_data
    model = PCAEstimator(3).fit(Dataset(X))
    mu = X.mean(0)
    _, _, Vt = np.linalg.svd(X - mu, full_matrices=False)
    assert _subspace_angle(np.asarray(model.components), Vt[:3].T) < 1.0


def test_distributed_pca_matches_local(correlated_data):
    """distributed ≈ local (PCASuite.scala:85) — TSQR over the 8-shard
    mesh must agree with the single-replica SVD."""
    X = correlated_data
    local = PCAEstimator(3).fit(Dataset(X))
    dist = DistributedPCAEstimator(3).fit(Dataset(X))
    assert _subspace_angle(
        np.asarray(local.components), np.asarray(dist.components)
    ) < 1.0


def test_approximate_pca_sketch_validity(correlated_data):
    X = correlated_data
    approx = ApproximatePCAEstimator(3, oversample=8, q=2).fit(Dataset(X))
    mu = X.mean(0)
    _, _, Vt = np.linalg.svd(X - mu, full_matrices=False)
    assert _subspace_angle(np.asarray(approx.components), Vt[:3].T) < 5.0


def test_column_pca_routing(correlated_data):
    est = ColumnPCAEstimator(3, num_chips=8)
    model = est.optimize(Dataset(correlated_data), num_per_shard=250)
    assert est.chosen in ("local", "distributed")


def test_pca_on_descriptor_matrices():
    rng = np.random.default_rng(1)
    items = [rng.normal(size=(30, 16)).astype(np.float32) for _ in range(5)]
    model = PCAEstimator(4).fit(HostDataset(items))
    out = model.apply_batch(HostDataset(items))
    assert out.items[0].shape == (30, 4)


def test_kmeans_separates_clusters():
    rng = np.random.default_rng(2)
    centers = np.array([[0, 0], [10, 0], [0, 10]], np.float32)
    X = np.concatenate(
        [c + 0.3 * rng.normal(size=(100, 2)).astype(np.float32) for c in centers]
    )
    model = KMeansPlusPlusEstimator(3, num_iters=10, seed=0).fit(Dataset(X))
    learned = np.sort(np.asarray(model.centers), axis=0)
    np.testing.assert_allclose(learned, np.sort(centers, axis=0), atol=0.5)
    # one-hot assignment
    onehot = model.apply_batch(Dataset(X)).numpy()
    assert onehot.shape == (300, 3)
    assert np.all(onehot.sum(axis=1) == 1.0)


def test_gmm_recovers_mixture():
    rng = np.random.default_rng(3)
    X = np.concatenate(
        [
            rng.normal(loc=-4, scale=0.5, size=(500, 2)),
            rng.normal(loc=4, scale=1.0, size=(500, 2)),
        ]
    ).astype(np.float32)
    gmm = GaussianMixtureModelEstimator(2, num_iters=40, seed=0).fit(Dataset(X))
    means = np.sort(np.asarray(gmm.means)[:, 0])
    np.testing.assert_allclose(means, [-4, 4], atol=0.3)
    # posteriors are a valid distribution
    q = np.asarray(gmm.posteriors(X[:10]))
    np.testing.assert_allclose(q.sum(axis=1), 1.0, atol=1e-4)


def test_fisher_vector_shape_and_gradient_property():
    rng = np.random.default_rng(4)
    descs = rng.normal(size=(200, 8)).astype(np.float32)
    fv_est = ScalaGMMFisherVectorEstimator(k=4, num_iters=10)
    fv = fv_est.fit(HostDataset([descs]))
    out = np.asarray(fv.apply(descs))
    assert out.shape == (8, 2 * 4)  # (d, 2k): means + variances gradients
    assert np.isfinite(out).all()
    # FV of data drawn exactly at a component mean has near-zero mean-gradient
    gmm = fv.gmm
    at_mean = np.tile(np.asarray(gmm.means[0]), (50, 1)).astype(np.float32)
    g = np.asarray(fv.apply(at_mean))
    assert np.abs(g[:, 0]).max() < 1e-3  # component-0 mean gradient ≈ 0


def test_distributed_pca_on_descriptor_matrices():
    """3D descriptor datasets: distributed must match local (review
    regression — wrong mean/mask on the flattened rows)."""
    rng = np.random.default_rng(7)
    X = rng.normal(size=(8, 5, 4)).astype(np.float32)
    local = PCAEstimator(2).fit(Dataset(X.reshape(-1, 4)))
    dist = DistributedPCAEstimator(2).fit(Dataset(X))
    assert _subspace_angle(
        np.asarray(local.components), np.asarray(dist.components)
    ) < 1.0


# ----------------------------------------------------------- GMM fixtures
# (reference GaussianMixtureModelSuite.scala:12-159 — hand-computed and
# MLlib-derived expected values, ported with the reference's tolerances)


def test_gmm_single_center_exact():
    """k=1: the mean is the data mean exactly
    (GaussianMixtureModelSuite.scala:12-29)."""
    X = np.array([[1, 2, 6], [1, 3, 0], [1, 4, 6]], np.float32)
    g = GaussianMixtureModelEstimator(1, seed=0).fit(Dataset(X))
    np.testing.assert_allclose(np.asarray(g.means), [[1.0, 3.0, 4.0]], atol=1e-5)


def test_gmm_mllib_fixture_two_centers():
    """The Spark-MLlib-derived 1-D fixture: centers {5.1604, -4.3673},
    variances {0.86644, 1.1098} (GaussianMixtureModelSuite.scala:64-93;
    the reference asserts 1e-4 — our jitted EM converges to the same
    optimum at the same tolerance)."""
    data = np.array(
        [-5.1971, -2.5359, -3.8220, -5.2211, -5.0602, 4.7118, 6.8989,
         3.4592, 4.6322, 5.7048, 4.6567, 5.5026, 4.5605, 5.2043, 6.2734],
        np.float32,
    )[:, None]
    g = GaussianMixtureModelEstimator(2, seed=0, num_iters=100).fit(Dataset(data))
    means = np.asarray(g.means).ravel()
    variances = np.asarray(g.variances).ravel()
    order = np.argsort(means)  # keep the mean↔variance PAIRING intact
    np.testing.assert_allclose(means[order], [-4.3673, 5.1604], atol=1e-3)
    np.testing.assert_allclose(variances[order], [1.1098, 0.86644], atol=1e-3)


def test_gmm_data_txt_fixture():
    """The reference's checked-in 2-D mixture (gmm_data.txt): centers ≈ 0
    (atol .5), variances ≈ {(1, 25), (25, 1)} (atol 2), weights ≈ .5
    (atol .05) — GaussianMixtureModelSuite.scala:95-117."""
    path = os.path.join(os.path.dirname(__file__), "resources", "gmm_data.txt")
    data = np.loadtxt(path).astype(np.float32)
    g = GaussianMixtureModelEstimator(2, seed=0, num_iters=30).fit(Dataset(data))
    means = np.asarray(g.means)
    variances = np.asarray(g.variances)
    weights = np.asarray(g.weights)
    np.testing.assert_allclose(means, np.zeros((2, 2)), atol=0.5)
    # one component elongated in x, the other in y, each ≈ {1, 25}
    assert variances[0].argmax() != variances[1].argmax(), variances
    for v in variances:
        np.testing.assert_allclose(sorted(v), [1.0, 25.0], atol=2.0)
    np.testing.assert_allclose(weights, [0.5, 0.5], atol=0.05)


def test_gmm_posterior_hard_assignments():
    """Fixed model → hard posterior assignments (GaussianMixtureModelSuite
    .scala:119-158): tiny variances make the posteriors one-hot."""
    means = np.array([[1.0, 2.0, 0.0], [1.0, 3.0, 6.0]])
    variances = np.array([[1e-8, 1.0, 0.09], [1e-8, 1.0, 0.09]])
    weights = np.array([0.5, 0.5])
    gmm = GaussianMixtureModel(means, variances, weights)
    c1, c2 = [1.0, 0.0], [0.0, 1.0]
    data = np.array(
        [[1, 2, 6], [1, 3, 0], [1, 4, 6], [1, 1, 0]], np.float64
    )
    want = np.array([c2, c1, c2, c1])
    np.testing.assert_allclose(np.asarray(gmm.apply(data)), want, atol=1e-4)
    # single apply matches the batch rows
    np.testing.assert_allclose(np.asarray(gmm.apply(data[1])), c1, atol=1e-4)


def test_gmm_load_csv_voc_codebook():
    """The reference's real VOC codebook sideband files (dims × clusters
    layout, GaussianMixtureModel.scala:97-105): loads transposed to
    (k, d), weights normalized, posteriors well-formed."""
    base = os.path.join(os.path.dirname(__file__), "resources", "voc_codebook")
    gmm = GaussianMixtureModel.load_csv(
        os.path.join(base, "means.csv"),
        os.path.join(base, "variances.csv"),
        os.path.join(base, "priors"),
    )
    k, d = gmm.means.shape
    assert d == 80 and k >= 32, (k, d)
    assert gmm.variances.shape == (k, d)
    assert abs(float(np.asarray(gmm.weights).sum()) - 1.0) < 1e-2
    rng = np.random.default_rng(0)
    q = np.asarray(gmm.posteriors(rng.normal(size=(5, d)).astype(np.float32)))
    assert q.shape == (5, k)
    np.testing.assert_allclose(q.sum(axis=1), 1.0, atol=1e-3)
