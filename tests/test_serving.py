"""Static serving-readiness certifier tests (analysis/serving — KP9xx).

The acceptance contract of the tier: a fitted pipeline is provably ONE
warm, host-free, latency-bounded program over a declared envelope
before any traffic arrives — and the warmup-manifest claim is pinned
live: with the envelope armed, warm apply at EVERY pad-ladder shape the
envelope can produce performs ZERO cold XLA compiles (the PR-5
`compile_count` discipline extended past the single propagated shape).
"""

import numpy as np
import pytest

from keystone_tpu.analysis import (
    Severity,
    ServingCertificate,
    ServingEnvelope,
    as_source_spec,
    envelope_from_env,
    ladder_shapes,
    serving_pass,
    validate_graph,
    warmup_manifest,
)
from keystone_tpu.analysis.examples import build_example
from keystone_tpu.analysis.propagate import spec_pass
from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
from keystone_tpu.nodes.stats import LinearRectifier, PaddedFFT, RandomSignNode
from keystone_tpu.nodes.util import (
    ClassLabelIndicatorsFromInt,
    MaxClassifier,
    VectorCombiner,
)
from keystone_tpu.workflow import Pipeline, PipelineEnv


@pytest.fixture(autouse=True)
def _reset_env():
    PipelineEnv.reset()
    yield
    PipelineEnv.reset()


@pytest.fixture(autouse=True)
def _disarm_env_envelope(monkeypatch):
    """Certification must be armed explicitly in these tests."""
    for var in ("KEYSTONE_SLO_MS", "KEYSTONE_SERVING_MAX_BATCH",
                "KEYSTONE_SERVING_TENANTS"):
        monkeypatch.delenv(var, raising=False)


def _mnist_like():
    """The canonical fully-priced abstract example (the CLI's
    MnistRandomFFT registry entry)."""
    pipeline, source_spec = build_example("MnistRandomFFT")
    specs, _ = spec_pass(
        pipeline.graph, {pipeline.source: as_source_spec(source_spec)})
    return pipeline, specs


def _rules(diags):
    return [d.rule for d in diags]


# ------------------------------------------------------------- envelope


def test_envelope_validates_its_contract():
    with pytest.raises(ValueError):
        ServingEnvelope(min_batch=0)
    with pytest.raises(ValueError):
        ServingEnvelope(min_batch=8, max_batch=4)
    with pytest.raises(ValueError):
        ServingEnvelope(slo_seconds=0.0)
    with pytest.raises(ValueError):
        ServingEnvelope(tenants=0)


def test_envelope_from_env_arms_and_disarms(monkeypatch):
    assert envelope_from_env() is None  # disarmed by default
    monkeypatch.setenv("KEYSTONE_SLO_MS", "250")
    monkeypatch.setenv("KEYSTONE_SERVING_MAX_BATCH", "16")
    monkeypatch.setenv("KEYSTONE_SERVING_TENANTS", "3")
    env = envelope_from_env()
    assert env == ServingEnvelope(max_batch=16, slo_seconds=0.25, tenants=3)
    # a malformed value disarms rather than breaking validation
    monkeypatch.setenv("KEYSTONE_SLO_MS", "not-a-number")
    assert envelope_from_env() is None


def test_ladder_shapes_are_the_pad_target_image():
    from keystone_tpu.utils.batching import _pad_target

    shapes = ladder_shapes(ServingEnvelope(max_batch=64), chunk_rows=64)
    assert shapes == [1, 2, 4, 8, 16, 32, 64]
    # the contract KP902 certifies against: EVERY in-envelope batch
    # coalesces onto an enumerated shape
    for b in range(1, 65):
        assert _pad_target(b, 64, b) in shapes
    # batches past the chunk size clamp to the chunk
    assert ladder_shapes(
        ServingEnvelope(max_batch=512), chunk_rows=64)[-1] == 64
    # a narrowed batch range drops the small rungs
    assert ladder_shapes(
        ServingEnvelope(min_batch=5, max_batch=8), chunk_rows=64) == [8]


# ---------------------------------------------------------- the verdict


def test_certified_pipeline_and_report_surface():
    pipeline, specs = _mnist_like()
    cert, diags = serving_pass(
        pipeline.graph, specs, ServingEnvelope(max_batch=16),
        source=pipeline.source, sink=pipeline.sink, record=False)
    assert isinstance(cert, ServingCertificate)
    assert cert.certified
    assert cert.priced_stages > 0 and cert.unpriced_stages == 0
    assert cert.dominating_stage
    assert [s["batch"] for s in cert.shapes] == [1, 2, 4, 8, 16]
    for s in cert.shapes:
        # the certified bound is the upper envelope; the machine bound
        # (roofline + dispatch floor) the hardware lower one
        assert s["predicted_seconds"] > s["machine_seconds"] > 0
    assert "KP903" in _rules(diags)  # INFO: bound holds
    rec = cert.as_record()
    assert rec["certified"] and rec["shapes"] and rec["warmup_manifest"]


def test_validate_attaches_certificate_only_when_armed(monkeypatch):
    pipeline, source_spec = build_example("MnistRandomFFT")
    report = pipeline.validate(source_spec, raise_on_error=False)
    assert report.serving is None  # no envelope: the tier is skipped
    report = pipeline.validate(
        source_spec, serving=ServingEnvelope(max_batch=8),
        raise_on_error=False)
    assert report.serving is not None and report.serving.certified
    # the env-declared envelope arms it too
    monkeypatch.setenv("KEYSTONE_SLO_MS", "500")
    report = pipeline.validate(source_spec, raise_on_error=False)
    assert report.serving is not None
    assert report.serving.envelope.slo_seconds == 0.5


def test_kp901_names_host_stages_and_their_fix():
    pipeline, source_spec = build_example("NewsgroupsPipeline")
    specs, _ = spec_pass(
        pipeline.graph, {pipeline.source: as_source_spec(source_spec)})
    cert, diags = serving_pass(pipeline.graph, specs, record=False)
    errors = [d for d in diags if d.rule == "KP901"]
    assert errors and not cert.certified
    labels = {d.label for d in errors}
    assert "Trim" in labels  # the host NLP front-end, stage-named
    assert all("Fix:" in d.message for d in errors)


def test_kp903_busted_slo_names_the_dominating_stage():
    pipeline, specs = _mnist_like()
    cert, diags = serving_pass(
        pipeline.graph, specs,
        ServingEnvelope(max_batch=64, slo_seconds=1e-9),
        source=pipeline.source, sink=pipeline.sink, record=False)
    assert not cert.certified
    bust = [d for d in diags
            if d.rule == "KP903" and d.severity == Severity.ERROR]
    assert len(bust) == 1
    assert cert.dominating_stage in bust[0].message
    assert f"batch {cert.worst_shape['batch']}" in bust[0].message


def test_kp904_flags_donated_request_buffer():
    class _DonatingRectifier(LinearRectifier):
        donates_deps = (0,)

    pipe = RandomSignNode(8).to_pipeline() >> _DonatingRectifier(0.0)
    # the donating stage reads the RandomSign output (an interior
    # buffer): safe
    specs, _ = spec_pass(pipe.graph, {pipe.source: as_source_spec((8,))})
    _, diags = serving_pass(pipe.graph, specs, record=False)
    assert "KP904" not in _rules(diags)

    pipe2 = _DonatingRectifier(0.0).to_pipeline() >> RandomSignNode(8)
    specs2, _ = spec_pass(pipe2.graph, {pipe2.source: as_source_spec((8,))})
    cert, diags2 = serving_pass(pipe2.graph, specs2, record=False)
    kp904 = [d for d in diags2 if d.rule == "KP904"]
    assert len(kp904) == 1 and kp904[0].severity == Severity.ERROR
    assert not cert.certified


def test_kp905_prices_multi_tenant_residency():
    pipeline, specs = _mnist_like()
    _, diags = serving_pass(
        pipeline.graph, specs, ServingEnvelope(tenants=2),
        source=pipeline.source, sink=pipeline.sink,
        hbm_budget_bytes=1 << 40, record=False)
    info = [d for d in diags if d.rule == "KP905"]
    assert len(info) == 1 and info[0].severity == Severity.INFO

    cert, diags = serving_pass(
        pipeline.graph, specs, ServingEnvelope(tenants=1_000_000),
        source=pipeline.source, sink=pipeline.sink,
        hbm_budget_bytes=1 << 20, record=False)
    over = [d for d in diags if d.rule == "KP905"]
    assert len(over) == 1 and over[0].severity == Severity.ERROR
    assert not cert.certified


def test_kp906_flags_dynamic_metric_names_on_instantiated_operators():
    class _ChattyRectifier(LinearRectifier):
        def apply(self, x):
            from keystone_tpu.telemetry import counter

            counter(f"serve.{self.label}").inc()
            return super().apply(x)

    pipe = RandomSignNode(8).to_pipeline() >> _ChattyRectifier(0.0)
    specs, _ = spec_pass(pipe.graph, {pipe.source: as_source_spec((8,))})
    _, diags = serving_pass(pipe.graph, specs, record=False)
    kp906 = [d for d in diags if d.rule == "KP906"]
    assert len(kp906) == 1 and kp906[0].severity == Severity.WARNING
    assert "apply" in kp906[0].message

    class _HistogramRectifier(LinearRectifier):
        def apply(self, x):
            import jax.numpy as jnp

            h, _ = jnp.histogram(x, bins=int(x.shape[-1]))
            return h

    # np/jnp.histogram is math, not a metric factory (the KJ012
    # receiver filter applies here too)
    pipe2 = RandomSignNode(8).to_pipeline() >> _HistogramRectifier(0.0)
    specs2, _ = spec_pass(pipe2.graph, {pipe2.source: as_source_spec((8,))})
    _, diags2 = serving_pass(pipe2.graph, specs2, record=False)
    assert [d for d in diags2 if d.rule == "KP906"] == []


def test_serving_cert_lands_in_the_ledger():
    from keystone_tpu.telemetry import ledger

    pipeline, specs = _mnist_like()
    mark = ledger.session_mark()
    serving_pass(pipeline.graph, specs, ServingEnvelope(max_batch=8),
                 source=pipeline.source, sink=pipeline.sink,
                 label="MnistRandomFFT")
    records = [d for d in ledger.session_since(mark)
               if d["kind"] == "serving_cert"]
    assert len(records) == 1
    rec = records[0]
    assert rec["labels"] == ["MnistRandomFFT"]
    assert rec["chosen"]["entry"] == "certified"
    # the priced per-shape menu is the alternatives list
    assert [a["entry"] for a in rec["alternatives"]] == [
        "batch=1", "batch=2", "batch=4", "batch=8"]
    assert rec["predicted"]["worst_shape_seconds"] > 0


# ------------------------------------------------------ warmup manifest


def test_warmup_manifest_enumerates_sites_times_ladder():
    pipeline, source_spec = build_example("MnistRandomFFT")
    manifest = warmup_manifest(
        pipeline.graph,
        {pipeline.source: as_source_spec(source_spec)},
        envelope=ServingEnvelope(max_batch=16))
    assert manifest, "no warmable fused program site found"
    for entry in manifest:
        assert entry["counts"] == [1, 2, 4, 8, 16]
        assert hasattr(entry["element"], "shape")
        assert "Fused[" in entry["label"]


def _fit_small_predictor():
    """A tiny real fitted pipeline: gather(2 fft branches) → block LS →
    argmax. Fits in seconds on CPU; the default optimizer collapses the
    whole apply path into one fused program."""
    from keystone_tpu.data.dataset import Dataset

    rng = np.random.default_rng(0)
    dim, n, k = 16, 48, 3
    X = rng.normal(size=(n, dim)).astype(np.float32)
    y = rng.integers(0, k, n).astype(np.int32)
    branches = [
        RandomSignNode(dim, seed=i) >> PaddedFFT() >> LinearRectifier(0.0)
        for i in range(2)
    ]
    feat = Pipeline.gather(branches) >> VectorCombiner()
    train = Dataset.from_numpy(X)
    labels = ClassLabelIndicatorsFromInt(k)(Dataset.from_numpy(y)).get()
    pred = feat.and_then(
        BlockLeastSquaresEstimator(32, 1, 1e-2), train, labels
    ) >> MaxClassifier()
    return pred.fit(), X


def _compile_events(fn):
    """Run fn, return (number of XLA compile requests, result)."""
    from jax._src import monitoring

    events = []

    def listener(name, **kw):
        if name == "/jax/compilation_cache/compile_requests_use_cache":
            events.append(name)

    monitoring.register_event_listener(listener)
    try:
        out = fn()
    finally:
        try:
            monitoring._event_listeners.remove(listener)
        except ValueError:  # pragma: no cover - listener wrapper changed
            monitoring.clear_event_listeners()
    return len(events), out


LADDER = (1, 2, 4, 8, 16)


def test_armed_envelope_warm_serves_every_ladder_shape_zero_cold(
        monkeypatch):
    """THE acceptance pin: with the serving envelope armed
    (``KEYSTONE_SLO_MS``), `GraphExecutor._warm_plan` widens AOT warmup
    to every pad-ladder shape the envelope can produce, so warm apply
    at EVERY in-envelope shape performs 0 cold compiles — and matches
    the batch path datum-for-datum."""
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.workflow.executor import drain_warmups

    monkeypatch.setenv("KEYSTONE_SLO_MS", "1000")
    monkeypatch.setenv("KEYSTONE_SERVING_MAX_BATCH", str(max(LADDER)))
    fitted, X = _fit_small_predictor()
    batch_ref = np.asarray(fitted.apply(Dataset.from_numpy(X)).numpy())

    # one warm apply triggers the executor's warm scan (ladder-widened
    # by the armed envelope); drain so the background compiles land
    np.asarray(fitted.apply(Dataset.from_numpy(X[:1])).numpy())
    drain_warmups()

    def serve():
        return [
            np.asarray(fitted.apply(Dataset.from_numpy(X[:b])).numpy())
            for b in LADDER
        ]

    n_compiles, preds = _compile_events(serve)
    assert n_compiles == 0, (
        f"warm serving at the envelope's ladder shapes {LADDER} "
        f"performed {n_compiles} cold compile(s) — the KP902 coverage "
        "claim (0 cold compiles at ANY in-envelope shape) is broken")
    for b, p in zip(LADDER, preds):
        assert (p == batch_ref[:b]).all()


def test_warm_manifest_drives_ladder_warmup_without_env(monkeypatch):
    """The serving runtime's explicit pre-traffic warm step:
    `warmup_manifest()` fed to `GraphExecutor.warm_manifest` covers the
    whole ladder with NO env arming — the manifest is the contract."""
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.workflow import GraphExecutor
    from keystone_tpu.workflow.executor import drain_warmups
    from keystone_tpu.workflow.operators import DatasetOperator

    fitted, X = _fit_small_predictor()
    dim = X.shape[1]
    manifest = warmup_manifest(
        fitted.graph, {fitted.source: as_source_spec((dim,))},
        envelope=ServingEnvelope(max_batch=max(LADDER)))
    assert manifest

    # an executor over the bound fitted graph (the serving process)
    g, nid = fitted.graph.add_node(
        DatasetOperator(Dataset.from_numpy(X[:1])), [])
    g = g.replace_dependency(fitted.source, nid).remove_source(
        fitted.source)
    executor = GraphExecutor(g, optimize=False)
    submitted = executor.warm_manifest(manifest)
    assert submitted >= 1
    drain_warmups()

    def serve():
        for b in LADDER:
            np.asarray(fitted.apply(Dataset.from_numpy(X[:b])).numpy())

    n_compiles, _ = _compile_events(serve)
    assert n_compiles == 0, (
        f"manifest-driven warmup left {n_compiles} cold compile(s) "
        f"across ladder shapes {LADDER}")


def test_executor_embeds_certificate_in_trace_metadata(monkeypatch):
    """KP903's trace half: with the envelope armed, the apply executor
    embeds ``keystone.serving`` so `reconcile_serving` has a predicted
    side."""
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.telemetry import trace_run
    from keystone_tpu.telemetry.export import to_chrome_trace

    monkeypatch.setenv("KEYSTONE_SLO_MS", "1000")
    monkeypatch.setenv("KEYSTONE_SERVING_MAX_BATCH", "4")
    fitted, X = _fit_small_predictor()
    with trace_run() as tracer:
        np.asarray(fitted.apply(Dataset.from_numpy(X[:2])).numpy())
    trace = to_chrome_trace(tracer)
    cert = trace["keystone"].get("serving")
    assert cert is not None
    assert cert["slo_seconds"] == 1.0
    assert [s["batch"] for s in cert["shapes"]] == [1, 2, 4]
    assert all(s["predicted_seconds"] > 0 for s in cert["shapes"])


# ------------------------------------------------------- the reconcile


def _trace_with(cert_shapes, observed):
    return {"keystone": {
        "serving": {"shapes": cert_shapes, "slo_seconds": 1.0,
                    "certified": True, "dominating_stage": "Stage"},
        "serving_observed": observed,
    }}


def test_reconcile_serving_joins_on_the_padded_shape():
    from keystone_tpu.analysis.reconcile import (
        format_serving_reconciliation,
        reconcile_serving,
    )

    trace = _trace_with(
        [{"batch": 1, "predicted_seconds": 0.010, "machine_seconds": 1e-4},
         {"batch": 4, "predicted_seconds": 0.020, "machine_seconds": 2e-4}],
        [{"batch": 1, "chunk_shape": 1, "p50_ms": 6.0, "p99_ms": 9.0},
         # a batch-3 request coalesces onto the 4-rung: joins there
         {"batch": 3, "chunk_shape": 4, "p50_ms": 8.0},
         {"batch": 9, "chunk_shape": 16, "p50_ms": 9.0}])  # unjoined
    rec = reconcile_serving(trace)
    assert rec["shapes_joined"] == 2
    assert rec["violations"] == 0 and rec["bound_holds"] is True
    by_batch = {r["batch"]: r for r in rec["rows"]}
    assert by_batch[3]["predicted_bound_seconds"] == 0.020
    assert by_batch[3]["residual_seconds"] == pytest.approx(0.012)
    assert by_batch[9]["holds"] is None
    text = format_serving_reconciliation(rec)
    assert "holds" in text and "unjoined" in text


def test_reconcile_serving_flags_violations_and_degrades():
    from keystone_tpu.analysis.reconcile import (
        format_serving_reconciliation,
        reconcile_serving,
    )

    trace = _trace_with(
        [{"batch": 2, "predicted_seconds": 0.004, "machine_seconds": 1e-4}],
        [{"batch": 2, "chunk_shape": 2, "p50_ms": 11.0}])
    rec = reconcile_serving(trace)
    assert rec["bound_holds"] is False and rec["violations"] == 1
    assert rec["rows"][0]["residual_seconds"] < 0
    assert "VIOLATED" in format_serving_reconciliation(rec)

    empty = reconcile_serving({"keystone": {}})
    assert empty["rows"] == [] and empty["bound_holds"] is None
    assert "no joined shapes" in format_serving_reconciliation(empty)
