"""Generate the miniature archive fixtures checked in next to this file
(the analog of the reference's checked-in test tars used by
ImageNetLoaderSuite.scala:1-40 / VOCLoaderSuite.scala). Deterministic:
small crops of the two public test images re-encoded as baseline JPEG.

Run from the repo root:  python tests/resources/make_archive_fixtures.py
"""

import io
import os
import tarfile

import numpy as np
from PIL import Image

HERE = os.path.dirname(os.path.abspath(__file__))


def crops():
    gantry = np.asarray(Image.open(os.path.join(HERE, "gantrycrane.png")).convert("RGB"))
    voc = np.asarray(Image.open(os.path.join(HERE, "000012.jpg")).convert("RGB"))
    return [
        gantry[:64, :64], gantry[-64:, -64:], gantry[:64, -64:],
        voc[:64, :64], voc[-64:, -64:], voc[:64, -64:],
    ]


def jpeg_bytes(arr):
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=90)
    return buf.getvalue()


def write_tar(path, entries):
    # uncompressed tar (the native fast path indexes plain tars)
    with tarfile.open(path, "w") as tar:
        for name, data in entries:
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mtime = 0
            tar.addfile(info, io.BytesIO(data))


def main():
    cs = crops()
    jpegs = [jpeg_bytes(c) for c in cs]
    # imagenet: <synset>/<file> entries across two synsets, plus one
    # entry whose synset is NOT in the labels map (must be skipped)
    write_tar(os.path.join(HERE, "imagenet_mini.tar"), [
        ("n01234567/im_a.jpg", jpegs[0]),
        ("n01234567/im_b.jpg", jpegs[1]),
        ("n07654321/im_c.jpg", jpegs[2]),
        ("n07654321/im_d.jpg", jpegs[3]),
        ("n99999999/im_e.jpg", jpegs[4]),  # unlabeled synset
    ])
    # voc: flat files joined against a filename,class_id csv; one image
    # carries two labels, one has no csv row (skipped)
    write_tar(os.path.join(HERE, "voc_mini.tar"), [
        ("JPEGImages/000001.jpg", jpegs[0]),
        ("JPEGImages/000002.jpg", jpegs[3]),
        ("JPEGImages/000003.jpg", jpegs[5]),
        ("JPEGImages/000009.jpg", jpegs[2]),  # no label row
    ])
    with open(os.path.join(HERE, "voc_mini_labels.csv"), "w") as f:
        f.write("000001.jpg,3\n000001.jpg,11\n000002.jpg,0\n000003.jpg,19\n")
    print("wrote imagenet_mini.tar, voc_mini.tar, voc_mini_labels.csv")


if __name__ == "__main__":
    main()
