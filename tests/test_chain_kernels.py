"""Chain-megakernel backend acceptance suite — PR 16.

The contract (`keystone_tpu/ops/chain_kernels.py` + the fusion swap +
the unified planner's kernel axis):

  - both candidate families lower: the elementwise chain (the
    LinearPixels PixelScaler >> GrayScaler >> ImageVectorizer trail)
    and rectify→pool→vectorize, each matching its pure-jnp
    ``*_reference`` oracle in interpret mode at multiple AND ragged
    counts;
  - `fuse_masks_output` stages keep padded rows EXACT inside the
    kernel (the masked-stage column is streamed into VMEM);
  - a VMEM-overbudget geometry demotes cleanly: the dispatcher falls
    back to the oracle, and the planner prices the kernel assignment
    INF (`vmem_feasible` False → never chosen, never crashes);
  - the kill switch: `pallas_kernels=False` (env
    ``KEYSTONE_CHAIN_KERNELS=0``) reproduces the XLA-only program
    bit for bit and dispatches zero chain kernels;
  - the unified planner records the kernel decision in the ledger
    with the scored kernel/XLA alternative pair, and the enforced
    `planned_kernel` tag rides the fused program key (AOT-warmable:
    a warm second run performs zero cold compiles);
  - the bench tier: the ``kernel`` plan column exists and the
    LinearPixels bench instance actually swaps.
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.images.core import (
    GrayScaler,
    ImageVectorizer,
    PixelScaler,
)
from keystone_tpu.nodes.stats.scalers import StandardScalerModel
from keystone_tpu.nodes.util.fusion import (
    FusedBatchTransformer,
    _peephole,
    _stage_fuse,
)
from keystone_tpu.ops import chain_kernels as ck
from keystone_tpu.telemetry import ledger
from keystone_tpu.workflow import PipelineEnv
from keystone_tpu.workflow.env import config_override
from keystone_tpu.workflow.optimizer import DefaultOptimizer


def _elementwise_trail():
    """The LinearPixels featurizer trail — the planner's flagship
    elementwise-chain candidate."""
    stages = [PixelScaler(), GrayScaler(), ImageVectorizer()]
    fused = [_stage_fuse(s) for s in _peephole(stages)]
    return tuple(f[0] for f in fused), [f[1] for f in fused]


def _pipeline():
    return (PixelScaler().to_pipeline() >> GrayScaler()
            >> ImageVectorizer())


def _run(pipe, X, optimizer=None, **overrides):
    """One clean-env run; returns (host outputs, optimized graph)."""
    PipelineEnv.reset()
    try:
        if optimizer is not None:
            PipelineEnv.get().set_optimizer(optimizer)
        with config_override(**overrides):
            applied = pipe(Dataset.from_numpy(X))
            out = np.asarray(applied.get().numpy())
            return out, applied.executor.optimized_graph
    finally:
        PipelineEnv.reset()


def _serial_unfused(pipe, X):
    out, _ = _run(
        pipe, X,
        optimizer=DefaultOptimizer(fuse=False, sharding_planner=False,
                                   precision_planner=False,
                                   unified_planner=False),
        megafusion=False, overlap=False, concurrent_dispatch=False)
    return out


# ------------------------------------------------------------ lowerability


def test_lowerability_families():
    statics, _ = _elementwise_trail()
    v = ck.lowerability(statics)
    assert v["lowerable"] and v["family"] == "elementwise_chain", v

    from keystone_tpu.nodes.images.core import Pooler, SymmetricRectifier
    trail = [SymmetricRectifier(alpha=0.25), Pooler(6, 7, pool_fn="sum"),
             ImageVectorizer()]
    v = ck.lowerability(ck.stage_statics(trail))
    assert v["lowerable"] and v["family"] == "rectify_pool_vectorize", v


def test_unsupported_stage_is_a_named_suppression():
    """A chain blocked ONLY by deliberate non-lowerings (PaddedFFT)
    carries the named suppression the lint.sh audit accepts."""
    from keystone_tpu.nodes.stats import LinearRectifier, PaddedFFT
    trail = [PaddedFFT(), LinearRectifier(0.0)]
    v = ck.lowerability(ck.stage_statics(trail))
    assert not v["lowerable"]
    assert "PaddedFFT" in (v.get("suppressed") or {}), v


# ------------------------------------- interpret-mode numerics vs oracle


@pytest.mark.parametrize("n", [3, 11, 37, 64])
def test_elementwise_chain_interpret_matches_reference(n):
    """Multiple AND ragged counts: block_n=4 forces a padded tail
    block on every non-multiple count."""
    statics, params = _elementwise_trail()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(n, 8, 8, 3).astype(np.float32))
    got = np.asarray(ck.elementwise_chain_pallas(
        statics, params, x, block_n=4, interpret=True))
    want = np.asarray(ck.elementwise_chain_reference(statics, params, x))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("n", [2, 7, 16])
def test_rectify_pool_vectorize_interpret_matches_reference(n):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(n, 12, 12, 8).astype(np.float32))
    got = np.asarray(ck.rectify_pool_vectorize_pallas(
        x, 0.25, 0.0, 6, 5, interpret=True))
    want = np.asarray(ck.rectify_pool_vectorize_reference(
        x, 0.25, 0.0, 6, 5))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_masked_stage_padded_rows_exact():
    """`fuse_masks_output` inside the kernel: a chain containing a
    StandardScalerModel re-zeros padded rows at its chain position —
    bit-identical to the oracle's masking, including rows where the
    scaler would otherwise write (0 - mean) / std."""
    stages = [PixelScaler(), ImageVectorizer(),
              StandardScalerModel(np.full((192,), 0.5, np.float32),
                                  np.full((192,), 2.0, np.float32))]
    fused = [_stage_fuse(s) for s in _peephole(stages)]
    statics = tuple(f[0] for f in fused)
    params = [f[1] for f in fused]
    rng = np.random.RandomState(2)
    n, valid = 10, 6
    x = jnp.asarray(rng.rand(n, 8, 8, 3).astype(np.float32))
    mask = jnp.asarray(np.arange(n) < valid)
    got = np.asarray(ck.elementwise_chain_pallas(
        statics, params, x, mask, block_n=4, interpret=True))
    want = np.asarray(ck.elementwise_chain_reference(
        statics, params, x, mask))
    np.testing.assert_array_equal(got[valid:], want[valid:])
    assert np.all(got[valid:] == 0.0), "padded rows must stay zero"
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


# ------------------------------------------------- VMEM-overbudget demotion


def test_vmem_overbudget_demotes_to_reference(monkeypatch):
    """An overbudget geometry never crashes: the dispatcher falls back
    to the oracle, and `chain_feasible` reports the named reason the
    planner prices INF."""
    monkeypatch.setenv("KEYSTONE_CHAIN_KERNELS", "interpret")
    monkeypatch.setattr(ck, "_VMEM_BUDGET", 1)
    statics, params = _elementwise_trail()
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.rand(9, 8, 8, 3).astype(np.float32))
    got = np.asarray(ck.elementwise_chain(statics, params, x))
    want = np.asarray(ck.elementwise_chain_reference(statics, params, x))
    np.testing.assert_array_equal(got, want)

    ok, reason = ck.chain_feasible(
        [PixelScaler(), GrayScaler(), ImageVectorizer()], (8, 8, 3))
    assert not ok and "VMEM" in reason, (ok, reason)


def test_vmem_overbudget_planner_never_chooses_kernel(monkeypatch):
    """The planner's side of the demotion: with the budget floored the
    kernel assignment prices INF, so `kernel_choices` stays empty and
    the joint plan remains feasible."""
    from keystone_tpu.analysis import as_source_spec
    from keystone_tpu.analysis.examples import build_example
    from keystone_tpu.analysis.plan_ir import plan_unified
    from keystone_tpu.analysis.propagate import spec_pass

    monkeypatch.setattr(ck, "_VMEM_BUDGET", 1)
    pipeline, source_spec = build_example("LinearPixels")
    specs, _ = spec_pass(
        pipeline.graph, {pipeline.source: as_source_spec(source_spec)})
    uplan = plan_unified(pipeline.graph, specs)
    assert uplan is not None
    assert uplan.kernel_choices == {}, uplan.kernel_choices
    assert uplan.joint_seconds <= uplan.sequential_seconds
    infeasible = [c for c in uplan.scored_candidates
                  if c["entry"].startswith("kernel_") and
                  c["entry"].endswith("_on")]
    assert all(not c["feasible"] for c in infeasible), infeasible


def test_planner_prices_kernel_axis_on_linear_pixels():
    """The healthy-budget twin: the kernel axis joins the product menu,
    the chosen plan turns it on, and the scored entries carry the
    kernel/XLA pair the ledger records."""
    from keystone_tpu.analysis import as_source_spec
    from keystone_tpu.analysis.examples import build_example
    from keystone_tpu.analysis.plan_ir import plan_unified
    from keystone_tpu.analysis.propagate import spec_pass

    pipeline, source_spec = build_example("LinearPixels")
    specs, _ = spec_pass(
        pipeline.graph, {pipeline.source: as_source_spec(source_spec)})
    uplan = plan_unified(pipeline.graph, specs)
    assert uplan is not None and uplan.kernel_choices, uplan
    assert "kernel" in uplan.changed_kinds()
    for cand in uplan.kernel_choices.values():
        assert cand["kernel_seconds"] < cand["chain_seconds"], cand
        assert (cand.get("lowerable") or {}).get("family"), cand
    assert any(c["entry"].startswith("kernel_") and c["feasible"]
               for c in uplan.scored_candidates), uplan.scored_candidates


# ------------------------------------------------------- e2e swap + parity


@pytest.mark.parametrize("n", [37, 64])
def test_e2e_kernel_swap_matches_serial_unfused(monkeypatch, n):
    """The full optimizer path at multiple AND ragged counts: the plan
    tags `planned_kernel`, the fused program dispatches the interpret
    kernel, outputs stay allclose to the serial unfused path."""
    monkeypatch.setenv("KEYSTONE_CHAIN_KERNELS", "interpret")
    pipe = _pipeline()
    rng = np.random.RandomState(4)
    X = rng.rand(n, 8, 8, 3).astype(np.float32)
    out, g = _run(pipe, X, unified_min_savings_seconds=0.0)
    tagged = [op for vid in g.operators
              for op in [g.get_operator(vid)]
              if getattr(op, "planned_kernel", None) is not None]
    assert tagged, "no operator carries a planned_kernel tag"
    start, stop, family = tagged[0].planned_kernel
    assert family == "elementwise_chain" and stop - start >= 2
    base = _serial_unfused(pipe, X)
    np.testing.assert_allclose(out, base, rtol=1e-5, atol=1e-6)


def test_kill_switch_bit_for_bit(monkeypatch):
    """`pallas_kernels=False` reproduces the XLA-only fused program bit
    for bit (same outputs as a run that never heard of kernels) and
    plans no kernel."""
    pipe = _pipeline()
    rng = np.random.RandomState(5)
    X = rng.rand(37, 8, 8, 3).astype(np.float32)
    # reference: the pre-PR16 program (no kernel gate consulted at all
    # off-TPU — use_chain_kernels() is False without the interpret hook)
    want, _ = _run(pipe, X, unified_min_savings_seconds=0.0)
    # killed: planner enforcement off, swap gated off
    got, g = _run(pipe, X, unified_min_savings_seconds=0.0,
                  pallas_kernels=False)
    np.testing.assert_array_equal(got, want)
    assert not [op for vid in g.operators
                for op in [g.get_operator(vid)]
                if getattr(op, "planned_kernel", None) is not None]


def test_stale_kernel_tag_is_ignored(monkeypatch):
    """A `planned_kernel` tag that no longer matches the stage trail
    (the `planned_precision` stale-tag discipline) is silently ignored,
    never mis-lowered."""
    monkeypatch.setenv("KEYSTONE_CHAIN_KERNELS", "interpret")
    rng = np.random.RandomState(6)
    X = rng.rand(13, 8, 8, 3).astype(np.float32)
    stages = [PixelScaler(), GrayScaler(), ImageVectorizer()]
    op = FusedBatchTransformer(stages)
    op.planned_kernel = (0, 9, "elementwise_chain")  # out of range
    PipelineEnv.reset()
    try:
        got = np.asarray(op.apply_batch(
            Dataset.from_numpy(X)).numpy())
        ref = FusedBatchTransformer(
            [PixelScaler(), GrayScaler(), ImageVectorizer()])
        want = np.asarray(ref.apply_batch(
            Dataset.from_numpy(X)).numpy())
    finally:
        PipelineEnv.reset()
    np.testing.assert_array_equal(got, want)


# ------------------------------------------------- ledger + warm compiles


def test_kernel_decision_ledger_record(monkeypatch):
    """The enforced kernel axis is ledger-recorded: kind="kernel",
    chosen kernels naming family/slice/prices, and the scored
    alternatives carry the sequential (XLA) and kernel entries."""
    monkeypatch.setenv("KEYSTONE_CHAIN_KERNELS", "interpret")
    pipe = _pipeline()
    rng = np.random.RandomState(7)
    X = rng.rand(37, 8, 8, 3).astype(np.float32)
    mark = ledger.session_mark()
    _run(pipe, X, unified_min_savings_seconds=0.0)
    recs = [r for r in ledger.session_since(mark)
            if r.get("kind") == "kernel"]
    assert recs, "no kernel decision recorded"
    rec = recs[0]
    assert rec["enforced"] and rec["rule"] == "UnifiedPlannerRule", rec
    kernels = (rec.get("chosen") or {}).get("kernels")
    assert kernels, rec
    assert kernels[0]["family"] == "elementwise_chain"
    assert kernels[0]["kernel_seconds"] < kernels[0]["chain_seconds"]
    entries = [a.get("entry") for a in rec.get("alternatives") or []]
    assert "sequential" in entries, entries
    assert any(str(e).startswith("kernel_") for e in entries), entries


def test_ledger_header_names_the_kill_switch():
    """`--diff` can name a kernel flip as the suspect: the header
    snapshots `pallas_kernels` with its env knob."""
    assert ledger.CONFIG_ENV["pallas_kernels"] == "KEYSTONE_CHAIN_KERNELS"
    assert "kernel" in ledger.KINDS


def test_warm_kernel_run_zero_cold_compiles():
    """A rebuilt-from-scratch second run with a planned kernel serves
    everything warm: `planned_kernel` is part of the fused program key,
    so the swapped program caches like any other."""
    from keystone_tpu.dispatch_bench import measure_example
    from keystone_tpu.telemetry import compiles_snapshot
    from keystone_tpu.workflow.executor import drain_warmups

    r1 = measure_example("LinearPixels", "kernel")
    assert r1["apply_run_programs"] >= 1
    drain_warmups()
    first = compiles_snapshot()
    r2 = measure_example("LinearPixels", "kernel")
    drain_warmups()
    second = compiles_snapshot()
    new_cold = second["programs_compiled"] - first["programs_compiled"]
    assert new_cold == 0, (
        f"warm kernel-plan run performed {new_cold} cold compile(s)")
    assert any(d.get("kind") == "kernel" for d in r2["decisions"] or []), (
        "warm run lost the kernel decision")


def test_bench_kernel_plan_column():
    """The dispatch-bench tier gained the `kernel` plan: listed in
    PLANS, and its context turns the unified planner + kernels on."""
    from keystone_tpu.dispatch_bench import PLANS, _plan_context

    assert "kernel" in PLANS
    _, _, _, overrides = _plan_context("kernel")
    assert overrides["unified_planner"] is True
    assert overrides["pallas_kernels"] is True
    assert overrides["unified_min_savings_seconds"] == 0.0
