"""Single-datum serving path (VERDICT r4 #7).

The reference's dual batch/single dispatch (Operator.scala:77-100,
`batchTransform` vs `singleTransform` chosen by expression type) is a
core preserved property: a fitted pipeline serves one datum through the
same fitted state as a batch, with NO recompilation per request. These
tests pin both halves: single/batch parity, and warm applies triggering
zero XLA compilations (detected via jax's monitoring events, not
timing).
"""

import numpy as np
import pytest

from keystone_tpu.workflow import PipelineEnv


@pytest.fixture(autouse=True)
def _reset_env():
    PipelineEnv.reset()
    yield
    PipelineEnv.reset()


def _compile_events(fn):
    """Run fn, return the number of XLA compile requests it triggered."""
    from jax._src import monitoring

    events = []

    def listener(name, **kw):
        if name == "/jax/compilation_cache/compile_requests_use_cache":
            events.append(name)

    monitoring.register_event_listener(listener)
    try:
        out = fn()
    finally:
        try:
            monitoring._event_listeners.remove(listener)
        except ValueError:  # pragma: no cover - listener wrapper changed
            monitoring.clear_event_listeners()
    return len(events), out


def _fitted_cifar():
    from keystone_tpu.loaders.cifar_loader import synthetic_cifar
    from keystone_tpu.pipelines.random_patch_cifar import (
        RandomPatchCifarConfig,
        build_pipeline,
    )

    config = RandomPatchCifarConfig(
        num_filters=16, block_size=64, microbatch=32,
        synth_train=0, synth_test=0)
    train, _ = synthetic_cifar(64, 8, config.num_classes, config.seed)
    predictor = build_pipeline(train, config)
    return predictor.fit(), train


def test_cifar_single_datum_parity_and_no_recompile():
    fitted, train = _fitted_cifar()
    images = np.asarray(train.data.numpy())
    batch_preds = np.asarray(fitted.apply(train.data).numpy())

    # warm the single-datum (batch=1) programs
    first = fitted.apply(images[0])
    assert int(first) == int(batch_preds[0])

    # warm serving must not compile anything new, and must match the
    # batch path datum-for-datum (single/batch duality)
    def serve():
        return [int(fitted.apply(images[i])) for i in range(1, 4)]

    n_compiles, preds = _compile_events(serve)
    assert n_compiles == 0, (
        f"single-datum serving recompiled {n_compiles} programs on warm "
        "applies — the batch=1 path must stay jit-cached")
    assert preds == [int(p) for p in batch_preds[1:4]]


def test_newsgroups_single_doc_parity_and_no_recompile():
    from keystone_tpu.pipelines.text_pipelines import (
        build_newsgroups_predictor,
        synthetic_corpus,
    )

    labels, docs = synthetic_corpus(80, 3, seed=0)
    fitted = build_newsgroups_predictor(
        docs, labels, 3, common_features=500).fit()

    doc_items = list(docs.items)
    batch_preds = [int(p) for p in fitted.apply(docs).numpy()]
    first = fitted.apply(doc_items[0])  # warm batch=1 programs
    assert int(first) == batch_preds[0]

    def serve():
        return [int(fitted.apply(doc_items[i])) for i in range(1, 4)]

    n_compiles, preds = _compile_events(serve)
    assert n_compiles == 0, (
        f"single-doc serving recompiled {n_compiles} programs")
    assert preds == batch_preds[1:4]
