"""jaxlint (scripts/jaxlint.py) — the repo-wide AST lint gate.

Marked ``lint``: these run in tier-1 (they are fast and data-free) and
mirror `scripts/lint.sh`'s first stage, so CI and pytest cannot drift."""

import importlib.util
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.lint

REPO = Path(__file__).resolve().parent.parent


def _jaxlint():
    spec = importlib.util.spec_from_file_location(
        "jaxlint", REPO / "scripts" / "jaxlint.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_repo_is_clean():
    jl = _jaxlint()
    findings = []
    for f in sorted((REPO / "keystone_tpu").rglob("*.py")):
        findings.extend(jl.lint_file(f, repo_root=REPO))
    assert not findings, "\n".join(map(str, findings))


def test_seeded_violations_are_caught(tmp_path):
    jl = _jaxlint()
    bad = tmp_path / "nodes" / "learning" / "bad_solver.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "from functools import partial\n"
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "\n"
        "\n"
        "def fit(xs, W):\n"
        "    acc = jnp.zeros(4)\n"
        "    for x in xs:\n"
        "        acc = acc + jnp.dot(W, x)\n"     # KJ001
        "    return acc\n"
        "\n"
        "\n"
        "@jax.jit\n"
        "def apply(x):\n"
        "    return np.sum(x)\n"                   # KJ002
        "\n"
        "\n"
        "@partial(jax.jit, static_argnames=('n',))\n"
        "def _bad_step(W, R, n):\n"                # KJ003: no donate_argnums
        "    return W + R, R\n"
    )
    rules = sorted({f.rule for f in jl.lint_file(bad)})
    assert rules == ["KJ001", "KJ002", "KJ003"]


def test_kj005_flags_blocking_host_pulls(tmp_path):
    """KJ005: block_until_ready and np.asarray-over-device-values in
    workflow/ and nodes/ hot paths are flagged; a plain np.asarray over
    host items is not."""
    jl = _jaxlint()
    bad = tmp_path / "workflow" / "bad_pull.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "\n"
        "\n"
        "def force(data, x):\n"
        "    jax.block_until_ready(x)\n"                      # KJ005
        "    a = np.asarray(jnp.take(x, 0))\n"                # KJ005
        "    b = np.asarray(data.array)\n"                    # KJ005
        "    c = np.asarray([1, 2, 3])\n"                     # host: ok
        "    return a, b, c\n"
    )
    findings = jl.lint_file(bad)
    assert [f.rule for f in findings] == ["KJ005", "KJ005", "KJ005"]
    assert sorted(f.line for f in findings) == [7, 8, 9]

    # outside workflow/ and nodes/, the rule does not apply
    elsewhere = tmp_path / "loaders" / "ok_pull.py"
    elsewhere.parent.mkdir(parents=True)
    elsewhere.write_text(bad.read_text())
    assert jl.lint_file(elsewhere) == []


def test_kj005_suppression(tmp_path):
    jl = _jaxlint()
    f = tmp_path / "nodes" / "sanctioned.py"
    f.parent.mkdir(parents=True)
    f.write_text(
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "\n"
        "\n"
        "def drain(x):\n"
        "    return np.asarray(jnp.ravel(x))  "
        "# keystone: ignore[KJ005]\n"
    )
    assert jl.lint_file(f) == []


def test_suppression_comment_honored(tmp_path):
    jl = _jaxlint()
    f = tmp_path / "nodes" / "ok.py"
    f.parent.mkdir(parents=True)
    f.write_text(
        "import jax.numpy as jnp\n"
        "\n"
        "\n"
        "def fit(xs, W):\n"
        "    acc = jnp.zeros(4)\n"
        "    for x in xs:\n"
        "        acc = acc + jnp.dot(W, x)  # keystone: ignore[KJ001]\n"
        "    return acc\n"
    )
    assert jl.lint_file(f) == []


def test_kj009_flags_hardcoded_axis_literals(tmp_path):
    """KJ009 (axis-literal half): bare "data"/"model" strings in
    sharding constructions, collective calls, axis kwargs, and
    mesh.shape.get lookups under nodes//workflow/ are flagged; the
    meshlib-constant spelling and plain string data are not."""
    jl = _jaxlint()
    bad = tmp_path / "workflow" / "bad_axes.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "from jax import lax\n"
        "from jax.sharding import NamedSharding, PartitionSpec as P\n"
        "from keystone_tpu.parallel import mesh as meshlib\n"
        "\n"
        "\n"
        "def place(x, mesh):\n"
        "    a = P(\"data\", \"model\")\n"                       # KJ009
        "    b = NamedSharding(mesh, P(meshlib.DATA_AXIS))\n"    # ok
        "    c = lax.psum(x, \"data\")\n"                        # KJ009
        "    d = lax.psum(x, meshlib.DATA_AXIS)\n"               # ok
        "    e = mesh.shape.get(\"model\", 1)\n"                 # KJ009
        "    f = tree_reduce(x, axis=\"data\")\n"                # KJ009
        "    g = {\"data\": \"datum\"}\n"                        # plain: ok
        "    h = [\"model\", \"level\"]\n"                       # plain: ok
        "    return a, b, c, d, e, f, g, h\n"
    )
    findings = jl.lint_file(bad)
    assert [f.rule for f in findings] == ["KJ009"] * 4
    assert sorted(f.line for f in findings) == [7, 9, 11, 12]

    # outside nodes/ and workflow/, the axis-literal half does not apply
    elsewhere = tmp_path / "loaders" / "ok_axes.py"
    elsewhere.parent.mkdir(parents=True)
    elsewhere.write_text(bad.read_text())
    assert jl.lint_file(elsewhere) == []


def test_kj009_flags_bare_device_put(tmp_path):
    """KJ009 (device_put half): a sharding-less jax.device_put in the
    parallel-adjacent layers is flagged; explicit placements pass."""
    jl = _jaxlint()
    bad = tmp_path / "parallel" / "bad_put.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import jax\n"
        "from jax.sharding import NamedSharding, PartitionSpec as P\n"
        "\n"
        "\n"
        "def place(x, mesh):\n"
        "    a = jax.device_put(x)\n"                            # KJ009
        "    b = jax.device_put(x, NamedSharding(mesh, P()))\n"  # ok
        "    c = jax.device_put(x, device=None)\n"               # ok
        "    return a, b, c\n"
    )
    findings = jl.lint_file(bad)
    assert [f.rule for f in findings] == ["KJ009"]
    assert findings[0].line == 6

    # nodes/ hot paths are policed by the axis half, not the put half
    elsewhere = tmp_path / "nodes" / "ok_put.py"
    elsewhere.parent.mkdir(parents=True)
    elsewhere.write_text(bad.read_text())
    assert jl.lint_file(elsewhere) == []


def test_kj010_flags_in_shardings_without_out_shardings(tmp_path):
    """KJ010: a jax.jit/pjit call pinning in_shardings but not
    out_shardings leaks the output layout to XLA's partitioner (the
    caller re-shards downstream); fully-specified and fully-unspecified
    jits pass."""
    jl = _jaxlint()
    bad = tmp_path / "workflow" / "bad_layout.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import jax\n"
        "from jax.experimental.pjit import pjit\n"
        "\n"
        "\n"
        "def build(fn, sh):\n"
        "    a = jax.jit(fn, in_shardings=(sh,))\n"              # KJ010
        "    b = pjit(fn, in_shardings=(sh,))\n"                 # KJ010
        "    c = jax.jit(fn, in_shardings=(sh,), out_shardings=sh)\n"
        "    d = jax.jit(fn)\n"                                  # ok
        "    e = jax.jit(fn, donate_argnums=(0,))\n"             # ok
        "    return a, b, c, d, e\n"
    )
    findings = jl.lint_file(bad)
    assert [f.rule for f in findings] == ["KJ010"] * 2
    assert sorted(f.line for f in findings) == [6, 7]

    # outside nodes/ and workflow/, KJ010 does not apply
    elsewhere = tmp_path / "scripts" / "ok_layout.py"
    elsewhere.parent.mkdir(parents=True)
    elsewhere.write_text(bad.read_text())
    assert jl.lint_file(elsewhere) == []


def test_kj010_suppression(tmp_path):
    jl = _jaxlint()
    src = tmp_path / "nodes" / "suppressed_layout.py"
    src.parent.mkdir(parents=True)
    src.write_text(
        "import jax\n"
        "\n"
        "\n"
        "def build(fn, sh):\n"
        "    return jax.jit(fn, in_shardings=(sh,))"
        "  # keystone: ignore[KJ010]\n"
    )
    assert jl.lint_file(src) == []


def test_kj008_flags_self_container_mutator_calls(tmp_path):
    """Review regression: `self.seen.append(x)` in a hot path races
    exactly like `self.seen[k] = x` and must be flagged; mutator calls
    on the sanctioned `self.__dict__` memo chain must not."""
    jl = _jaxlint()
    bad = tmp_path / "nodes" / "bad_mutator.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "class T:\n"
        "    def add(self, a, b):\n"
        "        return a + b\n"
        "    def apply(self, x):\n"
        "        self.seen.append(x)\n"                    # KJ008
        "        self.__dict__.setdefault('memo', {})\n"   # sanctioned
        "        self.__dict__['hits'].append(x)\n"        # sanctioned
        "        y = self.add(x, x)\n"                     # method: ok
        "        return y\n")
    findings = jl.lint_file(bad)
    assert [f.rule for f in findings] == ["KJ008"]
    assert findings[0].line == 5 and "self.seen.append" in findings[0].message


def test_kj009_suppression(tmp_path):
    jl = _jaxlint()
    f = tmp_path / "data" / "sanctioned_put.py"
    f.parent.mkdir(parents=True)
    f.write_text(
        "import jax\n"
        "\n"
        "\n"
        "def stage(x):\n"
        "    return jax.device_put(x)  # keystone: ignore[KJ009]\n"
    )
    assert jl.lint_file(f) == []


def test_nested_loop_reports_once(tmp_path):
    jl = _jaxlint()
    f = tmp_path / "nodes" / "nested.py"
    f.parent.mkdir(parents=True)
    f.write_text(
        "import jax.numpy as jnp\n"
        "\n"
        "\n"
        "def fit(xss, total):\n"
        "    for xs in xss:\n"
        "        for x in xs:\n"
        "            total += jnp.dot(x, x)\n"
        "    return total\n"
    )
    findings = jl.lint_file(f)
    assert len(findings) == 1 and findings[0].rule == "KJ001"


def test_donate_argnums_present_passes(tmp_path):
    jl = _jaxlint()
    f = tmp_path / "nodes" / "learning" / "good_solver.py"
    f.parent.mkdir(parents=True)
    f.write_text(
        "from functools import partial\n"
        "import jax\n"
        "\n"
        "\n"
        "@partial(jax.jit, donate_argnums=(0, 1))\n"
        "def _good_step(W, R):\n"
        "    return W + R, R\n"
    )
    assert jl.lint_file(f) == []


def test_wall_clock_duration_caught(tmp_path):
    """KJ004: time.time() flagged in both the module-attribute and the
    from-import forms; perf_counter passes; suppression honored."""
    jl = _jaxlint()
    bad = tmp_path / "timing.py"
    bad.write_text(
        "import time\n"
        "from time import time as _t  # not the bare name: no bare-form flag\n"
        "\n"
        "\n"
        "def measure(fn):\n"
        "    t0 = time.time()\n"                    # KJ004
        "    fn()\n"
        "    return time.time() - t0\n"             # KJ004
    )
    findings = jl.lint_file(bad)
    assert [f.rule for f in findings] == ["KJ004", "KJ004"]
    assert findings[0].line == 6

    bare = tmp_path / "bare.py"
    bare.write_text(
        "from time import time\n"
        "\n"
        "\n"
        "def measure():\n"
        "    return time()\n"                       # KJ004 (bare form)
    )
    assert [f.rule for f in jl.lint_file(bare)] == ["KJ004"]

    good = tmp_path / "good.py"
    good.write_text(
        "import time\n"
        "\n"
        "\n"
        "def measure(fn):\n"
        "    t0 = time.perf_counter()\n"
        "    fn()\n"
        "    return time.perf_counter() - t0\n"
        "\n"
        "\n"
        "def stamp():\n"
        "    return time.time()  # keystone: ignore[KJ004]\n"
    )
    assert jl.lint_file(good) == []


def test_kj006_flags_fresh_jit_per_call(tmp_path):
    """KJ006: jit of a lambda / same-scope def inside a function body,
    and ANY jit call inside a loop, are flagged in workflow/ and
    nodes/; the instance-memoized idiom (jit over a call expression)
    and module-level jits pass."""
    jl = _jaxlint()
    bad = tmp_path / "workflow" / "bad_jit.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "\n"
        "\n"
        "def per_call(x):\n"
        "    f = jax.jit(lambda v: v * 2.0)\n"              # KJ006
        "    def step(v):\n"
        "        return v + 1.0\n"
        "    g = jax.jit(step)\n"                           # KJ006
        "    h = step\n"
        "    k = jax.jit(h)\n"                              # KJ006 (alias)
        "    return k(g(f(x)))\n"
        "\n"
        "\n"
        "def looped(xs):\n"
        "    out = []\n"
        "    for x in xs:\n"
        "        out.append(jax.jit(jnp.ravel)(x))\n"       # KJ006 (loop)
        "    return out\n"
    )
    findings = jl.lint_file(bad)
    assert [f.rule for f in findings] == ["KJ006"] * 4, findings
    assert sorted(f.line for f in findings) == [6, 9, 11, 18]

    good = tmp_path / "workflow" / "good_jit.py"
    good.write_text(
        "import jax\n"
        "\n"
        "_module_jit = jax.jit(lambda v: v * 2.0)\n"  # once per import: ok
        "\n"
        "\n"
        "class T:\n"
        "    def _fn(self):\n"
        "        return lambda v: v + 1.0\n"
        "\n"
        "    def apply(self, x):\n"
        "        f = self.__dict__.get('_jitted')\n"
        "        if f is None:\n"
        "            f = jax.jit(self._fn())\n"  # memoized idiom: ok
        "            self.__dict__['_jitted'] = f\n"
        "        return f(x)\n"
    )
    assert jl.lint_file(good) == []

    # outside workflow/ and nodes/, the rule does not apply
    elsewhere = tmp_path / "loaders" / "ok_jit.py"
    elsewhere.parent.mkdir(parents=True)
    elsewhere.write_text(bad.read_text())
    assert jl.lint_file(elsewhere) == []


def test_kj006_suppression(tmp_path):
    jl = _jaxlint()
    f = tmp_path / "nodes" / "cached_jit.py"
    f.parent.mkdir(parents=True)
    f.write_text(
        "import jax\n"
        "\n"
        "_CACHE = {}\n"
        "\n"
        "\n"
        "def program(key):\n"
        "    def fn(v):\n"
        "        return v * 2.0\n"
        "    if key not in _CACHE:\n"
        "        _CACHE[key] = jax.jit(fn)  # keystone: ignore[KJ006]\n"
        "    return _CACHE[key]\n"
    )
    assert jl.lint_file(f) == []


def test_kj007_flags_carry_realloc(tmp_path):
    """KJ007: a scan/fori body that rebuilds its carry with an
    allocating jnp call (concatenate/pad/...) and no in-place update is
    flagged in workflow/ and nodes/ — the megafused scan must never
    double O(model) state per trip."""
    jl = _jaxlint()
    bad = tmp_path / "nodes" / "bad_scan.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax import lax\n"
        "\n"
        "\n"
        "def grow(xs):\n"
        "    def body(carry, x):\n"
        "        return jnp.concatenate([carry, x[None]]), x\n"  # KJ007
        "    return lax.scan(body, jnp.zeros((0, 4)), xs)[0]\n"
        "\n"
        "\n"
        "def widen(xs):\n"
        "    def body(i, W):\n"
        "        return jnp.pad(W, ((0, 0), (0, 0))) + i\n"      # KJ007
        "    return lax.fori_loop(0, 8, body, jnp.zeros((8, 8)))\n"
    )
    rules = [f.rule for f in jl.lint_file(bad)]
    assert rules == ["KJ007", "KJ007"], rules


def test_kj007_inplace_and_output_patterns_pass(tmp_path):
    """KJ007 negatives: dynamic_update_slice / .at[].set carries, the
    empty-carry ys-output scan (the megafused program's own shape), an
    arithmetic solver carry, and code outside workflow//nodes/."""
    jl = _jaxlint()
    good = tmp_path / "workflow" / "good_scan.py"
    good.parent.mkdir(parents=True)
    good.write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax import lax\n"
        "\n"
        "\n"
        "def fill(xs):\n"
        "    def body(carry, ix):\n"
        "        i, x = ix\n"
        "        return lax.dynamic_update_slice(carry, x[None], (i, 0)), x\n"
        "    return lax.scan(body, jnp.zeros((8, 4)), xs)[0]\n"
        "\n"
        "\n"
        "def fill_at(xs):\n"
        "    def body(i, carry):\n"
        "        return carry.at[i].set(i * 1.0)\n"
        "    return lax.fori_loop(0, 8, body, jnp.zeros((8,)))\n"
        "\n"
        "\n"
        "def megafused_shape(xs, ms, chunk_fn):\n"
        "    def trip(carry, xm):\n"
        "        xb, mb = xm\n"
        "        return carry, chunk_fn(xb, mb)\n"
        "    return lax.scan(trip, (), (xs, ms))[1]\n"
        "\n"
        "\n"
        "def solver(xs, W0):\n"
        "    def step(W, x):\n"
        "        return W + jnp.outer(x, x), ()\n"
        "    return lax.scan(step, W0, xs)[0]\n"
    )
    assert jl.lint_file(good) == []

    elsewhere = tmp_path / "scripts_like" / "good_scan.py"
    elsewhere.parent.mkdir(parents=True)
    bad_body = (
        "import jax.numpy as jnp\n"
        "from jax import lax\n"
        "\n"
        "\n"
        "def grow(xs):\n"
        "    def body(carry, x):\n"
        "        return jnp.concatenate([carry, x[None]]), x\n"
        "    return lax.scan(body, jnp.zeros((0, 4)), xs)[0]\n"
    )
    elsewhere.write_text(bad_body)
    assert jl.lint_file(elsewhere) == []  # scope: workflow/ + nodes/ only


def test_kj007_suppression(tmp_path):
    jl = _jaxlint()
    f = tmp_path / "nodes" / "suppressed_scan.py"
    f.parent.mkdir(parents=True)
    f.write_text(
        "import jax.numpy as jnp\n"
        "from jax import lax\n"
        "\n"
        "\n"
        "def grow(xs):\n"
        "    def body(carry, x):\n"
        "        return jnp.concatenate([carry, x[None]]), x  "
        "# keystone: ignore[KJ007]\n"
        "    return lax.scan(body, jnp.zeros((0, 4)), xs)[0]\n"
    )
    assert jl.lint_file(f) == []


def test_kj008_flags_hot_path_state_writes(tmp_path):
    """KJ008: assignment to self.*, a declared global, or a module-level
    container inside apply/apply_batch/_chunk_loop is flagged in
    workflow/ and nodes/ — the KP511 race class at the file level."""
    jl = _jaxlint()
    bad = tmp_path / "nodes" / "bad_state.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "_TABLE = {}\n"
        "_total = 0\n"
        "\n"
        "\n"
        "class T:\n"
        "    def apply(self, x):\n"
        "        self.state = x\n"                       # KJ008
        "        return x\n"
        "\n"
        "    def apply_batch(self, data):\n"
        "        global _total\n"
        "        _total = _total + 1\n"                  # KJ008
        "        _TABLE[id(data)] = data\n"              # KJ008
        "        _TABLE.setdefault(0, data)\n"           # KJ008
        "        return data\n"
        "\n"
        "    def fit(self, data):\n"
        "        self.model = data\n"                    # fit: not hot
        "        return self\n"
    )
    findings = jl.lint_file(bad)
    assert [f.rule for f in findings] == ["KJ008"] * 4, findings
    assert sorted(f.line for f in findings) == [7, 12, 13, 14]

    # outside workflow/ and nodes/, the rule does not apply
    elsewhere = tmp_path / "loaders" / "ok_state.py"
    elsewhere.parent.mkdir(parents=True)
    elsewhere.write_text(bad.read_text())
    assert jl.lint_file(elsewhere) == []


def test_kj008_sanctioned_idioms_pass(tmp_path):
    """KJ008 negatives: the self.__dict__ memo idiom, structure-keyed
    caches (*CACHE*/*PENDING* names), local mutations, and writes
    outside hot-path methods."""
    jl = _jaxlint()
    good = tmp_path / "workflow" / "good_state.py"
    good.parent.mkdir(parents=True)
    good.write_text(
        "_PROGRAM_CACHE = {}\n"
        "_WARMUP_PENDING = {}\n"
        "\n"
        "\n"
        "class T:\n"
        "    def __init__(self):\n"
        "        self.model = None\n"                    # wiring: not hot
        "\n"
        "    def apply(self, x):\n"
        "        f = self.__dict__.get('_jitted')\n"
        "        if f is None:\n"
        "            self.__dict__['_jitted'] = f = x\n"  # memo idiom: ok
        "        _PROGRAM_CACHE[id(x)] = x\n"             # cache: ok\n
        "        _WARMUP_PENDING.pop(id(x), None)\n"      # cache: ok
        "        out = []\n"
        "        out.append(x)\n"                         # local: ok
        "        return out\n"
    )
    assert jl.lint_file(good) == []


def test_kj008_suppression(tmp_path):
    jl = _jaxlint()
    f = tmp_path / "nodes" / "suppressed_state.py"
    f.parent.mkdir(parents=True)
    f.write_text(
        "class T:\n"
        "    def apply(self, x):\n"
        "        self.last = x  # keystone: ignore[KJ008]\n"
        "        return x\n"
    )
    assert jl.lint_file(f) == []


def test_kj011_flags_literal_f32_casts_in_fused_bodies(tmp_path):
    """KJ011: literal float32 casts/scalars inside `fuse()` /
    `_chunk_loop` bodies silently re-promote bf16 boundaries and defeat
    the precision policy. All three forms flag: `.astype(jnp.float32)`,
    a bare `jnp.float32(...)` scalar (jnp promotion widens bf16 tensors
    against it), and a `dtype=jnp.float32` / positional-dtype call
    argument. Dtype-matched casts and code outside fused bodies pass."""
    jl = _jaxlint()
    bad = tmp_path / "nodes" / "bad_precision.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import jax.numpy as jnp\n"
        "\n"
        "\n"
        "class T:\n"
        "    def fuse(self):\n"
        "        def fn(p, x):\n"
        "            a = x.astype(jnp.float32)\n"            # KJ011
        "            b = a - jnp.float32(0.5)\n"             # KJ011
        "            c = jnp.asarray(b, jnp.float32)\n"      # KJ011
        "            d = jnp.zeros(3, dtype=jnp.float32)\n"  # KJ011
        "            e = jnp.asarray(0.5, x.dtype)\n"        # ok: matched
        "            return c + d.sum() + e\n"
        "        return ((\"T\",), (), fn)\n"
        "\n"
        "    def _chunk_loop(self, fn, params, xs, ms):\n"
        "        return fn(params, xs.astype(jnp.float32), ms)\n"  # KJ011
        "\n"
        "    def _build_program(self, mesh, shards, n, treedef, fns):\n"
        "        def per_shard(flat, xs, ms):\n"
        "            return xs.astype(jnp.float32)\n"           # KJ011
        "        return per_shard\n"
        "\n"
        "    def apply(self, x):\n"
        "        return x.astype(jnp.float32)\n"  # ok: not a fused body\n"
    )
    findings = jl.lint_file(bad)
    assert [f.rule for f in findings] == ["KJ011"] * 6
    assert sorted(f.line for f in findings) == [7, 8, 9, 10, 16, 20]

    # outside nodes/ and workflow/, KJ011 does not apply
    elsewhere = tmp_path / "loaders" / "ok_precision.py"
    elsewhere.parent.mkdir(parents=True)
    elsewhere.write_text(bad.read_text())
    assert jl.lint_file(elsewhere) == []


def test_kj011_suppression(tmp_path):
    """A genuine kernel constraint (RFFT accepts only f32/f64, uint8
    pixel decode) suppresses line-by-line with a rationale."""
    jl = _jaxlint()
    src = tmp_path / "workflow" / "suppressed_precision.py"
    src.parent.mkdir(parents=True)
    src.write_text(
        "import jax.numpy as jnp\n"
        "\n"
        "\n"
        "class T:\n"
        "    def fuse(self):\n"
        "        def fn(p, x):\n"
        "            # rfft accepts only f32/f64: widening is the\n"
        "            # kernel's contract, not a policy leak\n"
        "            return x.astype(jnp.float32)"
        "  # keystone: ignore[KJ011]\n"
        "        return ((\"T\",), (), fn)\n"
    )
    assert jl.lint_file(src) == []


def test_kj012_flags_dynamic_metric_names(tmp_path):
    """KJ012: `counter/gauge/histogram` with a non-literal metric name
    in workflow/+nodes/ hot paths mints unbounded registry cardinality.
    All the dynamic forms flag — f-string, %-format, concatenation,
    `.format()`, a plain variable, attribute/alias call forms, and a
    dynamic `name=` kwarg; literal names (positional or kwarg) pass."""
    jl = _jaxlint()
    bad = tmp_path / "workflow" / "bad_metrics.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "from keystone_tpu.telemetry import counter, gauge, histogram\n"
        "from keystone_tpu.telemetry import counter as _counter\n"
        "from keystone_tpu.telemetry import registry\n"
        "from keystone_tpu import telemetry\n"
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "\n"
        "\n"
        "def force(vertex, label, name, data):\n"
        "    counter(f'executor.forces.{vertex}').inc()\n"      # KJ012
        "    counter('executor.forces.%d' % vertex).inc()\n"    # KJ012
        "    gauge('live.' + label).add(1.0)\n"                 # KJ012
        "    histogram('t.{}'.format(label)).observe(0.1)\n"    # KJ012
        "    counter(name).inc()\n"                             # KJ012
        "    _counter(f'x.{vertex}').inc()\n"                   # KJ012
        "    telemetry.counter(f'y.{vertex}').inc()\n"          # KJ012
        "    gauge(name='z.' + label).add(1.0)\n"               # KJ012
        "    registry().counter(name).inc()\n"                  # KJ012
        "    counter('executor.node_forces').inc()\n"           # ok
        "    gauge(name='executor.live_bytes').add(1.0)\n"      # ok
        "    np.histogram(data, bins=vertex)\n"                 # ok: numpy
        "    return jnp.histogram(data, bins=vertex)\n"         # ok: jnp
    )
    findings = jl.lint_file(bad)
    assert [f.rule for f in findings] == ["KJ012"] * 9
    assert sorted(f.line for f in findings) == list(range(10, 19))

    # outside workflow/ and nodes/ (e.g. telemetry/'s own sanctioned
    # per-process dispatch accounting) the rule does not apply
    elsewhere = tmp_path / "telemetry" / "ok_metrics.py"
    elsewhere.parent.mkdir(parents=True)
    elsewhere.write_text(bad.read_text())
    assert jl.lint_file(elsewhere) == []


def test_kj012_suppression(tmp_path):
    """A genuinely bounded in-scope dimension suppresses per line."""
    jl = _jaxlint()
    src = tmp_path / "nodes" / "suppressed_metrics.py"
    src.parent.mkdir(parents=True)
    src.write_text(
        "from keystone_tpu.telemetry import counter\n"
        "\n"
        "\n"
        "def record(dim):\n"
        "    # bounded: dim is jax.process_index(), one per host\n"
        "    counter(f'dispatch.per.{dim}').inc()"
        "  # keystone: ignore[KJ012]\n"
    )
    assert jl.lint_file(src) == []


def test_kj013_flags_transpose_then_reshape_in_fused_bodies(tmp_path):
    """KJ013: transpose-then-reshape chains inside `fuse()` /
    `_chunk_loop` / `_build_program` bodies — the permuted buffer must
    materialize before the reshape, a full write+read the roofline's
    boundary-bytes model cannot see. All the spellings flag: `.T`
    method chains, `jnp.transpose(...)` fed to `.reshape`,
    `jnp.reshape(<transposed>, ...)`, and `.swapaxes` chains."""
    jl = _jaxlint()
    bad = tmp_path / "nodes" / "bad_layout.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import jax.numpy as jnp\n"
        "\n"
        "\n"
        "class T:\n"
        "    def fuse(self):\n"
        "        def fn(p, x):\n"
        "            a = x.T.reshape(-1, 4)\n"                    # KJ013
        "            b = jnp.transpose(x, (1, 0)).reshape(8,)\n"  # KJ013
        "            c = jnp.reshape(x.swapaxes(0, 1), (-1,))\n"  # KJ013
        "            return a, b, c\n"
        "        return ((\"T\",), (), fn)\n"
        "\n"
        "    def _chunk_loop(self, fn, params, xs, ms):\n"
        "        return fn(params, xs.mT.reshape(-1, 2), ms)\n"   # KJ013
        "\n"
        "    def _build_program(self, mesh):\n"
        "        def chunk_fn(xs):\n"
        "            return jnp.moveaxis(xs, 0, 1).reshape(4, -1)\n"  # KJ013
        "        return chunk_fn\n"
    )
    findings = jl.lint_file(bad)
    assert [f.rule for f in findings] == ["KJ013"] * 5, findings

    # reshape alone, transpose alone, and transpose AFTER reshape pass
    ok = tmp_path / "workflow" / "ok_layout.py"
    ok.parent.mkdir(parents=True)
    ok.write_text(
        "import jax.numpy as jnp\n"
        "\n"
        "\n"
        "class T:\n"
        "    def fuse(self):\n"
        "        def fn(p, x):\n"
        "            a = x.reshape(-1, 4)\n"
        "            b = a.T\n"
        "            c = jnp.reshape(x, (8,)).swapaxes(0, 0)\n"
        "            return a, b, c\n"
        "        return ((\"T\",), (), fn)\n"
        "\n"
        "    def apply(self, x):\n"
        "        # outside fused bodies the chain is host-side prep,\n"
        "        # not program traffic\n"
        "        return x.T.reshape(-1)\n"
    )
    assert jl.lint_file(ok) == []

    # outside nodes/ and workflow/, KJ013 does not apply
    elsewhere = tmp_path / "loaders" / "ok_layout.py"
    elsewhere.parent.mkdir(parents=True)
    elsewhere.write_text(bad.read_text())
    assert jl.lint_file(elsewhere) == []


def test_kj013_suppression(tmp_path):
    """A genuine layout contract (a kernel-required NHWC flip) carries
    the standard suppression with a rationale."""
    jl = _jaxlint()
    src = tmp_path / "workflow" / "suppressed_layout.py"
    src.parent.mkdir(parents=True)
    src.write_text(
        "import jax.numpy as jnp\n"
        "\n"
        "\n"
        "class T:\n"
        "    def fuse(self):\n"
        "        def fn(p, x):\n"
        "            # the conv kernel demands HWIO: the flip IS the\n"
        "            # stage's contract\n"
        "            return x.T.reshape(-1, 4)"
        "  # keystone: ignore[KJ013]\n"
        "        return ((\"T\",), (), fn)\n"
    )
    assert jl.lint_file(src) == []


def test_kj014_flags_blocking_host_io_in_hot_methods(tmp_path):
    """KJ014: `time.sleep`, file reads, and network calls inside an
    operator hot-path method stall every request for the full host-call
    latency — invisibly to the KP903 serving latency bound. All the
    spellings flag: `time.sleep`/bare `sleep`, `open(...)`,
    `Path.read_text/read_bytes`, `urllib.request.urlopen`,
    `requests.get`, `socket.create_connection`."""
    jl = _jaxlint()
    bad = tmp_path / "nodes" / "bad_io.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import time\n"
        "import socket\n"
        "import urllib.request\n"
        "import requests\n"
        "from pathlib import Path\n"
        "from time import sleep\n"
        "\n"
        "\n"
        "class T:\n"
        "    def apply(self, x):\n"
        "        time.sleep(0.1)\n"                               # KJ014
        "        sleep(0.1)\n"                                    # KJ014
        "        vocab = open('vocab.txt')\n"                     # KJ014
        "        return x, vocab\n"
        "\n"
        "    def apply_batch(self, data):\n"
        "        w = Path('weights.bin').read_bytes()\n"          # KJ014
        "        r = urllib.request.urlopen('http://e/x')\n"      # KJ014
        "        return data, w, r\n"
        "\n"
        "    def _chunk_loop(self, fn, params, xs, ms):\n"
        "        requests.get('http://e/feature-store')\n"        # KJ014
        "        socket.create_connection(('e', 80))\n"           # KJ014
        "        return fn(params, xs, ms)\n"
    )
    findings = jl.lint_file(bad)
    assert [f.rule for f in findings] == ["KJ014"] * 7, findings
    assert sorted(f.line for f in findings) == [11, 12, 13, 17, 18, 22, 23]

    # the same calls at construction/fit time — and sleeps outside any
    # operator class — are exactly where the rule says to hoist them
    ok = tmp_path / "nodes" / "ok_io.py"
    ok.write_text(
        "import time\n"
        "\n"
        "\n"
        "def backoff_helper():\n"
        "    time.sleep(0.1)\n"
        "\n"
        "\n"
        "class T:\n"
        "    def __init__(self, path):\n"
        "        self.vocab = open(path).read()\n"
        "\n"
        "    def fit(self, data):\n"
        "        import urllib.request\n"
        "        self.w = urllib.request.urlopen('http://e/w')\n"
        "        return self\n"
        "\n"
        "    def apply(self, x):\n"
        "        self.clock.sleep\n"
        "        return x\n"
    )
    assert jl.lint_file(ok) == []

    # outside workflow/ and nodes/ (loaders do blocking I/O by design)
    elsewhere = tmp_path / "loaders" / "reader.py"
    elsewhere.parent.mkdir(parents=True)
    elsewhere.write_text(bad.read_text())
    assert jl.lint_file(elsewhere) == []


def test_kj014_suppression(tmp_path):
    """A genuinely per-request external lookup suppresses per line with
    a rationale naming why it cannot be batched ahead of the request."""
    jl = _jaxlint()
    src = tmp_path / "workflow" / "suppressed_io.py"
    src.parent.mkdir(parents=True)
    src.write_text(
        "import requests\n"
        "\n"
        "\n"
        "class T:\n"
        "    def apply(self, x):\n"
        "        # per-request entitlement check: the auth decision\n"
        "        # cannot be precomputed\n"
        "        tok = requests.get('http://auth/check')"
        "  # keystone: ignore[KJ014]\n"
        "        return x, tok\n"
    )
    assert jl.lint_file(src) == []


def test_lint_sh_gate(tmp_path):
    """`scripts/lint.sh`'s jaxlint stage passes on the repo and fails on
    a seeded violation (the acceptance contract)."""
    clean = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "jaxlint.py"),
         str(REPO / "keystone_tpu")],
        capture_output=True, text=True)
    assert clean.returncode == 0, clean.stdout + clean.stderr

    bad = tmp_path / "nodes" / "bad.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import jax\nimport numpy as np\n\n\n"
        "@jax.jit\ndef f(x):\n    return np.sum(x)\n")
    seeded = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "jaxlint.py"), str(bad)],
        capture_output=True, text=True)
    assert seeded.returncode == 1
    assert "KJ002" in seeded.stdout


def test_kj015_flags_manual_chunk_knob_reads(tmp_path):
    """KJ015: a direct config `.chunk_size` read (cfg/config/
    execution_config() receivers) or a KEYSTONE_CHUNK_SIZE env read in
    hot-path modules bypasses the unified planner's chunk decision —
    the sanctioned path is `workflow.env.resolved_chunk_size()`."""
    jl = _jaxlint()
    bad = tmp_path / "workflow" / "bad_chunk.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import os\n"
        "from .env import execution_config\n"
        "\n"
        "\n"
        "def dispatch(items):\n"
        "    cfg = execution_config()\n"
        "    chunk = cfg.chunk_size\n"                           # KJ015
        "    other = execution_config().chunk_size\n"            # KJ015
        "    env = os.environ.get('KEYSTONE_CHUNK_SIZE', '256')\n"  # KJ015
        "    raw = os.environ['KEYSTONE_CHUNK_SIZE']\n"          # KJ015
        "    return chunk, other, env, raw\n"
    )
    findings = jl.lint_file(bad)
    assert [f.rule for f in findings] == ["KJ015"] * 4, findings
    assert sorted(f.line for f in findings) == [7, 8, 9, 10]


def test_kj015_negatives_and_suppression(tmp_path):
    """The sanctioned reader (`resolved_chunk_size()`), unrelated
    `.chunk_size` attributes on non-config receivers (a plan's chosen
    chunk), files outside nodes/+workflow/, the env.py definition site,
    and explicit suppressions all stay silent."""
    jl = _jaxlint()
    good = tmp_path / "workflow" / "good_chunk.py"
    good.parent.mkdir(parents=True)
    good.write_text(
        "from .env import execution_config, resolved_chunk_size\n"
        "\n"
        "\n"
        "def dispatch(items, uplan):\n"
        "    chunk = resolved_chunk_size()\n"
        "    chosen = uplan.chunk_size\n"  # a plan's decision, not the knob
        "    suppressed = execution_config().chunk_size  # keystone: ignore[KJ015]\n"
        "    return chunk, chosen, suppressed\n"
    )
    assert jl.lint_file(good) == []

    # outside nodes/+workflow/ the rule does not apply at all
    elsewhere = tmp_path / "utils" / "batching_like.py"
    elsewhere.parent.mkdir(parents=True)
    elsewhere.write_text(
        "import os\n"
        "\n"
        "\n"
        "def resolve(cfg):\n"
        "    return cfg.chunk_size, os.environ.get('KEYSTONE_CHUNK_SIZE')\n"
    )
    assert jl.lint_file(elsewhere) == []

    # the config definition + resolution site is sanctioned by path
    env_site = tmp_path / "workflow" / "env.py"
    env_site.write_text(
        "import os\n"
        "\n"
        "\n"
        "def execution_config_like():\n"
        "    return int(os.environ.get('KEYSTONE_CHUNK_SIZE', '256'))\n"
    )
    assert jl.lint_file(env_site) == []


def test_kj016_flags_pallas_call_outside_ops(tmp_path):
    """KJ016: a `pl.pallas_call` (or bare `pallas_call`) invocation in
    any module outside ops/ dodges the chain-kernel audit, the
    interpret oracles, the live canary, and the kill switch — flagged
    wherever it is minted; comments/docstrings naming the API do not
    trip it."""
    jl = _jaxlint()
    bad = tmp_path / "workflow" / "rogue_kernel.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import jax.experimental.pallas as pl\n"
        "from jax.experimental.pallas import pallas_call\n"
        "\n"
        "\n"
        "def body(x_ref, o_ref):\n"
        "    o_ref[...] = x_ref[...]\n"
        "\n"
        "\n"
        "def launch(x):\n"
        "    # pl.pallas_call in a comment stays silent\n"
        "    a = pl.pallas_call(body, out_shape=x)(x)\n"          # KJ016
        "    b = pallas_call(body, out_shape=x)(x)\n"             # KJ016
        "    return a, b\n"
    )
    findings = jl.lint_file(bad)
    assert [f.rule for f in findings] == ["KJ016"] * 2, findings
    assert sorted(f.line for f in findings) == [11, 12]


def test_kj016_negatives_and_suppression(tmp_path):
    """Kernels under ops/ are the sanctioned home; a suppressed call
    elsewhere (with its rationale) stays silent."""
    jl = _jaxlint()
    home = tmp_path / "ops" / "my_kernels.py"
    home.parent.mkdir(parents=True)
    home.write_text(
        "import jax.experimental.pallas as pl\n"
        "\n"
        "\n"
        "def build(body, shape):\n"
        "    return pl.pallas_call(body, out_shape=shape)\n"
    )
    assert jl.lint_file(home) == []

    elsewhere = tmp_path / "nodes" / "suppressed.py"
    elsewhere.parent.mkdir(parents=True)
    elsewhere.write_text(
        "import jax.experimental.pallas as pl\n"
        "\n"
        "\n"
        "def launch(body, x):\n"
        "    return pl.pallas_call(body, out_shape=x)(x)  # keystone: ignore[KJ016]\n"
    )
    assert jl.lint_file(elsewhere) == []


def test_kj017_flags_hardcoded_geometry_in_ops(tmp_path):
    """KJ017: inside ops/, a hard-coded VMEM byte budget (MiB shift or
    >=1 MiB constant) outside `_VMEM_BUDGET`, and a literal leading
    block-row count in a `pl.BlockSpec` shape, each reintroduce a
    second geometry arithmetic the KP1003 static proof cannot see."""
    jl = _jaxlint()
    bad = tmp_path / "ops" / "rogue_geometry.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import jax.experimental.pallas as pl\n"
        "from jax.experimental.pallas import tpu as pltpu\n"
        "\n"
        "_VMEM_BUDGET = 10 * (1 << 20)\n"      # sanctioned definition
        "\n"
        "\n"
        "def choose(per_row):\n"
        "    cap = 4 << 20\n"                   # KJ017: inline MiB shift
        "    if per_row > 2097152:\n"           # KJ017: >=1 MiB constant
        "        return 0\n"
        "    return cap // per_row\n"
        "\n"
        "\n"
        "def launch(body, h, w, k, x):\n"
        "    return pl.pallas_call(\n"
        "        body,\n"
        "        grid=(4,),\n"
        "        in_specs=[pl.BlockSpec((8, h, w, k),\n"   # KJ017: pinned block
        "                               lambda i: (i, 0, 0, 0),\n"
        "                               memory_space=pltpu.VMEM)],\n"
        "        out_shape=x,\n"
        "    )(x)\n"
    )
    findings = jl.lint_file(bad)
    assert [f.rule for f in findings] == ["KJ017"] * 3, findings
    assert sorted(f.line for f in findings) == [8, 9, 18]


def test_kj017_negatives_and_suppression(tmp_path):
    """The `_VMEM_BUDGET` definition is the sanctioned site; chooser-fed
    block variables and broadcast-dim literals of 1 stay silent; outside
    ops/ the rule does not run; a suppressed site (with its rationale)
    stays silent."""
    jl = _jaxlint()
    clean = tmp_path / "ops" / "clean_geometry.py"
    clean.parent.mkdir(parents=True)
    clean.write_text(
        "import jax.experimental.pallas as pl\n"
        "from jax.experimental.pallas import tpu as pltpu\n"
        "\n"
        "_VMEM_BUDGET = 10 * (1 << 20)\n"
        "\n"
        "\n"
        "def launch(body, bn, k, x):\n"
        "    return pl.pallas_call(\n"
        "        body,\n"
        "        grid=(4,),\n"
        "        in_specs=[pl.BlockSpec((bn, k), lambda i: (i, 0),\n"
        "                               memory_space=pltpu.VMEM),\n"
        "                  pl.BlockSpec((1, k), lambda i: (0, 0),\n"
        "                               memory_space=pltpu.VMEM)],\n"
        "        out_shape=x,\n"
        "    )(x)\n"
    )
    assert jl.lint_file(clean) == []

    # outside ops/, the rule does not apply (KJ016 owns that half)
    elsewhere = tmp_path / "analysis" / "budget_notes.py"
    elsewhere.parent.mkdir(parents=True)
    elsewhere.write_text("CAP = 12 * (1 << 20)\n")
    assert jl.lint_file(elsewhere) == []

    suppressed = tmp_path / "ops" / "legacy_chooser.py"
    suppressed.write_text(
        "def choose(per_img):\n"
        "    # conv-era kernel: input-only working set, own canary\n"
        "    return (3 << 20) // per_img  # keystone: ignore[KJ017]\n"
    )
    assert jl.lint_file(suppressed) == []


def test_kj018_flags_trace_time_telemetry(tmp_path):
    """KJ018: span/metric emissions lexically inside fused-program
    bodies (fuse()/_chunk_loop wholesale; _build_program only in its
    nested traced closures) record trace-time, not run-time."""
    jl = _jaxlint()
    bad = tmp_path / "nodes" / "util" / "bad_fused_telemetry.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "from keystone_tpu.telemetry import counter, span\n"
        "from keystone_tpu.telemetry import counter as _counter\n"
        "from keystone_tpu import telemetry\n"
        "import jax.numpy as jnp\n"
        "\n"
        "\n"
        "def fuse(ops, x):\n"
        "    with span('fused', 'node'):\n"            # KJ018 (line 8)
        "        counter('fused.calls').inc()\n"       # KJ018 (line 9)
        "        return jnp.dot(x, x)\n"
        "\n"
        "\n"
        "def _chunk_loop(chunks):\n"
        "    telemetry.span('chunk', 'chunk')\n"       # KJ018 (line 14)
        "    _counter('chunk.trips').inc()\n"          # KJ018 (line 15)
        "    return chunks\n"
        "\n"
        "\n"
        "def _build_program(stages):\n"
        "    counter('precision.casts_baked').inc()\n"  # ok: host prologue
        "\n"
        "    def chunk_fn(carry, x):\n"
        "        span('trip', 'chunk')\n"              # KJ018 (line 23)
        "        return carry, x\n"
        "\n"
        "    return chunk_fn\n"
    )
    findings = jl.lint_file(bad)
    assert [f.rule for f in findings] == ["KJ018"] * 5
    assert sorted(f.line for f in findings) == [8, 9, 14, 15, 23]

    # outside workflow/ and nodes/ the rule does not apply
    elsewhere = tmp_path / "telemetry" / "ok_fused.py"
    elsewhere.parent.mkdir(parents=True)
    elsewhere.write_text(bad.read_text())
    assert jl.lint_file(elsewhere) == []


def test_kj018_negative_forms(tmp_path):
    """Emissions OUTSIDE fused bodies — and non-telemetry calls that
    share a name inside them — stay silent."""
    jl = _jaxlint()
    clean = tmp_path / "workflow" / "ok_fused_telemetry.py"
    clean.parent.mkdir(parents=True)
    clean.write_text(
        "from keystone_tpu.telemetry import counter, span\n"
        "import numpy as np\n"
        "\n"
        "\n"
        "def execute(graph):\n"
        "    with span('node_force', 'node'):\n"   # ok: not a fused body
        "        counter('executor.node_forces').inc()\n"
        "    return graph\n"
        "\n"
        "\n"
        "def fuse(ops, data, tracker):\n"
        "    np.histogram(data, bins=4)\n"         # ok: numpy, not metrics
        "    tracker.span_of_control()\n"          # ok: attr isn't span\n"
        "    return ops\n"
    )
    assert jl.lint_file(clean) == []


def test_kj018_suppression(tmp_path):
    """A genuinely host-side call inside a fused body suppresses per
    line with the standard comment."""
    jl = _jaxlint()
    src = tmp_path / "nodes" / "suppressed_fused.py"
    src.parent.mkdir(parents=True)
    src.write_text(
        "from keystone_tpu.telemetry import counter\n"
        "\n"
        "\n"
        "def fuse(ops):\n"
        "    # host-side: fuse() here builds, it is not traced\n"
        "    counter('fusion.rewrites').inc()"
        "  # keystone: ignore[KJ018]\n"
        "    return ops\n"
    )
    assert jl.lint_file(src) == []


def test_kj019_flags_unbounded_request_buffers(tmp_path):
    """KJ019: unbounded queue.Queue constructions in serving/ and
    workflow/, plus SimpleQueue and request-buffer list-appends under
    serving/ only — every serving queue must be able to shed."""
    jl = _jaxlint()
    bad = tmp_path / "serving" / "bad_buffers.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import queue\n"
        "from queue import Queue, SimpleQueue\n"
        "\n"
        "\n"
        "class Loop:\n"
        "    def __init__(self):\n"
        "        self._ingress = queue.Queue()\n"        # KJ019 (line 7)
        "        self._lifo = queue.LifoQueue(0)\n"      # KJ019 (line 8)
        "        self._bare = Queue(maxsize=0)\n"        # KJ019 (line 9)
        "        self._simple = SimpleQueue()\n"         # KJ019 (line 10)
        "        self._requests = []\n"
        "\n"
        "    def submit(self, row):\n"
        "        self._requests.append(row)\n"           # KJ019 (line 14)
    )
    findings = jl.lint_file(bad)
    assert [f.rule for f in findings] == ["KJ019"] * 5
    assert sorted(f.line for f in findings) == [7, 8, 9, 10, 14]

    # under workflow/ only the unbounded Queue forms apply — the
    # list-append and SimpleQueue halves are serving-only vocabulary
    wf = tmp_path / "workflow" / "bad_buffers.py"
    wf.parent.mkdir(parents=True)
    wf.write_text(bad.read_text())
    assert sorted(f.line for f in jl.lint_file(wf)) == [7, 8, 9]

    # outside serving/ and workflow/ the rule does not apply at all
    elsewhere = tmp_path / "telemetry" / "bad_buffers.py"
    elsewhere.parent.mkdir(parents=True)
    elsewhere.write_text(bad.read_text())
    assert jl.lint_file(elsewhere) == []


def test_kj019_negative_forms(tmp_path):
    """Bounded queues, non-literal capacities (a decision was made),
    and appends onto non-buffer names stay silent."""
    jl = _jaxlint()
    clean = tmp_path / "serving" / "ok_buffers.py"
    clean.parent.mkdir(parents=True)
    clean.write_text(
        "import queue\n"
        "from keystone_tpu.workflow.env import execution_config\n"
        "\n"
        "\n"
        "class Loop:\n"
        "    def __init__(self, depth):\n"
        "        self._a = queue.Queue(maxsize=depth)\n"
        "        self._b = queue.Queue(\n"
        "            execution_config().serving_queue_depth)\n"
        "        self._c = queue.Queue(256)\n"
        "        self.batch = []\n"
        "\n"
        "    def dispatch(self, item, out):\n"
        "        self.batch.append(item)\n"  # 'batch' is not a buffer name
        "        out.append(item)\n"
    )
    assert jl.lint_file(clean) == []


def test_kj019_suppression(tmp_path):
    """A statically bounded producer suppresses per line with the
    standard comment."""
    jl = _jaxlint()
    src = tmp_path / "serving" / "suppressed_buffers.py"
    src.parent.mkdir(parents=True)
    src.write_text(
        "import queue\n"
        "\n"
        "\n"
        "def make():\n"
        "    # producer is the single warm thread: statically bounded\n"
        "    return queue.Queue()"
        "  # keystone: ignore[KJ019]\n"
    )
    assert jl.lint_file(src) == []


def test_kj020_flags_whole_dataset_drains(tmp_path):
    """KJ020: numpy whole-array drains and list()/tuple() over names
    bound from the out-of-core constructors are flagged under data/ and
    workflow/; the sanctioned .materialize()/.numpy() methods and
    untracked names are not."""
    jl = _jaxlint()
    bad = tmp_path / "data" / "bad_drain.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import numpy as np\n"
        "from keystone_tpu.data.dataset import OutOfCoreDataset\n"
        "from keystone_tpu.loaders import synthetic_out_of_core\n"
        "\n"
        "\n"
        "def build(loaders, counts, other):\n"
        "    src = OutOfCoreDataset(loaders, counts)\n"
        "    big = synthetic_out_of_core(1 << 20, 128)\n"
        "    a = np.asarray(src)\n"                    # KJ020
        "    b = np.concatenate(big)\n"                # KJ020
        "    c = list(src)\n"                          # KJ020
        "    d = src.materialize()\n"                  # sanctioned
        "    e = big.numpy()\n"                        # sanctioned
        "    f = np.asarray(other)\n"                  # untracked: ok
        "    return a, b, c, d, e, f\n"
    )
    findings = jl.lint_file(bad)
    assert [f.rule for f in findings] == ["KJ020"] * 3, findings
    assert sorted(f.line for f in findings) == [9, 10, 11]

    # outside data/ and workflow/, the rule does not apply
    elsewhere = tmp_path / "loaders" / "ok_drain.py"
    elsewhere.parent.mkdir(parents=True)
    elsewhere.write_text(bad.read_text())
    assert "KJ020" not in {f.rule for f in jl.lint_file(elsewhere)}


def test_kj020_suppression(tmp_path):
    """An explicitly-unconstrained full drain suppresses per line with
    the standard comment."""
    jl = _jaxlint()
    src = tmp_path / "workflow" / "sanctioned_drain.py"
    src.parent.mkdir(parents=True)
    src.write_text(
        "import numpy as np\n"
        "from keystone_tpu.data.dataset import SpilledDataset\n"
        "\n"
        "\n"
        "def reference_arm(host, count):\n"
        "    spilled = SpilledDataset(host, count)\n"
        "    # the bench's unconstrained reference arm drains whole\n"
        "    return np.asarray(spilled)"
        "  # keystone: ignore[KJ020]\n"
    )
    assert jl.lint_file(src) == []
