"""Dry-run tests of the pod launcher's argument assembly (VERDICT r3 #8;
reference analog: bin/keystone-ec2.sh + EC2.md — provision, distribute,
run with per-host flags)."""

import subprocess
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "bin", "launch-pod.sh")


def _run(*args):
    # env-var dry-run: the flag form would land in APP_ARGS after "--"
    env = dict(os.environ, KEYSTONE_POD_DRY_RUN="1")
    r = subprocess.run(
        [SCRIPT, *args],
        capture_output=True, text=True, cwd=REPO, timeout=30, env=env,
    )
    assert r.returncode == 0, r.stderr
    # undo the launcher's %q space-escaping for substring assertions
    return [l.replace("\\ ", " ")
            for l in r.stdout.splitlines() if l.startswith("DRYRUN:")]


def test_launch_assembles_tpu_vm_create():
    (line,) = _run("launch", "kp-test", "--zone", "us-west4-a",
                   "--project", "proj", "--accelerator", "v5litepod-16")
    assert "gcloud compute tpus tpu-vm create kp-test" in line
    assert "--zone us-west4-a" in line and "--project proj" in line
    assert "--accelerator-type v5litepod-16" in line


def test_launch_queued_resource_with_spot():
    (line,) = _run("launch", "kp-test", "--zone", "z", "--queued", "--spot")
    assert "queued-resources create kp-test" in line
    assert "--node-id kp-test" in line and "--spot" in line


def test_push_distributes_repo_to_all_workers():
    (line,) = _run("push", "kp-test", "--zone", "z")
    assert "tpu-vm scp --recurse" in line
    assert "--worker=all" in line
    assert "kp-test:/tmp/keystone_tpu" in line


def test_run_emits_one_process_per_host_with_coordinator_flags():
    """v5litepod-16 = 4 hosts: process ids 0..3, all pointing at host 0's
    coordinator, each invoking run-pipeline.sh with the multihost flags
    keystone_tpu.__main__ consumes."""
    lines = _run("run", "kp-test", "--zone", "z",
                 "--accelerator", "v5litepod-16", "--",
                 "pipelines.images.cifar.RandomPatchCifar",
                 "--num-filters", "256")
    # first line resolves worker 0's internal IP (TPU VM hostnames are
    # auto-generated; "<name>-0" does not resolve inside the pod)
    assert "tpus tpu-vm describe kp-test" in lines[0]
    assert "networkEndpoints" in lines[0] and "ipAddress" in lines[0]
    lines = lines[1:]
    assert len(lines) == 4
    for i, line in enumerate(sorted(lines, key=lambda l: l.split("--worker=")[1])):
        assert f"--worker={i}" in line
        assert "--coordinator" in line
        assert "WORKER0_IP" in line and ":8476" in line
        assert "--num-processes 4" in line
        assert f"--process-id {i}" in line
        assert "run-pipeline.sh" in line
        assert "RandomPatchCifar" in line and "--num-filters 256" in line


def test_run_single_host_accelerator():
    lines = _run("run", "kp", "--zone", "z", "--accelerator", "v5litepod-4",
                 "--", "pipelines.speech.TimitPipeline")
    assert len(lines) == 2  # describe (IP resolve) + one host process
    assert "tpus tpu-vm describe kp" in lines[0]
    assert "--num-processes 1" in lines[1] and "--process-id 0" in lines[1]


def test_delete():
    (line,) = _run("delete", "kp-test", "--zone", "z")
    assert "tpu-vm delete kp-test" in line and "--quiet" in line
