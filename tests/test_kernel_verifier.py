"""KP10xx static chain-kernel verifier — `analysis/kernels.py`.

Marked ``lint``: data-free, device-free (`eval_shape` traces only),
mirroring `scripts/lint.sh`'s --audit-kernels stage so CI and pytest
cannot drift.

The acceptance contract:

  - both registered lowering families verify clean on every rule at
    their flagship geometries (the --audit-kernels 6/6 gate);
  - every seeded mutation — an off-by-one grid, a constant index map,
    an out-of-range grid, a floor-instead-of-ceil pad recipe, an
    inflated VMEM block, a dropped/shifted mask stream, a corrupted
    boundary aval — is caught by the rule that owns it;
  - the static KP1003 verdict agrees with `chain_feasible`'s runtime
    chooser on every geometry in the matrix, under the default AND
    floored VMEM budgets (the shared-formula identity);
  - the unified planner prices statically refuted kernel entries INF
    (`kernel_choices` stays empty) and annotates verified candidates;
  - the --audit-kernels CLI emits the CI-annotation JSON schema with
    zero unsuppressed findings.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from keystone_tpu.analysis import Severity, as_source_spec, validate_graph
from keystone_tpu.analysis.examples import build_example
from keystone_tpu.analysis.kernels import (
    audit_kernels,
    batcher_pad_targets,
    check_grid_coverage,
    check_mask_discipline,
    check_oracle_boundaries,
    check_ragged_bounds,
    check_read_bounds,
    check_vmem_budget,
    statically_verified,
    verify_lowering,
)
from keystone_tpu.analysis.propagate import spec_pass
from keystone_tpu.nodes.images.core import (
    GrayScaler,
    ImageVectorizer,
    PixelScaler,
)
from keystone_tpu.nodes.stats.scalers import StandardScalerModel
from keystone_tpu.nodes.util.fusion import _RectifyPoolStage
from keystone_tpu.ops import chain_kernels as ck

pytestmark = pytest.mark.lint

REPO = Path(__file__).resolve().parent.parent


def _elem_stages():
    """The LinearPixels flagship elementwise-chain trail."""
    return [PixelScaler(), GrayScaler(), ImageVectorizer()]


def _rect_stages(pool=14, stride=13):
    """The RandomPatchCifar rectify→pool→vectorize trail."""
    return [_RectifyPoolStage(0.25, 0.0, pool, stride), ImageVectorizer()]


def _masked_stages():
    """An elementwise trail with a `fuse_masks_output` stage (the
    StandardScalerModel padded-row re-zeroing contract)."""
    return [PixelScaler(), ImageVectorizer(),
            StandardScalerModel(np.zeros(192, np.float32),
                                np.ones(192, np.float32))]


# ------------------------------------------------------ clean lowerings


def test_elementwise_lowering_verifies_clean():
    proof, diags = verify_lowering(_elem_stages(), (32, 32, 3))
    assert proof["family"] == "elementwise_chain"
    assert proof["verified"] and proof["refuted_by"] is None
    assert diags == []
    for rule in ("KP1001", "KP1002", "KP1003", "KP1004", "KP1005"):
        assert proof["rules"][rule].startswith("proved"), (
            rule, proof["rules"][rule])


def test_rectify_lowering_verifies_clean():
    proof, diags = verify_lowering(_rect_stages(), (27, 27, 256))
    assert proof["family"] == "rectify_pool_vectorize"
    assert proof["verified"] and proof["refuted_by"] is None
    assert diags == []
    for rule in ("KP1001", "KP1002", "KP1003", "KP1004", "KP1005"):
        assert proof["rules"][rule].startswith("proved"), rule


def test_masked_trail_proves_mask_discipline():
    proof, diags = verify_lowering(_masked_stages(), (8, 8, 3))
    assert proof["verified"] and diags == []
    assert "position(s) [2]" in proof["rules"]["KP1004"]


def test_unlowerable_trail_returns_no_verdict():
    """A chain no family expresses gets no proof and no diagnostics —
    the pre-kernel XLA path needs no kernel safety story."""
    from keystone_tpu.nodes.stats import PaddedFFT

    proof, diags = verify_lowering([PaddedFFT()], (48,))
    assert not proof["verified"] and diags == []
    assert statically_verified([PaddedFFT()], (48,)) is None


# --------------------------------------- KP1001 seeded grid mutations


def test_kp1001_off_by_one_grid_is_a_gap():
    imap = lambda i: (i, 0)  # noqa: E731
    assert check_grid_coverage((4,), (4, 8), imap, (16, 8)) == []
    problems = check_grid_coverage((3,), (4, 8), imap, (16, 8))
    assert problems and "coverage gap" in problems[0]


def test_kp1001_constant_index_map_is_a_double_write():
    problems = check_grid_coverage((4,), (4, 8), lambda i: (0, 0),
                                   (16, 8))
    assert problems and all("double-write" in p for p in problems)


def test_kp1001_overrun_grid_writes_out_of_bounds():
    problems = check_grid_coverage((5,), (4, 8), lambda i: (i, 0),
                                   (16, 8))
    assert problems and any("outside output dim 0" in p
                            for p in problems)


# ----------------------------------- KP1002 seeded pad/read mutations


def test_kp1002_floor_pad_recipe_drops_rows():
    counts = batcher_pad_targets(256)
    assert check_ragged_bounds(4, counts) == []
    floor = lambda n, b: (n // b) * b  # noqa: E731
    problems = check_ragged_bounds(4, [6], pad=floor)
    assert problems and "drops" in problems[0]


def test_kp1002_read_past_padded_operand():
    imap = lambda i: (i, 0)  # noqa: E731
    assert check_read_bounds((3,), (4, 8), imap, (12, 8), name="x") == []
    problems = check_read_bounds((4,), (4, 8), imap, (12, 8), name="x")
    assert problems and "x: grid point (3,) reads [12, 16)" in problems[0]


# -------------------------------------- KP1003 seeded block mutations


def test_kp1003_inflated_block_busts_budget_and_chooser():
    """A block one ladder rung above the chooser's pick fails BOTH
    halves: the working set exceeds the budget and the chooser-identity
    check names the divergence."""
    ladder = (8, 4, 2, 1)
    io = 1 << 20
    assert check_vmem_budget(4, io, 0, 0, ladder) == []
    problems = check_vmem_budget(8, io, 0, 0, ladder)
    assert any("exceeds the VMEM budget" in p for p in problems)
    assert any("chooser divergence" in p for p in problems)


def test_kp1003_deflated_block_is_chooser_divergence_only():
    problems = check_vmem_budget(2, 1 << 20, 0, 0, (8, 4, 2, 1))
    assert problems == [p for p in problems if "chooser divergence" in p]
    assert problems


def test_kp1003_shared_formula_is_the_chain_formula():
    """The one working-set arithmetic, pinned: 2× double-buffered
    streamed blocks + bn× transients + params."""
    assert ck.chain_vmem_bytes(3, 10, 4, 7) == 2 * 3 * 10 + 3 * 4 + 7
    assert ck.chain_block_rows(1 << 20, ladder=(8, 4, 2, 1)) == 4
    assert ck.chain_block_rows(1 << 30, ladder=(8, 4, 2, 1)) == 0


# --------------------------------------- KP1004 seeded mask mutations


def test_kp1004_dropped_mask_stream():
    problems = check_mask_discipline([1], [], False)
    assert problems and "streams no mask operand" in problems[0]


def test_kp1004_mask_consumed_at_wrong_position():
    problems = check_mask_discipline([1], [2], True)
    assert any("stage 1 declares fuse_masks_output" in p
               for p in problems)
    assert any("position 2 where no stage declares" in p
               for p in problems)


def test_kp1004_clean_positions_stay_silent():
    assert check_mask_discipline([], [], False) == []
    assert check_mask_discipline([0, 2], [0, 2], True) == []


# ------------------------------------- KP1005 seeded oracle mutations


def _avals(*shapes, dtype=jnp.float32):
    return [jax.ShapeDtypeStruct(s, dtype) for s in shapes]


def test_kp1005_boundary_count_mismatch():
    problems = check_oracle_boundaries(
        _avals((4, 8), (4, 16)), _avals((4, 8), (4, 16), (4, 32)), 4)
    assert problems and "boundary count mismatch" in problems[0]


def test_kp1005_dtype_and_tail_mismatch():
    kern = _avals((4, 8), (4, 16))
    oracle = _avals((4, 8)) + _avals((4, 24), dtype=jnp.bfloat16)
    problems = check_oracle_boundaries(kern, oracle, 4)
    assert any("dtype" in p for p in problems)
    assert any("tail" in p for p in problems)


def test_kp1005_batch_axis_not_preserved():
    problems = check_oracle_boundaries(
        _avals((4, 8), (1, 8)), _avals((4, 8), (1, 8)), 4)
    assert problems and "does not preserve the batch axis" in problems[0]


# ------------------------------- end-to-end refutation + the identity


def test_floored_budget_refutes_without_error(monkeypatch):
    """A VMEM-infeasible geometry the runtime chooser also refuses is a
    refutation FACT (refuted_by KP1003), not a safety ERROR — the
    planner prices it INF, nothing is broken."""
    monkeypatch.setattr(ck, "_VMEM_BUDGET", 1)
    proof, diags = verify_lowering(_elem_stages(), (32, 32, 3))
    assert proof["refuted_by"] == "KP1003"
    assert not proof["verified"]
    assert not [d for d in diags if d.severity >= Severity.ERROR]
    assert statically_verified(_elem_stages(), (32, 32, 3)) is False


@pytest.mark.parametrize("budget", [None, 1, 200_000, 3 << 20])
def test_static_verdict_agrees_with_chain_feasible(monkeypatch, budget):
    """The shared-formula identity, test-pinned: on every geometry in
    the matrix — both families, feasible and infeasible, default and
    floored budgets — `statically_verified` and `chain_feasible` reach
    the same verdict, because both sit on `chain_vmem_bytes`."""
    if budget is not None:
        monkeypatch.setattr(ck, "_VMEM_BUDGET", budget)
    matrix = [
        (_elem_stages(), (32, 32, 3)),
        (_elem_stages(), (8, 8, 3)),
        (_elem_stages(), (128, 128, 3)),
        (_elem_stages(), (2048, 2048, 3)),
        (_masked_stages(), (8, 8, 3)),
        (_rect_stages(), (27, 27, 256)),
        (_rect_stages(), (27, 27, 32)),
        (_rect_stages(), (5, 5, 8)),  # empty pool grid
    ]
    for stages, item in matrix:
        feasible, reason = ck.chain_feasible(stages, item, jnp.float32)
        verdict = statically_verified(stages, item)
        assert verdict is not None, (item, reason)
        assert verdict == bool(feasible), (item, reason, verdict)


def test_chooser_decisions_pinned():
    """Satellite regression pin: deduplicating the inline VMEM formulas
    into `chain_vmem_bytes`/`chain_block_rows` changed NO chooser
    decision."""
    assert ck._rectify_pool_vectorize_block(27, 27, 256, 14, 13) == 5
    assert ck.chain_feasible(_elem_stages(), (32, 32, 3),
                             jnp.float32) == (True, "block=4")
    assert ck.chain_feasible(_rect_stages(), (27, 27, 256),
                             jnp.float32) == (True, "block=5")


def test_batcher_pad_targets_enumerates_the_pr5_ladder():
    assert batcher_pad_targets(256) == [1, 2, 4, 8, 16, 32, 64, 128, 256]
    assert batcher_pad_targets(None) == [1] or batcher_pad_targets(None)


# --------------------------------------------- analyzer + planner wiring


def test_validate_full_runs_kernel_tier_clean():
    pipeline, source_spec = build_example("LinearPixels")
    report = validate_graph(
        pipeline.graph, {pipeline.source: as_source_spec(source_spec)},
        level="full")
    kern = [d for d in report.diagnostics
            if d.rule.startswith("KP100") and len(d.rule) == 6
            and d.severity >= Severity.WARNING]
    assert kern == [], kern


def test_audit_kernels_all_registered_lowerings_verify():
    findings, stats = audit_kernels()
    assert findings == [], findings
    assert not stats["build_errors"], stats["build_errors"]
    assert stats["lowerings"] >= 6
    assert stats["verified"] == stats["lowerings"]
    families = {p["family"] for p in stats["proofs"]}
    assert families == {"elementwise_chain", "rectify_pool_vectorize"}


def test_planner_annotates_verified_candidates():
    from keystone_tpu.analysis.plan_ir import plan_unified

    pipeline, source_spec = build_example("LinearPixels")
    specs, _ = spec_pass(
        pipeline.graph, {pipeline.source: as_source_spec(source_spec)})
    uplan = plan_unified(pipeline.graph, specs)
    assert uplan is not None and uplan.kernel_choices
    for cand in uplan.kernel_choices.values():
        assert cand["statically_verified"] is True, cand


def test_planner_prices_refuted_kernels_inf(monkeypatch):
    """A statically refuted lowering never joins the chosen plan even
    when its VMEM probe would pass — the verifier's verdict is its own
    gate, not an alias of `chain_feasible`."""
    import keystone_tpu.analysis.kernels as kmod
    from keystone_tpu.analysis.plan_ir import plan_unified

    monkeypatch.setattr(kmod, "statically_verified",
                        lambda *a, **k: False)
    pipeline, source_spec = build_example("LinearPixels")
    specs, _ = spec_pass(
        pipeline.graph, {pipeline.source: as_source_spec(source_spec)})
    uplan = plan_unified(pipeline.graph, specs)
    assert uplan is not None
    assert uplan.kernel_choices == {}, uplan.kernel_choices
    assert uplan.joint_seconds <= uplan.sequential_seconds


def test_kernel_pass_annotates_candidates_in_place():
    from keystone_tpu.analysis.kernels import kernel_pass
    from keystone_tpu.analysis.roofline import roofline_pass

    pipeline, source_spec = build_example("LinearPixels")
    specs, _ = spec_pass(
        pipeline.graph, {pipeline.source: as_source_spec(source_spec)})
    est, _ = roofline_pass(pipeline.graph, specs)
    proofs, diags = kernel_pass(pipeline.graph, specs, est)
    assert proofs and all(p["verified"] for p in proofs)
    lowerable = [c for c in est.candidates
                 if (c.get("lowerable") or {}).get("lowerable")]
    assert lowerable
    assert all(c.get("statically_verified") is True for c in lowerable)


# ------------------------------------------------------------------ CLI


def test_audit_kernels_cli_json_schema():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "keystone_tpu.analysis",
         "--audit-kernels", "--json"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["findings"] == []
    assert not payload["build_errors"]
    assert payload["audited_examples"] >= 7
    assert payload["total_lowerings"] >= 6
    assert payload["verified_lowerings"] == payload["total_lowerings"]
    for p in payload["proofs"]:
        assert p["verified"] is True, p
        assert set(p["rules"]) >= {"KP1001", "KP1002", "KP1003",
                                   "KP1004", "KP1005"}, p
