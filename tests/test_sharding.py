"""Static sharding analyzer tests (keystone_tpu/analysis/sharding.py).

The acceptance contract: partition specs propagate over the lowered
graph exactly as `Dataset` placement assigns them at force time (checked
against live arrays on the 8-device CPU mesh), the per-device memory
model divides the fleet estimate by real shard counts (reconciled
against observed per-shard bytes through a trace), and each KP6xx rule
fires on a seeded bug, stays quiet on the clean form, and suppresses
through the standard ``ignore=[...]`` channel."""

import json
import warnings

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from keystone_tpu.analysis import (
    PartitionRule,
    SpecDataset,
    validate_graph,
)
from keystone_tpu.analysis.examples import EXAMPLES, build_example
from keystone_tpu.analysis.memory import memory_pass
from keystone_tpu.analysis.propagate import spec_pass
from keystone_tpu.analysis.sharding import (
    DEMAND_DATA_SHARDED,
    ShardingResult,
    explain_rows,
    format_explain,
    per_device_pass,
    sharding_pass,
    spec_str,
)
from keystone_tpu.data.dataset import Dataset, leaf_sharding
from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
from keystone_tpu.nodes.stats import LinearRectifier, RandomSignNode
from keystone_tpu.parallel import mesh as meshlib
from keystone_tpu.workflow import Pipeline, Transformer


class _HostStage(Transformer):
    """Provably-host stage: the abstract trace dies on the numpy pull."""

    def apply(self, x):
        return np.asarray(x).sum()


def _chain_pipeline(dim=16, count=64):
    pipe = RandomSignNode(dim).to_pipeline() >> LinearRectifier(0.0)
    return pipe.apply(
        SpecDataset((dim,), np.float32, count=count, name="x"))


def _full(graph, **kwargs):
    return validate_graph(graph, level="full", **kwargs)


# ------------------------------------------------------------ propagation


def test_propagation_matches_runtime_placement():
    """The seeded spec at a Dataset vertex equals what placement actually
    assigned the live array — the analyzer and the runtime share
    `leaf_sharding`'s decision."""
    ds = Dataset.from_numpy(np.ones((64, 16), np.float32))
    applied = Transformer.from_function(lambda x: x * 2.0).to_pipeline()(ds)
    report = applied.validate(raise_on_error=False)
    assert report.shardings, "full-level validate must propagate shardings"
    placed_spec = meshlib.spec_of_array(ds.data)
    assert placed_spec is not None
    seeded = [
        sv for vid, sv in report.shardings.items()
        if sv is not None and getattr(vid, "id", None) is not None
    ]
    assert seeded
    # every device stage keeps the leading-axis data sharding
    for sv in seeded:
        leaf = sv.leaf_specs()[0]
        assert meshlib.spec_axes(leaf)[:1] == (meshlib.DATA_AXIS,), sv
    assert meshlib.spec_axes(placed_spec)[:1] == (meshlib.DATA_AXIS,)


def test_data_sharding_survives_elementwise_chain():
    applied = _chain_pipeline()
    report = _full(applied.graph)
    node_svs = {vid: sv for vid, sv in report.shardings.items()
                if sv is not None}
    assert len(node_svs) >= 3  # dataset + two stages (+ sink)
    for sv in node_svs.values():
        assert spec_str(sv).startswith("P('data'")
    assert not [d for d in report.diagnostics if d.rule.startswith("KP6")]


def test_sharding_only_runs_at_full_level():
    applied = _chain_pipeline()
    assert not validate_graph(applied.graph, level="memory").shardings
    assert validate_graph(applied.graph, level="full").shardings


# ------------------------------------------------------- KP601 (reshard)


def test_kp601_partition_rule_override_fires_and_suppresses():
    applied = _chain_pipeline()
    rules = [PartitionRule("LinearRectifier", P())]
    report = _full(applied.graph, partition_rules=rules)
    kp601 = report.by_rule("KP601")
    assert kp601 and "all-to-all" in kp601[0].message
    # the pinned stage now carries the rule's spec
    flagged = kp601[0].vertex
    assert spec_str(report.shardings[flagged]) == "P()"
    # suppression channel
    assert not _full(applied.graph, partition_rules=rules,
                     ignore=["KP601"]).by_rule("KP601")
    # no rules → no reshard
    assert not _full(applied.graph).by_rule("KP601")


def test_kp601_solver_demand_fires_on_replicated_input():
    feat = RandomSignNode(8).to_pipeline()
    data = SpecDataset((8,), np.float32, count=32, name="d")
    labels = SpecDataset((4,), np.float32, count=32, name="l")
    pred = feat.and_then(BlockLeastSquaresEstimator(8, 1, 0.1), data, labels)
    # replicating the featurizer forces the BCD fit's row-sharded demand
    # to disagree with its producer
    report = validate_graph(
        pred.graph, {pred.source: (8,)}, level="full",
        partition_rules=[("RandomSignNode", P())])
    demand_hits = [d for d in report.by_rule("KP601")
                   if "demands a data-sharded layout" in d.message]
    assert demand_hits
    # data-sharded producers satisfy the demand
    clean = validate_graph(pred.graph, {pred.source: (8,)}, level="full")
    assert not clean.by_rule("KP601")


def test_solver_fit_hooks_declare_row_sharded_demands():
    from keystone_tpu.nodes.learning.kernels import KernelRidgeRegression
    from keystone_tpu.nodes.learning.lbfgs import DenseLBFGSwithL2
    from keystone_tpu.nodes.learning.pca import DistributedPCAEstimator

    for est, n in [
        (BlockLeastSquaresEstimator(8, 1), 2),
        (KernelRidgeRegression(1.0, 0.1), 2),
        (DenseLBFGSwithL2(), 2),
        (DistributedPCAEstimator(4), 1),
    ]:
        res = est.abstract_sharding([None] * n, [None] * n)
        assert isinstance(res, ShardingResult)
        assert res.demands == (DEMAND_DATA_SHARDED,) * n, type(est).__name__


# -------------------------------------------------- KP605 (invalid rule)


def test_kp605_rule_with_unknown_axis_or_excess_rank():
    applied = _chain_pipeline()
    # "expert" is not an axis of the (data,)-mesh
    bad_axis = _full(applied.graph,
                     partition_rules=[("LinearRectifier", P("expert"))])
    kp605 = bad_axis.by_rule("KP605")
    assert kp605 and kp605[0].severity.name == "ERROR"
    assert "no axis 'expert'" in kp605[0].message
    # the bad rule was ignored: propagation kept the flowed spec
    assert spec_str(bad_axis.shardings[kp605[0].vertex]) == "P('data', None)"
    # more entries than the value's (count, dim) rank
    too_long = _full(
        applied.graph,
        partition_rules=[
            ("LinearRectifier",
             P(meshlib.DATA_AXIS, None, None))])
    assert too_long.by_rule("KP605")
    # a realizable rule stays KP605-quiet
    ok = _full(applied.graph,
               partition_rules=[("LinearRectifier", P(meshlib.DATA_AXIS))])
    assert not ok.by_rule("KP605")


def test_rules_never_pin_device_specs_on_host_values():
    """Review regression: a catch-all rule must not assign a device
    placement to a host-resident value — per-device bytes would divide
    by shards that don't exist and host consumers would fabricate
    KP603 all-gathers."""
    pipe = _HostStage().to_pipeline() >> _HostStage()
    applied = pipe.apply(SpecDataset(count=64, name="h", on_device=False))
    report = _full(applied.graph,
                   partition_rules=[(".*", P(meshlib.DATA_AXIS))])
    assert all(sv is None for sv in report.shardings.values())
    assert not report.by_rule("KP603")


def test_kp605_rejects_unrealizable_hook_placement():
    """Review regression: a hook-returned ShardedValue gets the same
    KP605 realizability contract as rule specs — an unknown axis must
    fail loudly, not silently model shard-count 1."""
    from keystone_tpu.analysis.sharding import ShardedValue

    class _BadHookStage(Transformer):
        def apply(self, x):
            return x * 2.0

        def abstract_sharding(self, in_shardings, in_specs):
            return ShardedValue(P("expert"))

    applied = (_BadHookStage().to_pipeline()).apply(
        SpecDataset((16,), np.float32, count=64, name="x"))
    report = _full(applied.graph)
    kp605 = report.by_rule("KP605")
    assert kp605 and "no axis 'expert'" in kp605[0].message
    # the bad placement was discarded: the default rule decided instead
    assert spec_str(report.shardings[kp605[0].vertex]).startswith("P('data'")


def test_kp605_raising_hook_is_loud_not_silent():
    """Review regression: a hook that raises must be distinguishable
    from 'no hook declared' — otherwise a broken solver hook silently
    drops its KP601 demand checks while the gate stays green."""

    class _RaisingHookStage(Transformer):
        def apply(self, x):
            return x * 2.0

        def abstract_sharding(self, in_shardings, in_specs):
            raise TypeError("refactor broke me")

    applied = (_RaisingHookStage().to_pipeline()).apply(
        SpecDataset((16,), np.float32, count=64, name="x"))
    report = _full(applied.graph)
    kp605 = report.by_rule("KP605")
    assert kp605 and "refactor broke me" in kp605[0].message
    assert kp605[0].severity.name == "WARNING"
    # default propagation still decided the stage's placement
    assert spec_str(report.shardings[kp605[0].vertex]).startswith("P('data'")


def test_per_device_bytes_models_padded_shards_at_ragged_counts():
    """At mesh-indivisible counts the runtime pads before splitting, so
    one shard holds ceil(count/shards) rows — the static per-device
    number must match the padded shard, not total/shards."""
    from keystone_tpu.analysis.sharding import per_device_bytes, seed_sharding
    from keystone_tpu.analysis.specs import DataSpec, shape_struct

    mesh = meshlib.current_mesh()
    spec = DataSpec(element=shape_struct((1024,), np.float32), count=12)
    sv = seed_sharding(spec, mesh)
    static = per_device_bytes(spec, sv, mesh)
    ds = Dataset.from_numpy(np.ones((12, 1024), np.float32))
    observed = ds.data.addressable_shards[0].data.nbytes
    assert static == observed == 2 * 4096  # ceil(12/8)=2 padded rows


# --------------------------------------------------- KP602 (replication)


def test_kp602_large_replicated_operand_on_model_mesh():
    mesh = meshlib.make_mesh(
        shape=(2, 4), axis_names=(meshlib.DATA_AXIS, meshlib.MODEL_AXIS))
    with meshlib.use_mesh(mesh):
        big = SpecDataset((4096,), np.float32, count=8192, name="big")
        applied = Transformer.from_function(
            lambda x: x, name="ident").to_pipeline()(big)
        # pin everything replicated: 128 MiB > the 64 MiB threshold and
        # the 4-way model axis divides the 4096-wide feature dim
        report = _full(applied.graph, partition_rules=[(".", P())])
        kp602 = report.by_rule("KP602")
        assert kp602 and "'model'" in kp602[0].message
        # the default (sharded) placement is quiet
        assert not _full(applied.graph).by_rule("KP602")
        # suppression channel
        assert not _full(applied.graph, partition_rules=[(".", P())],
                         ignore=["KP602"]).by_rule("KP602")


def test_kp602_quiet_below_threshold():
    mesh = meshlib.make_mesh(
        shape=(2, 4), axis_names=(meshlib.DATA_AXIS, meshlib.MODEL_AXIS))
    with meshlib.use_mesh(mesh):
        small = SpecDataset((64,), np.float32, count=128, name="small")
        applied = Transformer.from_function(
            lambda x: x, name="ident").to_pipeline()(small)
        report = _full(applied.graph, partition_rules=[(".", P())])
        assert not report.by_rule("KP602")


# ------------------------------------------------- KP603 (host all-gather)


def test_kp603_host_stage_consuming_sharded_data():
    pipe = RandomSignNode(16).to_pipeline() >> _HostStage()
    applied = pipe.apply(
        SpecDataset((16,), np.float32, count=64, name="x"))
    report = _full(applied.graph)
    kp603 = report.by_rule("KP603")
    assert kp603 and "all-gather" in kp603[0].message
    assert not _full(applied.graph, ignore=["KP603"]).by_rule("KP603")


def test_kp603_quiet_for_host_to_host():
    # a host stage consuming host data gathers nothing
    pipe = _HostStage().to_pipeline() >> _HostStage()
    applied = pipe.apply(SpecDataset(count=64, name="h", on_device=False))
    assert not _full(applied.graph).by_rule("KP603")


# ------------------------------------------- KP604 (indivisible counts)


def test_kp604_mesh_indivisible_count():
    ragged = _chain_pipeline(count=30)  # 8 shards do not divide 30
    report = _full(ragged.graph)
    kp604 = report.by_rule("KP604")
    assert kp604 and "pads to 32" in kp604[0].message
    # one diagnostic per distinct count, not one per stage
    assert len(kp604) == 1
    assert not _full(ragged.graph, ignore=["KP604"]).by_rule("KP604")
    assert not _full(_chain_pipeline(count=32).graph).by_rule("KP604")


# ----------------------------------------------- per-device memory model


def test_per_device_peak_divides_fleet_peak_by_shards():
    applied = _chain_pipeline(dim=16, count=64)
    report = _full(applied.graph)
    mem = report.memory
    assert mem.per_device_peak_bytes > 0
    shards = meshlib.n_data_shards()
    assert shards == 8
    assert mem.per_device_peak_bytes == mem.peak_bytes // shards


def test_kp600_per_device_budget_replaces_kp202():
    applied = _chain_pipeline(dim=256, count=4096)
    tight = _full(applied.graph, hbm_budget_bytes=256 << 10)
    assert tight.by_rule("KP600")
    assert not tight.by_rule("KP202")  # replaced at the full tier
    # a budget the per-device peak satisfies is quiet, even though the
    # fleet-wide sum would have tripped the whole-fleet check
    mem = tight.memory
    assert mem.per_device_peak_bytes < mem.peak_bytes
    mid = _full(applied.graph,
                hbm_budget_bytes=(mem.per_device_peak_bytes
                                  + mem.peak_bytes) // 2)
    assert not mid.by_rule("KP600") and not mid.by_rule("KP202")


def test_per_device_static_matches_observed_shard_bytes(tmp_path):
    """Reconciliation closes the per-device loop: the static per-device
    estimate embedded in the trace equals the bytes one shard of the
    forced array actually holds on the 8-device mesh."""
    from keystone_tpu.analysis.reconcile import reconcile_trace
    from keystone_tpu.telemetry import trace_run

    path = str(tmp_path / "trace.json")
    ds = Dataset.from_numpy(np.ones((64, 16), np.float32))
    with trace_run(path):
        out = Transformer.from_function(
            lambda x: x * 2.0).to_pipeline()(ds).get()
    rec = reconcile_trace(json.load(open(path)))
    rows = [r for r in rec["rows"]
            if r.get("static_per_device_bytes") and r["observed_bytes"]]
    assert rows, rec["rows"]
    leaf = jax.tree_util.tree_leaves(out.data)[0]
    observed_shard = leaf.addressable_shards[0].data.nbytes
    for r in rows:
        assert r["static_per_device_bytes"] == observed_shard, r
        assert r["spec"].startswith("P('data'"), r
    assert rec["static_per_device_peak_bytes"] and \
        rec["static_per_device_peak_bytes"] <= rec["static_peak_bytes"]


# ------------------------------------------------------- explain surface


def test_explain_rows_and_table():
    applied = _chain_pipeline()
    graph = applied.graph
    specs, _ = spec_pass(graph, {})
    shardings, diags, boundary = sharding_pass(graph, specs)
    est, _ = memory_pass(graph, specs)
    per_dev, _ = per_device_pass(graph, specs, shardings, est)
    rows = explain_rows(graph, specs, shardings, boundary, per_dev)
    assert rows and all(
        set(r) >= {"vertex", "label", "spec", "per_device_bytes",
                   "boundary_bytes"} for r in rows)
    table = format_explain(rows)
    assert "per-dev" in table and "P('data'" in table


@pytest.mark.lint
def test_explain_sharding_cli_all_examples_clean(capsys):
    from keystone_tpu.analysis.__main__ import main

    rc = main(["--explain-sharding"])
    out = capsys.readouterr().out
    assert rc == 0, out
    for name in EXAMPLES:
        assert f"✓ {name}" in out
    assert "P('data'" in out


@pytest.mark.lint
def test_explain_sharding_cli_json(capsys):
    from keystone_tpu.analysis.__main__ import main

    rc = main(["--explain-sharding", "--json", "MnistRandomFFT"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert payload["devices"] == 8
    ex = payload["examples"][0]
    assert ex["example"] == "MnistRandomFFT"
    assert ex["findings"] == []
    assert ex["stages"] and all("spec" in s for s in ex["stages"])


# --------------------------------------------------- runtime satellites


def test_reshard_short_circuits_identity():
    from keystone_tpu.parallel.collectives import reshard

    x = meshlib.shard_leading_axis(np.ones((16, 4), np.float32))
    same = reshard(x, P(meshlib.DATA_AXIS))
    assert same is x  # no program built or dispatched
    moved = reshard(x, P())
    assert moved is not x
    np.testing.assert_array_equal(np.asarray(moved), np.asarray(x))
    # and resharding the moved value back to its own layout is free again
    assert reshard(moved, P()) is moved


def test_leaf_sharding_ragged_leading_axis_falls_back_replicated():
    mesh = meshlib.make_mesh(jax.devices()[:2])
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        sh = leaf_sharding(mesh, (3, 4))  # 3 rows on a 2-device mesh
    assert any("does not divide" in str(w.message) for w in caught)
    assert sh.spec == P()
    # divisible shapes keep the data sharding, silently
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        sh2 = leaf_sharding(mesh, (4, 4))
    assert not caught
    assert meshlib.spec_axes(sh2.spec)[:1] == (meshlib.DATA_AXIS,)
    # a ragged COUNT still forces fine through Dataset (placement pads)
    with meshlib.use_mesh(mesh):
        ds = Dataset.from_numpy(
            np.arange(12, dtype=np.float32).reshape(3, 4))
        out = Transformer.from_function(lambda x: x + 1).to_pipeline()(ds)
        got = out.get().numpy()
        np.testing.assert_allclose(
            got, np.arange(12, dtype=np.float32).reshape(3, 4) + 1)


@pytest.mark.lint
def test_example_pipelines_have_zero_kp6xx(capsys):
    for name in sorted(EXAMPLES):
        pipeline, source_spec = build_example(name)
        report = pipeline.validate(source_spec, raise_on_error=False)
        kp6 = [d for d in report.diagnostics if d.rule.startswith("KP6")]
        assert not kp6, (name, [str(d) for d in kp6])
