"""Independent numpy reference implementations of the dense descriptors.

These are deliberately written WITHOUT jax and without the library's
conv/gather helpers — plain numpy with explicit loops where practical —
so they cross-check the XLA programs in `keystone_tpu.nodes.images`
the way the reference's `pyconv.py` scipy script cross-checks its
Convolver (src/test/python/images/pyconv.py:1-29). Any indexing,
padding, or binning bug in the fused TPU formulations shows up as a
numeric mismatch against these.
"""

from __future__ import annotations

import numpy as np


def corr1d_same(a: np.ndarray, k: np.ndarray, axis: int) -> np.ndarray:
    """Cross-correlation along `axis` with XLA 'SAME' zero padding
    (pad_lo = (len-1)//2, remainder high)."""
    a = np.moveaxis(np.asarray(a, np.float64), axis, 0)
    kl = len(k)
    lo = (kl - 1) // 2
    hi = kl - 1 - lo
    zlo = np.zeros((lo,) + a.shape[1:])
    zhi = np.zeros((hi,) + a.shape[1:])
    ap = np.concatenate([zlo, a, zhi], axis=0)
    out = np.zeros_like(a)
    for j in range(kl):
        out += k[j] * ap[j : j + a.shape[0]]
    return np.moveaxis(out, 0, axis)


def sep_filter(img: np.ndarray, k: np.ndarray) -> np.ndarray:
    return corr1d_same(corr1d_same(img, k, 0), k, 1)


def orientation_maps(mag, ang, n_bins):
    """Soft-assigned orientation histogram maps, (H, W, n_bins)."""
    t = np.mod(ang / (2.0 * np.pi) * n_bins, n_bins)
    lo = np.floor(t)
    frac = t - lo
    lo = lo.astype(np.int64) % n_bins
    hi = (lo + 1) % n_bins
    h, w = mag.shape
    maps = np.zeros((h, w, n_bins))
    for y in range(h):
        for x in range(w):
            maps[y, x, lo[y, x]] += mag[y, x] * (1.0 - frac[y, x])
            maps[y, x, hi[y, x]] += mag[y, x] * frac[y, x]
    return maps


# --------------------------------------------------------------------------
# vl_dsift fast-mode oracle (the reference's actual SIFT numerics)
#
# Literal scalar-loop re-derivation of the JNI entry the reference uses:
# VLFeat.cxx:40-210 (`getMultiScaleDSIFTs_f` + `Java_utils_external_VLFeat_
# getSIFTs`) driving vl_dsift in fast mode: per scale s, binSize = bin+2s,
# step = step + s*scaleStep, vl_imsmooth with sigma = binSize/6 (magnif,
# VLFeat.cxx:45,87), bounds offset off = (1+2*numScales)-3s so scales align
# (:95-99), flat window + windowSize 1.5 (:100-104), contrast threshold
# 0.005 zeroing (:63,140-147), then vl_dsift_transpose_descriptor + x512
# short scaling clamped at 255 (:252-259). The image enters TRANSPOSED:
# the Scala side passes width=xDim (which is the HEIGHT, Image.scala:139)
# and flat[y*xDim + x] (Image.scala:89-104), and the final descriptor
# transpose undoes it.
#
# vl_dsift fast-mode internals reproduced here (dsift.c of vlfeat 0.9.20,
# the version the reference Makefile pins): one-sided border / central
# interior gradients; soft orientation binning between adjacent bins;
# per-orientation-channel TRIANGULAR convolution (unit integral, edge-
# replicate padding) standing in for bilinear spatial binning; bin values
# sampled at framex + binx*binSize; each spatial bin reweighted by the
# mean of a Gaussian window (sigma = windowSize*binSize) over the bin
# support, times binSize to restore unit kernel height; L2 -> clamp 0.2
# -> L2 normalization with +VL_EPSILON_F. Zero-egress caveat: vlfeat
# sources are not fetchable here, so the Gaussian-smoothing support
# (ceil(4*sigma)) and the window-mean formula are re-derived from the
# published algorithm; the reference's own VLFeatSuite tolerates exactly
# this class of smoothing difference (99.5% of entries within 1).
# --------------------------------------------------------------------------

VL_EPSILON_F = 1.19209290e-07


def _edge_pad_conv1d(a: np.ndarray, k: np.ndarray, axis: int) -> np.ndarray:
    """Symmetric-kernel convolution along `axis` with EDGE-REPLICATE
    padding (vlfeat VL_PAD_BY_CONTINUITY)."""
    a = np.moveaxis(np.asarray(a, np.float64), axis, 0)
    r = (len(k) - 1) // 2
    lo = np.repeat(a[:1], r, axis=0)
    hi = np.repeat(a[-1:], r, axis=0)
    ap = np.concatenate([lo, a, hi], axis=0)
    out = np.zeros_like(a)
    for j in range(len(k)):
        out += k[j] * ap[j : j + a.shape[0]]
    return np.moveaxis(out, 0, axis)


def vl_imsmooth(img: np.ndarray, sigma: float) -> np.ndarray:
    """vl_imsmooth_f: separable Gaussian, support ceil(4*sigma),
    normalized, edge-replicate padding."""
    if sigma < 0.01:
        return np.asarray(img, np.float64)
    r = max(int(np.ceil(4.0 * sigma)), 1)
    x = np.arange(-r, r + 1, dtype=np.float64)
    k = np.exp(-0.5 * (x / sigma) ** 2)
    k /= k.sum()
    return _edge_pad_conv1d(_edge_pad_conv1d(img, k, 0), k, 1)


def _vl_triangular_conv(maps: np.ndarray, bin_size: int) -> np.ndarray:
    """vl_imconvcoltri_f twice (rows then cols): triangular kernel of
    half-width bin_size, UNIT INTEGRAL (taps (bs-|k|)/bs^2), edge-replicate
    padding."""
    bs = bin_size
    k = (bs - np.abs(np.arange(-(bs - 1), bs))).astype(np.float64) / (bs * bs)
    return _edge_pad_conv1d(_edge_pad_conv1d(maps, k, 0), k, 1)


def _vl_bin_window_mean(bin_size: int, num_bins: int, bin_index: int,
                        window_size: float) -> float:
    """_vl_dsift_get_bin_window_mean: mean over the triangular support of
    the Gaussian window (sigma = binSize*windowSize) centered on the
    descriptor center, offset by the bin's delta."""
    delta = bin_size * (bin_index - (num_bins - 1) / 2.0)
    sigma = bin_size * window_size
    xs = np.arange(-bin_size + 1, bin_size, dtype=np.float64)
    return float(np.mean(np.exp(-0.5 * ((xs + delta) / sigma) ** 2)))


def _vl_dsift_fast(smoothed: np.ndarray, step: int, bin_size: int, off: int):
    """vl_dsift_process in flat-window mode on a pre-smoothed vlfeat-layout
    image, bounds [off, dim-1]. Returns (descrs (n, 128) in vlfeat
    (biny, binx, bint) layout, first-pass norms (n,))."""
    h, w = smoothed.shape  # vlfeat height (rows) / width (cols)
    n_bin_t, n_bin_s = 8, 4
    # gradients: central interior, one-sided borders (dsift.c update pass)
    grads = np.zeros((h, w, n_bin_t))
    for y in range(h):
        for x in range(w):
            if y == 0:
                gy = smoothed[1, x] - smoothed[0, x]
            elif y == h - 1:
                gy = smoothed[h - 1, x] - smoothed[h - 2, x]
            else:
                gy = 0.5 * (smoothed[y + 1, x] - smoothed[y - 1, x])
            if x == 0:
                gx = smoothed[y, 1] - smoothed[y, 0]
            elif x == w - 1:
                gx = smoothed[y, w - 1] - smoothed[y, w - 2]
            else:
                gx = 0.5 * (smoothed[y, x + 1] - smoothed[y, x - 1])
            mod = np.sqrt(gx * gx + gy * gy)
            angle = np.arctan2(gy, gx)
            nt = np.mod(angle, 2 * np.pi) * (n_bin_t / (2 * np.pi))
            bint = int(np.floor(nt)) % n_bin_t
            rbint = nt - np.floor(nt)
            grads[y, x, bint] += (1.0 - rbint) * mod
            grads[y, x, (bint + 1) % n_bin_t] += rbint * mod
    agg = _vl_triangular_conv(grads, bin_size)

    frame_size = bin_size * (n_bin_s - 1) + 1
    frames_y = [fy for fy in range(off, (h - 1) - frame_size + 2, step)]
    frames_x = [fx for fx in range(off, (w - 1) - frame_size + 2, step)]
    wmean = [_vl_bin_window_mean(bin_size, n_bin_s, b, 1.5) * bin_size
             for b in range(n_bin_s)]
    descrs = np.zeros((len(frames_y) * len(frames_x), 128))
    norms = np.zeros(len(frames_y) * len(frames_x))
    i = 0
    for fy in frames_y:          # framey is the OUTER loop (dsift.c)
        for fx in frames_x:
            d = np.zeros(128)
            for biny in range(n_bin_s):
                for binx in range(n_bin_s):
                    v = agg[fy + biny * bin_size, fx + binx * bin_size, :]
                    d[biny * n_bin_s * n_bin_t + binx * n_bin_t:
                      biny * n_bin_s * n_bin_t + (binx + 1) * n_bin_t] = (
                        wmean[binx] * wmean[biny] * v)
            norm = np.sqrt(np.sum(d * d)) + VL_EPSILON_F
            d /= norm
            norms[i] = norm
            d = np.minimum(d, 0.2)
            d /= np.sqrt(np.sum(d * d)) + VL_EPSILON_F
            descrs[i] = d
            i += 1
    return descrs, norms


def vl_dsift_multiscale(gray: np.ndarray, step: int = 3, bin_size: int = 4,
                        num_scales: int = 4, scale_step: int = 0) -> np.ndarray:
    """The full JNI getSIFTs oracle: (H, W) grayscale in [0,1] ->
    (num_desc, 128) float of quantized shorts in [0, 255], scales
    concatenated (groupByPixels=false path, VLFeat.cxx:160-185)."""
    gray = np.asarray(gray, np.float64)
    img_vl = gray.T  # Scala flattening transposes (Image.scala:89-104)
    out = []
    for s in range(num_scales):
        bs = bin_size + 2 * s
        sigma = bs / 6.0
        st = step + s * scale_step
        # clamped like vl_dsift clamps bounds to the image (negative for
        # num_scales >= 5; unclamped it would wrap numpy indexing)
        off = max((1 + 2 * num_scales) - s * 3, 0)
        smoothed = vl_imsmooth(img_vl, sigma)
        descrs, norms = _vl_dsift_fast(smoothed, st, bs, off)
        descrs[norms < 0.005] = 0.0  # contrast threshold zeroing
        # vl_dsift_transpose_descriptor + x512 short scaling clamp 255
        n = descrs.shape[0]
        res = np.zeros((n, 128))
        for i in range(n):
            for y in range(4):
                for x in range(4):
                    for t in range(8):
                        tt = (8 // 4 - t) % 8
                        v = descrs[i, (y * 4 + x) * 8 + t]
                        q = int(512.0 * v)
                        res[i, (x * 4 + y) * 8 + tt] = min(q, 255)
        out.append(res)
    return np.concatenate(out, axis=0)


def hog(img, cell_size: int):
    """Scalar-loop oracle for descriptors.HogExtractor, implementing the
    REFERENCE semantics (HogExtractor.scala:33-296 / voc-dpm
    features.cc), not the jax formulation: per-pixel 18-orientation
    snapping by max |dot| against 9 unit vectors, bilinear tent binning
    of each pixel's magnitude into the 4 surrounding cells, interior
    cells only, 32 features (18 sensitive + 9 insensitive + 4 texture +
    trailing 0). Axis convention: the reference's x is the ROW index
    (Image.scala:139 — xDim is the height), so its dx is the vertical
    derivative. Returns ((cells_r-2)*(cells_c-2), 32)."""
    img = np.asarray(img, np.float64)
    cs = cell_size
    h, w, c = img.shape
    cells_r = int(np.floor(h / cs + 0.5))
    cells_c = int(np.floor(w / cs + 0.5))
    vis_r, vis_c = min(cells_r * cs, h), min(cells_c * cs, w)
    uu = np.cos(np.arange(9) * np.pi / 9)
    vv = np.sin(np.arange(9) * np.pi / 9)
    hist = np.zeros((cells_r, cells_c, 18))
    for r in range(1, vis_r - 1):
        for col in range(1, vis_c - 1):
            # highest-gradient channel, scanning c-1..0 with strict >
            best_m2 = -np.inf
            gv = gh = 0.0
            for ch in range(c - 1, -1, -1):
                dv = img[r + 1, col, ch] - img[r - 1, col, ch]
                dh = img[r, col + 1, ch] - img[r, col - 1, ch]
                m2 = dv * dv + dh * dh
                if m2 > best_m2:
                    best_m2, gv, gh = m2, dv, dh
            mag = np.sqrt(best_m2)
            # snap to one of 18 orientations (strict >, init 0.0)
            best_dot, best_o = 0.0, 0
            for o in range(9):
                dot = uu[o] * gh + vv[o] * gv
                if dot > best_dot:
                    best_dot, best_o = dot, o
                elif -dot > best_dot:
                    best_dot, best_o = -dot, o + 9
            # bilinear tent binning into the 4 surrounding cells
            rp = (r + 0.5) / cs - 0.5
            cp = (col + 0.5) / cs - 0.5
            irp, icp = int(np.floor(rp)), int(np.floor(cp))
            vr0, vc0 = rp - irp, cp - icp
            vr1, vc1 = 1.0 - vr0, 1.0 - vc0
            if irp >= 0 and icp >= 0:
                hist[irp, icp, best_o] += vr1 * vc1 * mag
            if irp + 1 < cells_r and icp >= 0:
                hist[irp + 1, icp, best_o] += vr0 * vc1 * mag
            if irp >= 0 and icp + 1 < cells_c:
                hist[irp, icp + 1, best_o] += vr1 * vc0 * mag
            if irp + 1 < cells_r and icp + 1 < cells_c:
                hist[irp + 1, icp + 1, best_o] += vr0 * vc0 * mag
    energy = np.zeros((cells_r, cells_c))
    for o in range(9):
        energy += (hist[:, :, o] + hist[:, :, o + 9]) ** 2
    eps = 1e-4
    fr, fc = max(cells_r - 2, 0), max(cells_c - 2, 0)
    out = np.zeros((fr * fc, 32))
    for r in range(fr):
        for col in range(fc):
            row = r * fc + col
            hc = hist[r + 1, col + 1, :]
            # four 2x2 cell-energy blocks containing cell (r+1, col+1),
            # in the reference's n1..n4 order
            ns = []
            for dr, dc in ((1, 1), (0, 1), (1, 0), (0, 0)):
                blk = (energy[r + dr, col + dc] + energy[r + dr + 1, col + dc]
                       + energy[r + dr, col + dc + 1]
                       + energy[r + dr + 1, col + dc + 1])
                ns.append(1.0 / np.sqrt(blk + eps))
            ts = [0.0, 0.0, 0.0, 0.0]
            for o in range(18):
                acc = 0.0
                for i, n in enumerate(ns):
                    hv = min(hc[o] * n, 0.2)
                    acc += hv
                    ts[i] += hv
                out[row, o] = 0.5 * acc
            for o in range(9):
                s = hc[o] + hc[o + 9]
                out[row, 18 + o] = 0.5 * sum(min(s * n, 0.2) for n in ns)
            for i in range(4):
                out[row, 27 + i] = 0.2357 * ts[i]
            # out[row, 31] stays 0 (truncation feature)
    return out


def _conv2d_same(img, xf, yf):
    """ImageUtils.conv2D (ImageUtils.scala:226-338): zero-pad to
    (h+lx−1, w+ly−1) with floor/ceil split, reverse both filters, then
    valid separable correlation — i.e. same-size TRUE convolution, xf
    along axis 0 (the reference's x = row), yf along axis 1."""
    img = np.asarray(img, np.float64)
    xf = np.asarray(xf, np.float64)[::-1]
    yf = np.asarray(yf, np.float64)[::-1]
    lx, ly = len(xf), len(yf)
    pad_x = ((lx - 1) // 2, lx - 1 - (lx - 1) // 2)
    pad_y = ((ly - 1) // 2, ly - 1 - (ly - 1) // 2)
    pad = [pad_x, pad_y] + [(0, 0)] * (img.ndim - 2)
    p = np.pad(img, pad)
    mid = np.zeros((img.shape[0],) + p.shape[1:], np.float64)
    for i in range(lx):
        mid += xf[i] * p[i : i + img.shape[0]]
    out = np.zeros(img.shape, np.float64)
    for i in range(ly):
        out += yf[i] * mid[:, i : i + img.shape[1]]
    return out


def daisy(gray, stride: int = 4, radius: int = 7, rings: int = 3,
          ring_points: int = 8, num_orientations: int = 8,
          pixel_border: int = 16):
    """Scalar-structure oracle for DaisyExtractor.scala:28-201:
    [1,0,-1]⊗[1,2,1] gradients, H rectified orientation maps,
    incremental un-normalized Gaussian blur levels on the
    σ(n)=R·n/2Q variance schedule, center (level-0) + T×Q ring
    histograms at angle 2π(t−1)/T with Scala round-half-up offsets,
    each H-vector L2-normalized (zeroed under 1e-8). Returns
    (num_keypoints, H·(T·Q+1)) — the transpose of the Scala output,
    rows in the reference's x-major keypoint order, columns in its
    packing order (center, then t-major (t,q) blocks)."""
    import math

    gray = np.asarray(gray, np.float64)
    R, Q, T, H, border = radius, rings, ring_points, num_orientations, pixel_border
    f1 = [1.0, 0.0, -1.0]
    f2 = [1.0, 2.0, 1.0]
    ix = _conv2d_same(gray, f1, f2)
    iy = _conv2d_same(gray, f2, f1)

    sigma_sq = [(R * n / (2.0 * Q)) ** 2 for n in range(Q + 1)]
    diffs = [sigma_sq[n + 1] - sigma_sq[n] for n in range(Q)]
    taps = []
    for t in diffs:
        support = int(math.ceil(math.sqrt(
            -2.0 * t * math.log(1e-6) - t * math.log(2.0 * math.pi * t))))
        n = np.arange(-support, support + 1, dtype=np.float64)
        taps.append(np.exp(-(n ** 2) / (2.0 * t))
                    / math.sqrt(2.0 * math.pi * t))

    level_maps = []
    accs = []
    for o in range(H):
        a = 2.0 * math.pi * o / H
        omap = np.maximum(math.cos(a) * ix + math.sin(a) * iy, 0.0)
        layers = []
        acc = omap
        for q in range(Q):
            acc = _conv2d_same(acc, taps[q], taps[q])
            layers.append(acc)
        accs.append(layers)
    # level_maps[q][o] mirrors the Scala daisyLayers(level)(angle)
    level_maps = [[accs[o][q] for o in range(H)] for q in range(Q)]

    def norm_hist(v):
        nrm = math.sqrt(float(np.sum(v * v)))
        return v / nrm if nrm > 1e-8 else np.zeros_like(v)

    h, w = gray.shape
    kx = list(range(border, h - border, stride))
    ky = list(range(border, w - border, stride))
    rows = []
    for x0 in kx:
        for y0 in ky:
            d = [norm_hist(np.asarray(
                [level_maps[0][o][x0, y0] for o in range(H)]))]
            for t in range(T):
                theta = 2.0 * math.pi * (t - 1) / T
                for q in range(Q):
                    r = R * (1.0 + q) / Q
                    ox = int(math.floor(r * math.sin(theta) + 0.5))
                    oy = int(math.floor(r * math.cos(theta) + 0.5))
                    d.append(norm_hist(np.asarray(
                        [level_maps[q][o][x0 + ox, y0 + oy]
                         for o in range(H)])))
            rows.append(np.concatenate(d))
    return np.stack(rows)


def lcs(img, stride: int, subpatch_size: int, subpatches: int):
    """Reference for descriptors.LCSExtractor: (n_y*n_x, 2*g*g*C)."""
    img = np.asarray(img, np.float64)
    sp, g = subpatch_size, subpatches
    box = np.ones(sp) / sp
    mean = sep_filter(img, box)
    mean2 = sep_filter(img * img, box)
    std = np.sqrt(np.maximum(mean2 - mean * mean, 0.0))
    h, w, c = img.shape
    span = g * sp
    n_y = max((h - span) // stride + 1, 0)
    n_x = max((w - span) // stride + 1, 0)
    off = sp // 2
    rows = []
    for iy in range(n_y):
        for ix in range(n_x):
            y0 = iy * stride + off
            x0 = ix * stride + off
            feats = []
            for m in (mean, std):
                for gy in range(g):
                    for gx in range(g):
                        feats.extend(m[y0 + gy * sp, x0 + gx * sp, :])
            rows.append(feats)
    return np.asarray(rows)
