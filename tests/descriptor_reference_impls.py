"""Independent numpy reference implementations of the dense descriptors.

These are deliberately written WITHOUT jax and without the library's
conv/gather helpers — plain numpy with explicit loops where practical —
so they cross-check the XLA programs in `keystone_tpu.nodes.images`
the way the reference's `pyconv.py` scipy script cross-checks its
Convolver (src/test/python/images/pyconv.py:1-29). Any indexing,
padding, or binning bug in the fused TPU formulations shows up as a
numeric mismatch against these.
"""

from __future__ import annotations

import numpy as np


def gaussian_kernel(sigma: float) -> np.ndarray:
    radius = max(int(np.ceil(3 * sigma)), 1)
    x = np.arange(-radius, radius + 1, dtype=np.float64)
    k = np.exp(-0.5 * (x / sigma) ** 2)
    return k / k.sum()


def corr1d_same(a: np.ndarray, k: np.ndarray, axis: int) -> np.ndarray:
    """Cross-correlation along `axis` with XLA 'SAME' zero padding
    (pad_lo = (len-1)//2, remainder high)."""
    a = np.moveaxis(np.asarray(a, np.float64), axis, 0)
    kl = len(k)
    lo = (kl - 1) // 2
    hi = kl - 1 - lo
    zlo = np.zeros((lo,) + a.shape[1:])
    zhi = np.zeros((hi,) + a.shape[1:])
    ap = np.concatenate([zlo, a, zhi], axis=0)
    out = np.zeros_like(a)
    for j in range(kl):
        out += k[j] * ap[j : j + a.shape[0]]
    return np.moveaxis(out, 0, axis)


def sep_filter(img: np.ndarray, k: np.ndarray) -> np.ndarray:
    return corr1d_same(corr1d_same(img, k, 0), k, 1)


def central_gradients(gray: np.ndarray):
    dy = np.zeros_like(gray)
    dx = np.zeros_like(gray)
    dy[1:-1, :] = (gray[2:, :] - gray[:-2, :]) * 0.5
    dx[:, 1:-1] = (gray[:, 2:] - gray[:, :-2]) * 0.5
    return dy, dx


def orientation_maps(mag, ang, n_bins):
    """Soft-assigned orientation histogram maps, (H, W, n_bins)."""
    t = np.mod(ang / (2.0 * np.pi) * n_bins, n_bins)
    lo = np.floor(t)
    frac = t - lo
    lo = lo.astype(np.int64) % n_bins
    hi = (lo + 1) % n_bins
    h, w = mag.shape
    maps = np.zeros((h, w, n_bins))
    for y in range(h):
        for x in range(w):
            maps[y, x, lo[y, x]] += mag[y, x] * (1.0 - frac[y, x])
            maps[y, x, hi[y, x]] += mag[y, x] * frac[y, x]
    return maps


def dense_sift_one_scale(gray, bin_size: int, step: int, sigma: float):
    """Reference for sift._sift_one_scale: (num_desc, 128)."""
    gray = np.asarray(gray, np.float64)
    if sigma > 0.01:
        gray = sep_filter(gray, gaussian_kernel(sigma))
    dy, dx = central_gradients(gray)
    mag = np.sqrt(dx * dx + dy * dy)
    ang = np.arctan2(dy, dx)
    maps = orientation_maps(mag, ang, 8)
    agg = sep_filter(maps, np.ones(bin_size))

    h, w = gray.shape
    span = 4 * bin_size
    n_y = max((h - span) // step + 1, 0)
    n_x = max((w - span) // step + 1, 0)
    off = bin_size // 2
    descs = np.zeros((n_y * n_x, 128))
    i = 0
    for iy in range(n_y):
        for ix in range(n_x):
            y0 = iy * step + off
            x0 = ix * step + off
            d = []
            for by in range(4):
                for bx in range(4):
                    d.extend(agg[y0 + by * bin_size, x0 + bx * bin_size, :])
            descs[i] = d
            i += 1
    norm = np.linalg.norm(descs, axis=1, keepdims=True)
    descs = descs / np.maximum(norm, 1e-8)
    descs = np.minimum(descs, 0.2)
    norm2 = np.linalg.norm(descs, axis=1, keepdims=True)
    return descs / np.maximum(norm2, 1e-8) * 512.0


def hog(img, cell_size: int):
    """Reference for descriptors.HogExtractor: (cy*cx, 31)."""
    img = np.asarray(img, np.float64)
    cs = cell_size
    dy = np.zeros_like(img)
    dx = np.zeros_like(img)
    dy[1:-1] = (img[2:] - img[:-2]) * 0.5
    dx[:, 1:-1] = (img[:, 2:] - img[:, :-2]) * 0.5
    mag2 = dx * dx + dy * dy
    cidx = np.argmax(mag2, axis=-1)
    yy, xx = np.indices(cidx.shape)
    gx, gy = dx[yy, xx, cidx], dy[yy, xx, cidx]
    mag = np.sqrt(mag2[yy, xx, cidx])
    ang = np.arctan2(gy, gx)
    omaps = orientation_maps(mag, ang, 18)
    agg = sep_filter(omaps, np.ones(cs))
    off = cs // 2
    cells = agg[off::cs, off::cs, :]
    cy, cx = cells.shape[:2]
    unsigned = cells[..., :9] + cells[..., 9:]
    energy = np.sum(unsigned**2, axis=-1)
    epad = np.pad(energy, 1, mode="edge")
    eps = 1e-4
    feats = []
    for oy in (0, 1):
        for ox in (0, 1):
            blk = (
                epad[oy : oy + cy, ox : ox + cx]
                + epad[oy + 1 : oy + 1 + cy, ox : ox + cx]
                + epad[oy : oy + cy, ox + 1 : ox + 1 + cx]
                + epad[oy + 1 : oy + 1 + cy, ox + 1 : ox + 1 + cx]
            )
            feats.append((blk, 1.0 / np.sqrt(blk + eps)))
    f_signed = sum(np.minimum(cells * inv[..., None], 0.2) for _, inv in feats) * 0.5
    f_unsigned = (
        sum(np.minimum(unsigned * inv[..., None], 0.2) for _, inv in feats) * 0.5
    )
    g_feats = np.stack(
        [
            np.sum(np.minimum(np.minimum(cells * inv[..., None], 0.2), 0.2), axis=-1)
            * 0.2357
            for _, inv in feats
        ],
        axis=-1,
    )
    out = np.concatenate([f_signed, f_unsigned, g_feats], axis=-1)
    return out.reshape(cy * cx, 31)


def daisy(gray, stride: int, radius: int, rings: int, ring_points: int,
          num_orientations: int):
    """Reference for descriptors.DaisyExtractor: (n_y*n_x, (1+Q*T)*H)."""
    gray = np.asarray(gray, np.float64)
    R, Q, T, H = radius, rings, ring_points, num_orientations
    dy, dx = central_gradients(gray)
    omaps = np.stack(
        [
            np.maximum(np.cos(a) * dx + np.sin(a) * dy, 0.0)
            for a in np.arange(H) * (2 * np.pi / H)
        ],
        axis=-1,
    )
    level_maps = []
    acc = omaps
    for q in range(Q):
        sigma = R * (q + 1) / (Q * 2.0)
        acc = sep_filter(acc, gaussian_kernel(sigma))
        level_maps.append(acc)
    h, w = gray.shape
    margin = R + 1
    n_y = max((h - 2 * margin) // stride + 1, 0)
    n_x = max((w - 2 * margin) // stride + 1, 0)
    rows = []
    for iy in range(n_y):
        for ix in range(n_x):
            y0 = iy * stride + margin
            x0 = ix * stride + margin
            d = [level_maps[0][y0, x0, :]]
            for q in range(Q):
                r = R * (q + 1) / Q
                for t in range(T):
                    a = 2 * np.pi * t / T
                    oy = int(np.round(r * np.sin(a)))
                    ox = int(np.round(r * np.cos(a)))
                    d.append(level_maps[q][y0 + oy, x0 + ox, :])
            rows.append(np.concatenate(d))
    out = np.stack(rows)
    norm = np.linalg.norm(out, axis=1, keepdims=True)
    return out / np.maximum(norm, 1e-8)


def lcs(img, stride: int, subpatch_size: int, subpatches: int):
    """Reference for descriptors.LCSExtractor: (n_y*n_x, 2*g*g*C)."""
    img = np.asarray(img, np.float64)
    sp, g = subpatch_size, subpatches
    box = np.ones(sp) / sp
    mean = sep_filter(img, box)
    mean2 = sep_filter(img * img, box)
    std = np.sqrt(np.maximum(mean2 - mean * mean, 0.0))
    h, w, c = img.shape
    span = g * sp
    n_y = max((h - span) // stride + 1, 0)
    n_x = max((w - span) // stride + 1, 0)
    off = sp // 2
    rows = []
    for iy in range(n_y):
        for ix in range(n_x):
            y0 = iy * stride + off
            x0 = ix * stride + off
            feats = []
            for m in (mean, std):
                for gy in range(g):
                    for gx in range(g):
                        feats.extend(m[y0 + gy * sp, x0 + gx * sp, :])
            rows.append(feats)
    return np.asarray(rows)
