"""Independent numpy reference implementations of the dense descriptors.

These are deliberately written WITHOUT jax and without the library's
conv/gather helpers — plain numpy with explicit loops where practical —
so they cross-check the XLA programs in `keystone_tpu.nodes.images`
the way the reference's `pyconv.py` scipy script cross-checks its
Convolver (src/test/python/images/pyconv.py:1-29). Any indexing,
padding, or binning bug in the fused TPU formulations shows up as a
numeric mismatch against these.
"""

from __future__ import annotations

import numpy as np


def gaussian_kernel(sigma: float) -> np.ndarray:
    radius = max(int(np.ceil(3 * sigma)), 1)
    x = np.arange(-radius, radius + 1, dtype=np.float64)
    k = np.exp(-0.5 * (x / sigma) ** 2)
    return k / k.sum()


def corr1d_same(a: np.ndarray, k: np.ndarray, axis: int) -> np.ndarray:
    """Cross-correlation along `axis` with XLA 'SAME' zero padding
    (pad_lo = (len-1)//2, remainder high)."""
    a = np.moveaxis(np.asarray(a, np.float64), axis, 0)
    kl = len(k)
    lo = (kl - 1) // 2
    hi = kl - 1 - lo
    zlo = np.zeros((lo,) + a.shape[1:])
    zhi = np.zeros((hi,) + a.shape[1:])
    ap = np.concatenate([zlo, a, zhi], axis=0)
    out = np.zeros_like(a)
    for j in range(kl):
        out += k[j] * ap[j : j + a.shape[0]]
    return np.moveaxis(out, 0, axis)


def sep_filter(img: np.ndarray, k: np.ndarray) -> np.ndarray:
    return corr1d_same(corr1d_same(img, k, 0), k, 1)


def central_gradients(gray: np.ndarray):
    dy = np.zeros_like(gray)
    dx = np.zeros_like(gray)
    dy[1:-1, :] = (gray[2:, :] - gray[:-2, :]) * 0.5
    dx[:, 1:-1] = (gray[:, 2:] - gray[:, :-2]) * 0.5
    return dy, dx


def orientation_maps(mag, ang, n_bins):
    """Soft-assigned orientation histogram maps, (H, W, n_bins)."""
    t = np.mod(ang / (2.0 * np.pi) * n_bins, n_bins)
    lo = np.floor(t)
    frac = t - lo
    lo = lo.astype(np.int64) % n_bins
    hi = (lo + 1) % n_bins
    h, w = mag.shape
    maps = np.zeros((h, w, n_bins))
    for y in range(h):
        for x in range(w):
            maps[y, x, lo[y, x]] += mag[y, x] * (1.0 - frac[y, x])
            maps[y, x, hi[y, x]] += mag[y, x] * frac[y, x]
    return maps


def dense_sift_one_scale(gray, bin_size: int, step: int, sigma: float):
    """Reference for sift._sift_one_scale: (num_desc, 128)."""
    gray = np.asarray(gray, np.float64)
    if sigma > 0.01:
        gray = sep_filter(gray, gaussian_kernel(sigma))
    dy, dx = central_gradients(gray)
    mag = np.sqrt(dx * dx + dy * dy)
    ang = np.arctan2(dy, dx)
    maps = orientation_maps(mag, ang, 8)
    agg = sep_filter(maps, np.ones(bin_size))

    h, w = gray.shape
    span = 4 * bin_size
    n_y = max((h - span) // step + 1, 0)
    n_x = max((w - span) // step + 1, 0)
    off = bin_size // 2
    descs = np.zeros((n_y * n_x, 128))
    i = 0
    for iy in range(n_y):
        for ix in range(n_x):
            y0 = iy * step + off
            x0 = ix * step + off
            d = []
            for by in range(4):
                for bx in range(4):
                    d.extend(agg[y0 + by * bin_size, x0 + bx * bin_size, :])
            descs[i] = d
            i += 1
    norm = np.linalg.norm(descs, axis=1, keepdims=True)
    descs = descs / np.maximum(norm, 1e-8)
    descs = np.minimum(descs, 0.2)
    norm2 = np.linalg.norm(descs, axis=1, keepdims=True)
    return descs / np.maximum(norm2, 1e-8) * 512.0


def hog(img, cell_size: int):
    """Scalar-loop oracle for descriptors.HogExtractor, implementing the
    REFERENCE semantics (HogExtractor.scala:33-296 / voc-dpm
    features.cc), not the jax formulation: per-pixel 18-orientation
    snapping by max |dot| against 9 unit vectors, bilinear tent binning
    of each pixel's magnitude into the 4 surrounding cells, interior
    cells only, 32 features (18 sensitive + 9 insensitive + 4 texture +
    trailing 0). Axis convention: the reference's x is the ROW index
    (Image.scala:139 — xDim is the height), so its dx is the vertical
    derivative. Returns ((cells_r-2)*(cells_c-2), 32)."""
    img = np.asarray(img, np.float64)
    cs = cell_size
    h, w, c = img.shape
    cells_r = int(np.floor(h / cs + 0.5))
    cells_c = int(np.floor(w / cs + 0.5))
    vis_r, vis_c = min(cells_r * cs, h), min(cells_c * cs, w)
    uu = np.cos(np.arange(9) * np.pi / 9)
    vv = np.sin(np.arange(9) * np.pi / 9)
    hist = np.zeros((cells_r, cells_c, 18))
    for r in range(1, vis_r - 1):
        for col in range(1, vis_c - 1):
            # highest-gradient channel, scanning c-1..0 with strict >
            best_m2 = -np.inf
            gv = gh = 0.0
            for ch in range(c - 1, -1, -1):
                dv = img[r + 1, col, ch] - img[r - 1, col, ch]
                dh = img[r, col + 1, ch] - img[r, col - 1, ch]
                m2 = dv * dv + dh * dh
                if m2 > best_m2:
                    best_m2, gv, gh = m2, dv, dh
            mag = np.sqrt(best_m2)
            # snap to one of 18 orientations (strict >, init 0.0)
            best_dot, best_o = 0.0, 0
            for o in range(9):
                dot = uu[o] * gh + vv[o] * gv
                if dot > best_dot:
                    best_dot, best_o = dot, o
                elif -dot > best_dot:
                    best_dot, best_o = -dot, o + 9
            # bilinear tent binning into the 4 surrounding cells
            rp = (r + 0.5) / cs - 0.5
            cp = (col + 0.5) / cs - 0.5
            irp, icp = int(np.floor(rp)), int(np.floor(cp))
            vr0, vc0 = rp - irp, cp - icp
            vr1, vc1 = 1.0 - vr0, 1.0 - vc0
            if irp >= 0 and icp >= 0:
                hist[irp, icp, best_o] += vr1 * vc1 * mag
            if irp + 1 < cells_r and icp >= 0:
                hist[irp + 1, icp, best_o] += vr0 * vc1 * mag
            if irp >= 0 and icp + 1 < cells_c:
                hist[irp, icp + 1, best_o] += vr1 * vc0 * mag
            if irp + 1 < cells_r and icp + 1 < cells_c:
                hist[irp + 1, icp + 1, best_o] += vr0 * vc0 * mag
    energy = np.zeros((cells_r, cells_c))
    for o in range(9):
        energy += (hist[:, :, o] + hist[:, :, o + 9]) ** 2
    eps = 1e-4
    fr, fc = max(cells_r - 2, 0), max(cells_c - 2, 0)
    out = np.zeros((fr * fc, 32))
    for r in range(fr):
        for col in range(fc):
            row = r * fc + col
            hc = hist[r + 1, col + 1, :]
            # four 2x2 cell-energy blocks containing cell (r+1, col+1),
            # in the reference's n1..n4 order
            ns = []
            for dr, dc in ((1, 1), (0, 1), (1, 0), (0, 0)):
                blk = (energy[r + dr, col + dc] + energy[r + dr + 1, col + dc]
                       + energy[r + dr, col + dc + 1]
                       + energy[r + dr + 1, col + dc + 1])
                ns.append(1.0 / np.sqrt(blk + eps))
            ts = [0.0, 0.0, 0.0, 0.0]
            for o in range(18):
                acc = 0.0
                for i, n in enumerate(ns):
                    hv = min(hc[o] * n, 0.2)
                    acc += hv
                    ts[i] += hv
                out[row, o] = 0.5 * acc
            for o in range(9):
                s = hc[o] + hc[o + 9]
                out[row, 18 + o] = 0.5 * sum(min(s * n, 0.2) for n in ns)
            for i in range(4):
                out[row, 27 + i] = 0.2357 * ts[i]
            # out[row, 31] stays 0 (truncation feature)
    return out


def daisy(gray, stride: int, radius: int, rings: int, ring_points: int,
          num_orientations: int):
    """Reference for descriptors.DaisyExtractor: (n_y*n_x, (1+Q*T)*H)."""
    gray = np.asarray(gray, np.float64)
    R, Q, T, H = radius, rings, ring_points, num_orientations
    dy, dx = central_gradients(gray)
    omaps = np.stack(
        [
            np.maximum(np.cos(a) * dx + np.sin(a) * dy, 0.0)
            for a in np.arange(H) * (2 * np.pi / H)
        ],
        axis=-1,
    )
    level_maps = []
    acc = omaps
    for q in range(Q):
        sigma = R * (q + 1) / (Q * 2.0)
        acc = sep_filter(acc, gaussian_kernel(sigma))
        level_maps.append(acc)
    h, w = gray.shape
    margin = R + 1
    n_y = max((h - 2 * margin) // stride + 1, 0)
    n_x = max((w - 2 * margin) // stride + 1, 0)
    rows = []
    for iy in range(n_y):
        for ix in range(n_x):
            y0 = iy * stride + margin
            x0 = ix * stride + margin
            d = [level_maps[0][y0, x0, :]]
            for q in range(Q):
                r = R * (q + 1) / Q
                for t in range(T):
                    a = 2 * np.pi * t / T
                    oy = int(np.round(r * np.sin(a)))
                    ox = int(np.round(r * np.cos(a)))
                    d.append(level_maps[q][y0 + oy, x0 + ox, :])
            rows.append(np.concatenate(d))
    out = np.stack(rows)
    norm = np.linalg.norm(out, axis=1, keepdims=True)
    return out / np.maximum(norm, 1e-8)


def lcs(img, stride: int, subpatch_size: int, subpatches: int):
    """Reference for descriptors.LCSExtractor: (n_y*n_x, 2*g*g*C)."""
    img = np.asarray(img, np.float64)
    sp, g = subpatch_size, subpatches
    box = np.ones(sp) / sp
    mean = sep_filter(img, box)
    mean2 = sep_filter(img * img, box)
    std = np.sqrt(np.maximum(mean2 - mean * mean, 0.0))
    h, w, c = img.shape
    span = g * sp
    n_y = max((h - span) // stride + 1, 0)
    n_x = max((w - span) // stride + 1, 0)
    off = sp // 2
    rows = []
    for iy in range(n_y):
        for ix in range(n_x):
            y0 = iy * stride + off
            x0 = ix * stride + off
            feats = []
            for m in (mean, std):
                for gy in range(g):
                    for gx in range(g):
                        feats.extend(m[y0 + gy * sp, x0 + gx * sp, :])
            rows.append(feats)
    return np.asarray(rows)
