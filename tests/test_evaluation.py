"""Evaluator tests with hand-computed confusion matrices (model:
reference MulticlassClassifierEvaluatorSuite / BinaryClassifierEvaluatorSuite)."""

import numpy as np

from keystone_tpu import Dataset
from keystone_tpu.evaluation import (
    BinaryClassifierEvaluator,
    MulticlassClassifierEvaluator,
)


def test_multiclass_confusion_and_metrics():
    preds = [0, 1, 2, 1, 0, 2, 2]
    actual = [0, 1, 1, 1, 0, 2, 0]
    m = MulticlassClassifierEvaluator(3)(
        Dataset(np.asarray(preds, np.int32)), Dataset(np.asarray(actual, np.int32))
    )
    expected = np.array(
        [
            [2, 0, 1],  # actual 0: predicted 0 twice, 2 once
            [0, 2, 1],  # actual 1
            [0, 0, 1],  # actual 2
        ],
        dtype=float,
    )
    np.testing.assert_array_equal(m.confusion, expected)
    assert abs(m.accuracy - 5 / 7) < 1e-6
    assert abs(m.class_precision(2) - 1 / 3) < 1e-6
    assert abs(m.class_recall(0) - 2 / 3) < 1e-6
    assert "Accuracy" in m.summary()


def test_multiclass_padding_excluded():
    """Padded rows (7 items over 8 shards -> pads to 8) must not count."""
    preds = Dataset(np.asarray([0, 0, 0, 0, 0, 0, 0], np.int32))
    actual = Dataset(np.asarray([0, 0, 0, 0, 0, 0, 0], np.int32))
    m = MulticlassClassifierEvaluator(2)(preds, actual)
    assert m.total == 7.0
    assert m.accuracy == 1.0


def test_multiclass_host_lists():
    m = MulticlassClassifierEvaluator(2)([0, 1, 1], [0, 1, 0])
    assert m.total == 3.0
    assert abs(m.accuracy - 2 / 3) < 1e-6


def test_binary_contingency():
    m = BinaryClassifierEvaluator()(
        [True, True, False, False, True], [True, False, False, True, True]
    )
    assert (m.tp, m.fp, m.tn, m.fn) == (2.0, 1.0, 1.0, 1.0)
    assert abs(m.precision - 2 / 3) < 1e-6
    assert abs(m.recall - 2 / 3) < 1e-6
    assert abs(m.accuracy - 3 / 5) < 1e-6
