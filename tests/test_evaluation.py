"""Evaluator tests with hand-computed confusion matrices (model:
reference MulticlassClassifierEvaluatorSuite / BinaryClassifierEvaluatorSuite /
MeanAveragePrecisionSuite / AugmentedExamplesEvaluator)."""

import numpy as np
import pytest

from keystone_tpu import Dataset
from keystone_tpu.evaluation import (
    AugmentedExamplesEvaluator,
    BinaryClassifierEvaluator,
    MeanAveragePrecisionEvaluator,
    MulticlassClassifierEvaluator,
)


def test_multiclass_confusion_and_metrics():
    preds = [0, 1, 2, 1, 0, 2, 2]
    actual = [0, 1, 1, 1, 0, 2, 0]
    m = MulticlassClassifierEvaluator(3)(
        Dataset(np.asarray(preds, np.int32)), Dataset(np.asarray(actual, np.int32))
    )
    expected = np.array(
        [
            [2, 0, 1],  # actual 0: predicted 0 twice, 2 once
            [0, 2, 1],  # actual 1
            [0, 0, 1],  # actual 2
        ],
        dtype=float,
    )
    np.testing.assert_array_equal(m.confusion, expected)
    assert abs(m.accuracy - 5 / 7) < 1e-6
    assert abs(m.class_precision(2) - 1 / 3) < 1e-6
    assert abs(m.class_recall(0) - 2 / 3) < 1e-6
    assert "Accuracy" in m.summary()


def test_multiclass_padding_excluded():
    """Padded rows (7 items over 8 shards -> pads to 8) must not count."""
    preds = Dataset(np.asarray([0, 0, 0, 0, 0, 0, 0], np.int32))
    actual = Dataset(np.asarray([0, 0, 0, 0, 0, 0, 0], np.int32))
    m = MulticlassClassifierEvaluator(2)(preds, actual)
    assert m.total == 7.0
    assert m.accuracy == 1.0


def test_multiclass_host_lists():
    m = MulticlassClassifierEvaluator(2)([0, 1, 1], [0, 1, 0])
    assert m.total == 3.0
    assert abs(m.accuracy - 2 / 3) < 1e-6


def test_binary_contingency():
    m = BinaryClassifierEvaluator()(
        [True, True, False, False, True], [True, False, False, True, True]
    )
    assert (m.tp, m.fp, m.tn, m.fn) == (2.0, 1.0, 1.0, 1.0)
    assert abs(m.precision - 2 / 3) < 1e-6
    assert abs(m.recall - 2 / 3) < 1e-6
    assert abs(m.accuracy - 3 / 5) < 1e-6


def test_multiclass_reference_suite_fixture():
    """The reference suite's complete 9-instance 3-class fixture
    (MulticlassClassifierEvaluatorSuite.scala:9-63): per-class P/R/F1 and
    F2, micro (= accuracy for single-label), and macro aggregates."""
    preds = [0, 0, 0, 1, 1, 1, 1, 2, 2]
    actual = [0, 1, 0, 0, 1, 1, 1, 2, 0]
    m = MulticlassClassifierEvaluator(3)(preds, actual)
    want_conf = np.array([[2, 1, 1], [1, 3, 0], [0, 0, 1]], float)
    np.testing.assert_array_equal(m.confusion, want_conf)
    p = [2 / 3, 3 / 4, 1 / 2]
    r = [2 / 4, 3 / 4, 1 / 1]
    f1 = [2 * pi * ri / (pi + ri) for pi, ri in zip(p, r)]
    f2 = [5 * pi * ri / (4 * pi + ri) for pi, ri in zip(p, r)]
    for c in range(3):
        assert abs(m.class_precision(c) - p[c]) < 1e-7
        assert abs(m.class_recall(c) - r[c]) < 1e-7
        assert abs(m.class_f1(c) - f1[c]) < 1e-7
        assert abs(m.class_fbeta(c, 2.0) - f2[c]) < 1e-7
    assert abs(m.micro_recall - 6 / 9) < 1e-7
    assert abs(m.micro_recall - m.micro_precision) < 1e-7
    assert abs(m.micro_recall - m.micro_f1) < 1e-7
    assert abs(m.macro_precision - sum(p) / 3) < 1e-7
    assert abs(m.macro_recall - sum(r) / 3) < 1e-7
    assert abs(m.macro_f1 - sum(f1) / 3) < 1e-7
    assert abs(m.macro_fbeta(2.0) - sum(f2) / 3) < 1e-7


# --------------------------------------------------------------------- mAP
# (reference MeanAveragePrecisionSuite.scala:11-33 + adversarial edges)


def test_map_reference_matlab_fixture():
    """The reference suite's 4-class fixture with MATLAB-derived expected
    APs (MeanAveragePrecisionSuite.scala:15-31)."""
    actuals = [[0, 3], [2], [1, 2], [0]]
    scores = np.array(
        [
            [0.1, -0.05, 0.12, 0.5],
            [-0.23, -0.45, 0.23, 0.1],
            [-0.34, -0.32, -0.66, 1.52],
            [-0.1, -0.2, 0.5, 0.8],
        ]
    )
    aps = MeanAveragePrecisionEvaluator(4)(scores, actuals)
    np.testing.assert_allclose(aps, [1.0, 0.3333, 0.5, 0.3333], atol=1e-4)


def test_map_tied_scores_stable_order():
    """All scores equal: ranking degenerates to the (stable) original
    order [pos at index 1 of 3] → precision [0, 1/2, 1/3], recall
    [0, 1, 1]; max precision at every recall level is 1/2 → AP = 0.5."""
    scores = np.array([[0.5], [0.5], [0.5]])
    actuals = [[], [0], []]
    aps = MeanAveragePrecisionEvaluator(1)(scores, actuals)
    assert abs(aps[0] - 0.5) < 1e-9


def test_map_all_positive_class_is_one():
    """Every example positive → precision 1 at every rank → AP = 1
    regardless of score ordering."""
    scores = np.array([[0.1], [0.9], [0.5]])
    actuals = [[0], [0], [0]]
    aps = MeanAveragePrecisionEvaluator(1)(scores, actuals)
    assert abs(aps[0] - 1.0) < 1e-9


def test_map_single_example():
    scores = np.array([[0.3, 0.7]])
    aps = MeanAveragePrecisionEvaluator(2)(scores, [[1]])
    assert aps[0] == 0.0 and abs(aps[1] - 1.0) < 1e-9


def test_map_worst_ranking_hand_value():
    """One positive ranked dead last of 3: precision [0, 0, 1/3], recall
    [0, 0, 1] → max precision ≥ every recall level is 1/3 → AP = 1/3."""
    scores = np.array([[0.9], [0.8], [0.1]])
    actuals = [[], [], [0]]
    aps = MeanAveragePrecisionEvaluator(1)(scores, actuals)
    assert abs(aps[0] - 1 / 3) < 1e-9


# -------------------------------------------------------- augmented examples
# (reference AugmentedExamplesEvaluator.scala:16-69)


def test_augmented_average_policy_hand_fixture():
    """Two originals, two variants each; per-group mean then argmax
    (dyadic values so the arithmetic is exact).
    Group 'a': mean([0.75,0.125],[0.25,0.875]) = [0.5,0.5] → argmax tie
    → class 0 (true 0, right).
    Group 'b': mean([0.75,0.25],[0.25,0.25]) = [0.5,0.25] → class 0
    (true 1, wrong — a single high-scoring variant outvotes) → acc 1/2."""
    ids = ["a", "a", "b", "b"]
    scores = np.array(
        [[0.75, 0.125], [0.25, 0.875], [0.75, 0.25], [0.25, 0.25]]
    )
    actuals = [0, 0, 1, 1]
    m = AugmentedExamplesEvaluator(2, agg="mean")(ids, scores, actuals)
    assert m.total == 2.0
    assert abs(m.accuracy - 0.5) < 1e-9


def test_augmented_borda_policy_hand_fixture():
    """Borda (AugmentedExamplesEvaluator.scala:27-34): per variant each
    class scores its ascending-sort rank. Group 'a' variants [1,3,2] →
    ranks [0,2,1]; [9,1,5] → ranks [2,0,1]; [2,8,4] → ranks [0,2,1].
    Rank sums = [2,4,3] → argmax class 1, even though plain score-mean
    ([4,4,11/3]) would tie classes 0/1 and argmax to 0."""
    ids = ["a", "a", "a"]
    scores = np.array([[1.0, 3.0, 2.0], [9.0, 1.0, 5.0], [2.0, 8.0, 4.0]])
    actuals = [1, 1, 1]
    m = AugmentedExamplesEvaluator(3, agg="borda")(ids, scores, actuals)
    assert m.accuracy == 1.0
    mean_m = AugmentedExamplesEvaluator(3, agg="mean")(ids, scores, actuals)
    assert mean_m.accuracy == 0.0


def test_augmented_inconsistent_group_labels_raise():
    """Reference asserts one distinct label per name group
    (AugmentedExamplesEvaluator.scala:55)."""
    ids = ["a", "a"]
    scores = np.array([[0.9, 0.1], [0.2, 0.8]])
    with pytest.raises(ValueError, match="inconsistent labels"):
        AugmentedExamplesEvaluator(2)(ids, scores, [0, 1])


def test_augmented_single_variant_groups_match_plain_multiclass():
    ids = [0, 1, 2]
    scores = np.array([[0.9, 0.1], [0.2, 0.8], [0.7, 0.3]])
    actuals = [0, 1, 1]
    m = AugmentedExamplesEvaluator(2)(ids, scores, actuals)
    plain = MulticlassClassifierEvaluator(2)([0, 1, 0], actuals)
    assert m.total == plain.total == 3.0
    assert abs(m.accuracy - plain.accuracy) < 1e-9
    np.testing.assert_array_equal(m.confusion, plain.confusion)
