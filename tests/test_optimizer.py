"""Optimizer-rule tests (model: reference NodeOptimizationRuleSuite.scala,
AutocCacheRuleSuite.scala:74-181) plus regression tests for review
findings (HostDataset routing, stale prefix identity)."""

import gc

import numpy as np
import pytest

from keystone_tpu import Dataset, HostDataset, Pipeline, PipelineEnv, Transformer
from keystone_tpu.workflow import Estimator
from keystone_tpu.workflow.autocache import (
    AutoCacheRule,
    CacheMarker,
    Profile,
    estimate_cached_run_time,
    get_runs,
)
from keystone_tpu.workflow.graph import Graph
from keystone_tpu.workflow.optimizer import AutoCachingOptimizer
from keystone_tpu.workflow.pipeline import OptimizableEstimator


class Upper(Transformer):
    def apply(self, x):
        return x.upper()


def test_host_dataset_routed_to_batch_path():
    out = Upper()(HostDataset(["a", "b"])).get()
    assert isinstance(out, HostDataset)
    assert out.items == ["A", "B"]


def test_host_dataset_through_gather():
    p = Pipeline.gather([Upper(), Upper()])
    out = p(HostDataset(["x"])).get()
    assert out.items == [["X", "X"]]


def test_autocaching_optimizer_instantiates_and_runs():
    PipelineEnv.get().set_optimizer(AutoCachingOptimizer(strategy="aggressive"))
    ds = Dataset.from_numpy(np.ones((8, 2), np.float32))
    p = Transformer.from_function(lambda x: x + 1).to_pipeline()
    out = p(ds).get()
    np.testing.assert_allclose(out.numpy(), 2 * np.ones((8, 2)))


class MeanEstimator(Estimator):
    n_fits = 0

    def fit(self, data):
        MeanEstimator.n_fits += 1
        mu = float(np.mean(data.numpy()))
        return Transformer.from_function(lambda x: x - mu)


def test_prefix_identity_survives_gc_address_reuse():
    """Stale-state regression: freed estimators/datasets must never collide
    with new objects reusing the same address (review finding)."""
    start_fits = MeanEstimator.n_fits
    outs = []
    for i in range(4):
        est = MeanEstimator()
        train = Dataset.from_numpy(np.full((4, 1), float(i), np.float32))
        p = Transformer.from_function(lambda x: x).to_pipeline().and_then(est, train)
        outs.append(float(p(np.float32(10.0)).get()))
        del est, train, p
        gc.collect()
    assert outs == [10.0, 9.0, 8.0, 7.0]
    assert MeanEstimator.n_fits - start_fits == 4


# ---------------------------------------------------------------- autocache


def _diamond_graph():
    """source-free diamond: data -> f -> {g, h} -> (both weighted users)."""
    ident = lambda name: Transformer.from_function(lambda x: x, name=name)
    g = Graph()
    g, data = g.add_node(
        __import__("keystone_tpu.workflow.operators", fromlist=["DatasetOperator"]).DatasetOperator(
            Dataset.from_numpy(np.ones((8, 2), np.float32))
        ),
        [],
    )
    g, f = g.add_node(ident("f"), [data])
    g, a = g.add_node(ident("a"), [f])
    g, b = g.add_node(ident("b"), [f])
    g, s1 = g.add_sink(a)
    g, s2 = g.add_sink(b)
    return g, data, f, a, b


def test_get_runs_counts_weighted_demand():
    g, data, f, a, b = _diamond_graph()
    runs = get_runs(g, cached=set())
    assert runs[a] == 1 and runs[b] == 1
    assert runs[f] == 2  # two consumers
    # weight on a consumer multiplies demand
    g2 = g.set_operator(a, WeightedIdentity(3))
    runs2 = get_runs(g2, cached=set())
    assert runs2[f] == 4  # 3 (weighted a) + 1 (b)
    # caching f collapses its runs
    assert get_runs(g2, cached={f})[f] == 1


class WeightedIdentity(Transformer):
    def __init__(self, weight):
        self.weight = weight

    def apply(self, x):
        return x


def test_aggressive_cache_inserts_marker_on_shared_node():
    g, data, f, a, b = _diamond_graph()
    rule = AutoCacheRule(strategy="aggressive")
    g2, _ = rule.apply((g, {}))
    cache_nodes = [
        n for n in g2.nodes if isinstance(g2.get_operator(n), CacheMarker)
    ]
    assert len(cache_nodes) == 1
    (c,) = cache_nodes
    assert g2.get_dependencies(c) == (f,)
    # both consumers rewired through the cache
    assert g2.get_dependencies(a) == (c,)
    assert g2.get_dependencies(b) == (c,)


def test_greedy_cache_respects_memory_budget():
    g, data, f, a, b = _diamond_graph()
    profiles = {f: Profile(ns=1e9, mem_bytes=100.0)}
    # budget too small: no caching
    rule = AutoCacheRule(strategy="greedy", mem_budget_bytes=10)
    rule_profiles = lambda *args, **kw: profiles
    import keystone_tpu.workflow.autocache as ac

    orig = ac.profile_nodes
    ac.profile_nodes = lambda *a, **k: profiles
    try:
        g_small, _ = rule.apply((g, {}))
        assert not any(isinstance(g_small.get_operator(n), CacheMarker) for n in g_small.nodes)
        # ample budget: caches f
        rule2 = AutoCacheRule(strategy="greedy", mem_budget_bytes=10_000)
        g_big, _ = rule2.apply((g, {}))
        assert any(isinstance(g_big.get_operator(n), CacheMarker) for n in g_big.nodes)
    finally:
        ac.profile_nodes = orig


def test_estimate_cached_run_time():
    g, data, f, a, b = _diamond_graph()
    profiles = {f: Profile(1000.0, 1.0), a: Profile(10.0, 1.0), b: Profile(10.0, 1.0)}
    uncached = estimate_cached_run_time(g, set(), profiles)
    cached = estimate_cached_run_time(g, {f}, profiles)
    assert uncached == 2 * 1000 + 10 + 10
    assert cached == 1000 + 10 + 10


class RoutingEstimator(OptimizableEstimator):
    """Picks an implementation from the sample size (cost-model routing
    pattern, LeastSquaresEstimatorSuite analog)."""

    def __init__(self):
        self.chosen = None

    @property
    def default(self):
        return MeanEstimator()

    def optimize(self, sample, num_per_shard):
        self.chosen = "big" if num_per_shard > 10 else "small"
        return MeanEstimator()


def test_node_optimization_rule_consults_sample():
    est = RoutingEstimator()
    train = Dataset.from_numpy(np.arange(800, dtype=np.float32).reshape(100, 8))
    p = Transformer.from_function(lambda x: x).to_pipeline().and_then(est, train)
    _ = p(train).get()
    assert est.chosen == "big"  # 100 rows over 8 shards -> 13/shard > 10


def _double_diamond_graph():
    """Two shared nodes with different profiles hanging off one dataset:
    data -> f1 -> {a, b}, data -> f2 -> {c, d} (4 sinks)."""
    from keystone_tpu.workflow.operators import DatasetOperator

    ident = lambda name: Transformer.from_function(lambda x: x, name=name)
    g = Graph()
    g, data = g.add_node(
        DatasetOperator(Dataset.from_numpy(np.ones((8, 2), np.float32))), []
    )
    g, f1 = g.add_node(ident("f1"), [data])
    g, a = g.add_node(ident("a"), [f1])
    g, b = g.add_node(ident("b"), [f1])
    g, f2 = g.add_node(ident("f2"), [data])
    g, c = g.add_node(ident("c"), [f2])
    g, d = g.add_node(ident("d"), [f2])
    for leaf in (a, b, c, d):
        g, _ = g.add_sink(leaf)
    return g, f1, f2


@pytest.mark.parametrize(
    "budget,expect",
    [
        (10, set()),           # nothing fits
        (60, {"f2"}),          # only the small node fits
        (100, {"f1"}),         # best saving first; f2 no longer fits
        (149, {"f1"}),         # f2 still does not fit (100 + 50 > 149)
        (200, {"f1", "f2"}),   # both fit
    ],
)
def test_greedy_cache_across_memory_budgets(monkeypatch, budget, expect):
    """Greedy decisions swept across budgets with synthetic profiles
    (reference AutocCacheRuleSuite.scala:74-181)."""
    import keystone_tpu.workflow.autocache as ac

    g, f1, f2 = _double_diamond_graph()
    profiles = {f1: Profile(ns=1000.0, mem_bytes=100.0),
                f2: Profile(ns=600.0, mem_bytes=50.0)}
    monkeypatch.setattr(ac, "profile_nodes", lambda *a, **k: profiles)

    rule = AutoCacheRule(strategy="greedy", mem_budget_bytes=budget)
    g2, _ = rule.apply((g, {}))
    cached_parents = {
        g2.get_operator(g2.get_dependencies(n)[0]).label
        for n in g2.nodes
        if isinstance(g2.get_operator(n), CacheMarker)
    }
    assert cached_parents == expect


def test_profile_nodes_attributes_compute_to_slow_node():
    """Honest-profiling sanity (VERDICT r2 #5): `profile_nodes` must
    measure a node's compute time, not just dispatch. A node that
    genuinely takes ~50 ms per call must dominate the profile over a
    cheap sibling — under dispatch-only timing both would be ~0.
    Reference analog: AutoCacheRule.profileNodes times real work on
    per-partition samples (AutoCacheRule.scala:153-469)."""
    import time as _time

    from keystone_tpu.workflow.autocache import profile_nodes

    class Slow(Transformer):
        def apply(self, x):
            _time.sleep(0.15)
            return x * 2.0

        def apply_batch(self, data):
            _time.sleep(0.15)
            return data.map_batches(lambda a: a * 2.0)

    class Cheap(Transformer):
        def apply(self, x):
            return x + 1.0

        def apply_batch(self, data):
            return data.map_batches(lambda a: a + 1.0)

    PipelineEnv.reset()
    data = Dataset(np.ones((64, 4), np.float32))
    pipe = Slow().to_pipeline() >> Cheap()
    result = pipe(data)
    graph = result.executor.graph
    targets = [v for v in graph.operators]
    profiles = profile_nodes(graph, targets, scales=(2, 4))
    # the transformer instance itself is the node operator
    slow_ns = cheap_ns = None
    for node, op in graph.operators.items():
        if node in profiles:
            name = type(op).__name__
            if name == "Slow":
                slow_ns = profiles[node].ns
            elif name == "Cheap":
                cheap_ns = profiles[node].ns
    assert slow_ns is not None and cheap_ns is not None
    assert slow_ns > 100e6  # most of the 150 ms sleep is attributed
    # generous ratio: the cheap node's cost is retrace/dispatch (tens of
    # ms, load-sensitive on a saturated CI box); the 150 ms sleep keeps
    # the margin even when a compile lands in the cheap profile
    assert slow_ns > 2 * cheap_ns


def test_dataset_sync_forces_value():
    """Dataset.sync() must return only after the computation's value is
    real on host (a scalar pull, not block_until_ready which is a no-op
    through the axon tunnel)."""
    d = Dataset(np.arange(12, dtype=np.float32).reshape(3, 4))
    out = d.map_batches(lambda a: a * 3.0)
    assert out.sync() is out
    np.testing.assert_allclose(np.asarray(out.array)[0, 1], 3.0)
