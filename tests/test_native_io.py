"""Native host data-plane tests (model: reference VLFeatSuite/EncEvalSuite,
which exercise the JNI boundary against real fixtures — here the ctypes
boundary of native/keystone_io.cpp, with pure-Python paths as the oracle).

Skip cleanly when the native build is absent (`make -C native`).
"""

import io
import os
import tarfile

import numpy as np
import pytest

from keystone_tpu.utils import native_io

pytestmark = pytest.mark.skipif(
    not native_io.available(), reason="native library not built"
)


def _jpeg_bytes(rng, h, w):
    from PIL import Image

    img = Image.fromarray(rng.integers(0, 255, (h, w, 3), dtype=np.uint8))
    bio = io.BytesIO()
    img.save(bio, format="JPEG", quality=95)
    return bio.getvalue()


@pytest.fixture
def jpeg_tar(tmp_path):
    """A tar of JPEGs in class subdirectories + one non-image entry."""
    rng = np.random.default_rng(0)
    path = tmp_path / "imgs.tar"
    blobs = {}
    with tarfile.open(path, "w") as tar:
        for i, (cls, h, w) in enumerate(
            [("cat", 24, 32), ("cat", 40, 40), ("dog", 32, 24), ("dog", 28, 36)]
        ):
            data = _jpeg_bytes(rng, h, w)
            name = f"{cls}/img{i}.jpg"
            blobs[name] = data
            ti = tarfile.TarInfo(name)
            ti.size = len(data)
            tar.addfile(ti, io.BytesIO(data))
        meta = b"not an image"
        ti = tarfile.TarInfo("README.txt")
        ti.size = len(meta)
        tar.addfile(ti, io.BytesIO(meta))
    return path, blobs


def test_tar_index_matches_tarfile(jpeg_tar):
    path, blobs = jpeg_tar
    buf = path.read_bytes()
    index = native_io.tar_index(buf)
    assert index is not None
    names = [n for n, _, _ in index]
    assert names == list(blobs.keys()) + ["README.txt"]
    for name, off, size in index:
        expected = blobs.get(name, b"not an image")
        assert buf[off : off + size] == expected


def test_tar_index_pax_long_names(tmp_path):
    """Python tarfile writes PAX; names >100 chars live in 'x' headers."""
    long_name = "a" * 80 + "/" + "b" * 80 + "/img.jpg"
    rng = np.random.default_rng(3)
    data = _jpeg_bytes(rng, 16, 16)
    path = tmp_path / "long.tar"
    with tarfile.open(path, "w", format=tarfile.PAX_FORMAT) as tar:
        ti = tarfile.TarInfo(long_name)
        ti.size = len(data)
        tar.addfile(ti, io.BytesIO(data))
    buf = path.read_bytes()
    index = native_io.tar_index(buf)
    assert index is not None and len(index) == 1
    name, off, size = index[0]
    assert name == long_name
    assert buf[off : off + size] == data


def test_tar_index_gnu_long_names(tmp_path):
    long_name = "g" * 120 + "/img.bin"
    path = tmp_path / "gnu.tar"
    payload = b"\xff" * 100
    with tarfile.open(path, "w", format=tarfile.GNU_FORMAT) as tar:
        ti = tarfile.TarInfo(long_name)
        ti.size = len(payload)
        tar.addfile(ti, io.BytesIO(payload))
    index = native_io.tar_index(path.read_bytes())
    assert index is not None and len(index) == 1
    assert index[0][0] == long_name


def test_jpeg_batch_decode_matches_pil(jpeg_tar):
    from PIL import Image

    path, blobs = jpeg_tar
    buf = path.read_bytes()
    index = {n: (o, s) for n, o, s in native_io.tar_index(buf)}
    entries = [index[n] for n in blobs]
    images, ok = native_io.decode_jpeg_batch(buf, entries)
    assert ok == len(blobs)
    for img, data in zip(images, blobs.values()):
        ref = np.asarray(Image.open(io.BytesIO(data)).convert("RGB"), np.float32)
        assert img.shape == ref.shape
        # libjpeg and PIL share the decode path; allow tiny IDCT drift
        assert np.abs(img - ref).max() <= 1.0


def test_jpeg_batch_flags_corrupt_entry(jpeg_tar):
    path, blobs = jpeg_tar
    buf = bytearray(path.read_bytes())
    index = {n: (o, s) for n, o, s in native_io.tar_index(bytes(buf))}
    entries = [index[n] for n in blobs]
    # corrupt the second image's entropy data (past the SOI marker)
    off, size = entries[1]
    buf[off + size // 2 : off + size // 2 + 64] = b"\0" * 64
    images, ok = native_io.decode_jpeg_batch(bytes(buf), entries)
    assert ok >= len(blobs) - 1
    assert images[0] is not None


def test_load_images_from_tar_native_path(jpeg_tar):
    from keystone_tpu.loaders.image_loaders import load_images_from_tar

    path, blobs = jpeg_tar

    def label_fn(name):
        return name.split("/")[0] if name.endswith(".jpg") else None

    out = load_images_from_tar(str(path), label_fn)
    assert [n for n, _, _ in out] == list(blobs.keys())
    assert all(img.dtype == np.float32 and img.ndim == 3 for _, img, _ in out)
    assert [lab for _, _, lab in out] == ["cat", "cat", "dog", "dog"]


def test_cifar_native_matches_numpy():
    rng = np.random.default_rng(1)
    records = rng.integers(0, 256, (64, 3073), dtype=np.uint8)
    imgs, labels = native_io.parse_cifar(records)
    ref_labels = records[:, 0].astype(np.int32)
    ref_imgs = (
        records[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).astype(np.float32)
    )
    np.testing.assert_array_equal(labels, ref_labels)
    np.testing.assert_array_equal(imgs, ref_imgs)


def test_csv_native_matches_loadtxt(tmp_path):
    rng = np.random.default_rng(2)
    ref = rng.normal(size=(50, 7)).astype(np.float32)
    p = tmp_path / "m.csv"
    np.savetxt(p, ref, delimiter=",", fmt="%.6f")
    out = native_io.parse_csv(str(p))
    np.testing.assert_allclose(out, np.loadtxt(p, delimiter=",", dtype=np.float32), atol=1e-5)


def test_tokenize_ws_matches_split():
    text = "  the quick\nbrown\tfox  jumps \r\n over  "
    assert native_io.tokenize_ws(text) == text.split()
