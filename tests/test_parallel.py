"""Collectives + multi-host layers on the 8-device CPU mesh.

Mirrors the reference's approach of exercising 'distributed' semantics
in local mode (PipelineContext, SURVEY.md §4): every collective here
runs over 8 real (virtual CPU) devices, so psum/all_gather/shard layout
bugs surface without a pod.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from keystone_tpu.parallel import (
    DATA_AXIS,
    all_gather_rows,
    broadcast,
    co_sharded,
    current_mesh,
    dataset_from_process_local,
    global_data_mesh,
    init_multihost,
    reshard,
    tree_aggregate,
    tree_reduce_sum,
)
from keystone_tpu.parallel.mesh import shard_leading_axis


def test_tree_reduce_sum_matches_numpy():
    x = np.arange(64 * 5, dtype=np.float32).reshape(64, 5)
    xs = shard_leading_axis(jnp.asarray(x))
    got = tree_reduce_sum(xs)
    np.testing.assert_allclose(np.asarray(got), x.sum(axis=0), rtol=1e-6)


def test_tree_aggregate_moments():
    # the StandardScaler shape: per-shard (sum, sumsq, n) then psum
    x = np.random.default_rng(0).normal(size=(64, 3)).astype(np.float32)
    xs = shard_leading_axis(jnp.asarray(x))
    agg = tree_aggregate(
        xs,
        lambda rows: {
            "sum": rows.sum(axis=0),
            "sumsq": (rows * rows).sum(axis=0),
            "n": jnp.asarray(rows.shape[0], jnp.float32),
        },
    )
    np.testing.assert_allclose(np.asarray(agg["sum"]), x.sum(axis=0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(agg["sumsq"]), (x * x).sum(axis=0), rtol=1e-5)
    assert float(agg["n"]) == 64.0


def test_broadcast_is_replicated():
    w = jnp.ones((4, 4))
    wb = broadcast(w)
    assert wb.sharding.is_fully_replicated


def test_co_sharded_and_reshard():
    a = shard_leading_axis(jnp.ones((16, 2)))
    b = shard_leading_axis(jnp.zeros((16, 2)))
    assert co_sharded(a, b)
    rep = reshard(a, P())
    assert rep.sharding.is_fully_replicated
    assert not co_sharded(a, rep)


def test_all_gather_rows_replicates_full_axis():
    x = np.arange(32, dtype=np.float32).reshape(32, 1)
    xs = shard_leading_axis(jnp.asarray(x))
    g = all_gather_rows(xs)
    assert g.shape == (32, 1)
    assert g.sharding.is_fully_replicated
    np.testing.assert_array_equal(np.asarray(g), x)


def test_init_multihost_single_process_noop():
    assert init_multihost() == 1
    assert init_multihost() == 1  # idempotent


def test_global_data_mesh_axes():
    m = global_data_mesh()
    assert m.shape == {DATA_AXIS: 8}
    m2 = global_data_mesh(model_shards=2)
    assert m2.shape == {DATA_AXIS: 4, "model": 2}


def test_dataset_from_process_local_single_process():
    rows = np.arange(24, dtype=np.float32).reshape(12, 2)
    ds = dataset_from_process_local(rows, mesh=current_mesh())
    assert ds.count == 12
    np.testing.assert_array_equal(ds.numpy(), rows)
    # padded + sharded over data axis
    assert ds.array.sharding.spec == P(DATA_AXIS)


def test_solver_agrees_across_mesh_shapes():
    # the 'same program, different cluster size' property the reference
    # gets from partition-count independence: fitting on a 1-device vs
    # 8-device mesh must give the same model
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.learning import LinearMapEstimator
    from keystone_tpu.parallel.mesh import make_mesh, use_mesh

    rng = np.random.default_rng(1)
    X = rng.normal(size=(96, 6)).astype(np.float32)
    W = rng.normal(size=(6, 3)).astype(np.float32)
    Y = X @ W
    with use_mesh(make_mesh(jax.devices()[:1])):
        m1 = LinearMapEstimator(lam=0.0).fit(Dataset(X), Dataset(Y))
    m8 = LinearMapEstimator(lam=0.0).fit(Dataset(X), Dataset(Y))
    np.testing.assert_allclose(np.asarray(m1.W), np.asarray(m8.W), atol=1e-3)
