"""Collectives + multi-host layers on the 8-device CPU mesh.

Mirrors the reference's approach of exercising 'distributed' semantics
in local mode (PipelineContext, SURVEY.md §4): every collective here
runs over 8 real (virtual CPU) devices, so psum/all_gather/shard layout
bugs surface without a pod.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from keystone_tpu.parallel import (
    DATA_AXIS,
    all_gather_rows,
    broadcast,
    co_sharded,
    current_mesh,
    dataset_from_process_local,
    global_data_mesh,
    init_multihost,
    reshard,
    tree_aggregate,
    tree_reduce_sum,
)
from keystone_tpu.parallel.mesh import shard_leading_axis


def test_tree_reduce_sum_matches_numpy():
    x = np.arange(64 * 5, dtype=np.float32).reshape(64, 5)
    xs = shard_leading_axis(jnp.asarray(x))
    got = tree_reduce_sum(xs)
    np.testing.assert_allclose(np.asarray(got), x.sum(axis=0), rtol=1e-6)


def test_tree_aggregate_moments():
    # the StandardScaler shape: per-shard (sum, sumsq, n) then psum
    x = np.random.default_rng(0).normal(size=(64, 3)).astype(np.float32)
    xs = shard_leading_axis(jnp.asarray(x))
    agg = tree_aggregate(
        xs,
        lambda rows: {
            "sum": rows.sum(axis=0),
            "sumsq": (rows * rows).sum(axis=0),
            "n": jnp.asarray(rows.shape[0], jnp.float32),
        },
    )
    np.testing.assert_allclose(np.asarray(agg["sum"]), x.sum(axis=0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(agg["sumsq"]), (x * x).sum(axis=0), rtol=1e-5)
    assert float(agg["n"]) == 64.0


def test_collective_cache_fn_key():
    # distinct callbacks sharing a code object must NOT collide (defaults,
    # closures, value types); identical ones must (reuse, not recompile)
    from keystone_tpu.parallel.collectives import _fn_key

    def by_default(s):
        return lambda a, scale=s: a * scale

    def by_closure(s):
        return lambda a: a * s

    assert _fn_key(by_default(2.0)) != _fn_key(by_default(3.0))
    assert _fn_key(by_default(2.0)) == _fn_key(by_default(2.0))
    assert _fn_key(by_closure(2.0)) != _fn_key(by_closure(3.0))
    assert _fn_key(by_closure(2.0)) == _fn_key(by_closure(2.0))
    assert _fn_key(by_closure(1)) != _fn_key(by_closure(1.0))  # 1 == 1.0 but
    # traces to a different program

    class T:
        def m(self, x):
            return x

    t1, t2 = T(), T()
    assert _fn_key(t1.m) != _fn_key(t2.m)  # state lives on self


def test_broadcast_is_replicated():
    w = jnp.ones((4, 4))
    wb = broadcast(w)
    assert wb.sharding.is_fully_replicated


def test_co_sharded_and_reshard():
    a = shard_leading_axis(jnp.ones((16, 2)))
    b = shard_leading_axis(jnp.zeros((16, 2)))
    assert co_sharded(a, b)
    rep = reshard(a, P())
    assert rep.sharding.is_fully_replicated
    assert not co_sharded(a, rep)


def test_all_gather_rows_replicates_full_axis():
    x = np.arange(32, dtype=np.float32).reshape(32, 1)
    xs = shard_leading_axis(jnp.asarray(x))
    g = all_gather_rows(xs)
    assert g.shape == (32, 1)
    assert g.sharding.is_fully_replicated
    np.testing.assert_array_equal(np.asarray(g), x)


def test_init_multihost_single_process_noop():
    assert init_multihost() == 1
    assert init_multihost() == 1  # idempotent


def test_global_data_mesh_axes():
    m = global_data_mesh()
    assert m.shape == {DATA_AXIS: 8}
    m2 = global_data_mesh(model_shards=2)
    assert m2.shape == {DATA_AXIS: 4, "model": 2}


def test_dataset_from_process_local_single_process():
    rows = np.arange(24, dtype=np.float32).reshape(12, 2)
    ds = dataset_from_process_local(rows, mesh=current_mesh())
    assert ds.count == 12
    np.testing.assert_array_equal(ds.numpy(), rows)
    # padded + sharded over data axis
    assert ds.array.sharding.spec == P(DATA_AXIS)


def _mesh_2d(data_shards=4, model_shards=2):
    from keystone_tpu.parallel.mesh import make_mesh

    return make_mesh(
        jax.devices()[: data_shards * model_shards],
        shape=(data_shards, model_shards),
        axis_names=(DATA_AXIS, "model"),
    )


def test_dataset_feature_axis_sharded_on_2d_mesh():
    # (n, d) leaves shard d over 'model' — the library-level analog of
    # VectorSplitter feature blocking (SURVEY §2.7 row 2)
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.parallel.mesh import use_mesh

    X = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)
    with use_mesh(_mesh_2d()):
        ds = Dataset(X)
        assert ds.array.sharding.spec == P(DATA_AXIS, "model")
        # images (4-D) stay data-sharded / model-replicated
        imgs = Dataset(np.zeros((16, 4, 4, 3), np.float32))
        assert imgs.array.sharding.spec == P(DATA_AXIS)
    np.testing.assert_array_equal(ds.numpy(), X)


def test_bcd_on_2d_mesh():
    # fitting over a ('data','model') mesh must give the same model as a
    # single device: the tp sharding changes layout, not math
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
    from keystone_tpu.parallel.mesh import make_mesh, use_mesh

    rng = np.random.default_rng(7)
    X = rng.normal(size=(96, 24)).astype(np.float32)
    W = rng.normal(size=(24, 3)).astype(np.float32)
    Y = X @ W + 0.01 * rng.normal(size=(96, 3)).astype(np.float32)
    est = lambda: BlockLeastSquaresEstimator(block_size=8, num_iter=4, lam=0.1)
    with use_mesh(make_mesh(jax.devices()[:1])):
        m1 = est().fit(Dataset(X), Dataset(Y))
    with use_mesh(_mesh_2d()):
        m2d = est().fit(Dataset(X), Dataset(Y))
    np.testing.assert_allclose(np.asarray(m1.W), np.asarray(m2d.W), atol=2e-3)
    np.testing.assert_allclose(np.asarray(m1.b), np.asarray(m2d.b), atol=2e-3)


def _lbfgs_2d_mesh_stable() -> tuple:
    """Capability probe: does the 2d ('data','model') mesh L-BFGS path
    track the 1-device path numerically on this jax/backend? Some jax
    versions diverge from the very first iterations (the feature-axis
    sharding perturbs the line search, not a tolerance issue — observed
    max|ΔW| ≈ 0.4 at 3 iters where healthy platforms sit at float32
    noise). A 3-iteration micro-fit separates the two regimes cheaply."""
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.learning import DenseLBFGSwithL2
    from keystone_tpu.parallel.mesh import make_mesh, use_mesh

    rng = np.random.default_rng(3)
    X = rng.normal(size=(64, 16)).astype(np.float32)
    Y = X @ rng.normal(size=(16, 2)).astype(np.float32)
    with use_mesh(make_mesh(jax.devices()[:1])):
        m1 = DenseLBFGSwithL2(lam=0.5, num_iters=3).fit(Dataset(X), Dataset(Y))
    with use_mesh(_mesh_2d()):
        m2 = DenseLBFGSwithL2(lam=0.5, num_iters=3).fit(Dataset(X), Dataset(Y))
    dev = float(np.abs(np.asarray(m1.W) - np.asarray(m2.W)).max())
    return dev < 1e-2, dev


def test_exact_and_lbfgs_on_2d_mesh():
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.learning import DenseLBFGSwithL2, LinearMapEstimator
    from keystone_tpu.parallel.mesh import make_mesh, use_mesh

    stable, deviation = _lbfgs_2d_mesh_stable()
    if not stable:
        pytest.skip(
            "2d-mesh L-BFGS numerics diverge from the 1-device path on "
            f"this jax/backend (probe max|ΔW|={deviation:.3f} at 3 iters)"
        )
    rng = np.random.default_rng(3)
    X = rng.normal(size=(64, 16)).astype(np.float32)
    Y = (X @ rng.normal(size=(16, 2)).astype(np.float32))
    with use_mesh(make_mesh(jax.devices()[:1])):
        exact1 = LinearMapEstimator(lam=0.5).fit(Dataset(X), Dataset(Y))
        lbfgs1 = DenseLBFGSwithL2(lam=0.5, num_iters=15).fit(Dataset(X), Dataset(Y))
    with use_mesh(_mesh_2d()):
        exact2 = LinearMapEstimator(lam=0.5).fit(Dataset(X), Dataset(Y))
        lbfgs2 = DenseLBFGSwithL2(lam=0.5, num_iters=15).fit(Dataset(X), Dataset(Y))
    np.testing.assert_allclose(np.asarray(exact1.W), np.asarray(exact2.W), atol=2e-3)
    np.testing.assert_allclose(np.asarray(lbfgs1.W), np.asarray(lbfgs2.W), atol=2e-3)


def test_solver_agrees_across_mesh_shapes():
    # the 'same program, different cluster size' property the reference
    # gets from partition-count independence: fitting on a 1-device vs
    # 8-device mesh must give the same model
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.learning import LinearMapEstimator
    from keystone_tpu.parallel.mesh import make_mesh, use_mesh

    rng = np.random.default_rng(1)
    X = rng.normal(size=(96, 6)).astype(np.float32)
    W = rng.normal(size=(6, 3)).astype(np.float32)
    Y = X @ W
    with use_mesh(make_mesh(jax.devices()[:1])):
        m1 = LinearMapEstimator(lam=0.0).fit(Dataset(X), Dataset(Y))
    m8 = LinearMapEstimator(lam=0.0).fit(Dataset(X), Dataset(Y))
    np.testing.assert_allclose(np.asarray(m1.W), np.asarray(m8.W), atol=1e-3)
