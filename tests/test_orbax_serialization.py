"""Orbax checkpoint backend for FittedPipeline (save/load round-trip).

Runs in a SUBPROCESS with the axon PJRT plugin unregistered
(PALLAS_AXON_POOL_IPS removed): orbax's save path initializes every
registered jax backend, and through a wedged device tunnel that
initialization hangs forever — the suite must stay hermetic. The pickle
backend's in-process test lives in test_pipeline.py.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os
import sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
import numpy as np

from keystone_tpu.data.dataset import Dataset
from keystone_tpu.nodes.learning import LinearMapEstimator
from keystone_tpu.nodes.stats import StandardScaler
from keystone_tpu.parallel.mesh import make_mesh
from keystone_tpu.nodes.util import Identity
from keystone_tpu.workflow import FittedPipeline

out = sys.argv[1]
mesh = make_mesh()
rng = np.random.default_rng(0)
X = rng.normal(size=(64, 5)).astype(np.float32)
W = rng.normal(size=(5, 3)).astype(np.float32)
Y = X @ W

train = Dataset(X, mesh=mesh)
labels = Dataset(Y, mesh=mesh)
pipe = Identity().and_then(StandardScaler(), train).and_then(
    LinearMapEstimator(lam=1e-6), train, labels)
fitted = pipe.fit()
want = fitted(train).numpy()

path = out + "/fitted_orbax"
fitted.save(path, format="orbax")
assert os.path.isdir(path), path
assert os.path.exists(path + "/skeleton.pkl")
assert os.path.isdir(path + "/arrays"), "expected an orbax array ckpt"

loaded = FittedPipeline.load(path)
got = loaded(train).numpy()
np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

# single-datum path survives the round trip too
d_want = np.asarray(fitted(X[0]))
d_got = np.asarray(loaded(X[0]))
np.testing.assert_allclose(d_got, d_want, rtol=1e-5, atol=1e-5)

# unpickling the payload outside a load context must fail loudly
import pickle
wrapper = pickle.load(open(path + "/skeleton.pkl", "rb"))
assert wrapper["format"] == "keystone-orbax-v1"
assert wrapper["n_arrays"] > 0
try:
    pickle.loads(wrapper["payload"])
except RuntimeError as e:
    assert "load_pytree_orbax" in str(e)
else:
    raise AssertionError("bare payload unpickle should have raised")

# a torn save (sidecar id != skeleton id) must be rejected loudly
open(path + "/arrays_id.txt", "w").write("deadbeef")
try:
    FittedPipeline.load(path)
except RuntimeError as e:
    assert "torn" in str(e)
else:
    raise AssertionError("torn artifact should have raised")
open(path + "/arrays_id.txt", "w").write(wrapper["artifact_id"])

# a partial copy (missing arrays/) must be rejected loudly
import shutil
shutil.rmtree(path + "/arrays")
try:
    FittedPipeline.load(path)
except RuntimeError as e:
    assert "arrays" in str(e)
else:
    raise AssertionError("missing arrays dir should have raised")

print("ORBAX_OK")
"""


def test_orbax_roundtrip_subprocess(tmp_path):
    import importlib.util

    import jax

    # Capability probes mirroring what the WORKER script needs: the
    # jax_num_cpu_devices config knob (absent on jaxlib < 0.5 — the
    # worker would die in its first jax.config.update) and orbax itself.
    if not hasattr(jax.config, "jax_num_cpu_devices"):
        pytest.skip(
            "jax.config has no jax_num_cpu_devices option on this "
            "jax/jaxlib; the orbax worker cannot size its device mesh"
        )
    if importlib.util.find_spec("orbax") is None:
        pytest.skip("orbax is not installed")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-u", "-c", WORKER, str(tmp_path)],
        env=env, cwd=REPO, timeout=300, capture_output=True, text=True,
    )
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert "ORBAX_OK" in r.stdout
