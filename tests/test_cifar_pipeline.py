"""End-to-end RandomPatchCifar on the synthetic learnable task (north-star
pipeline, SURVEY.md §3.4), small config for the CPU mesh."""

from keystone_tpu.pipelines.random_patch_cifar import RandomPatchCifarConfig, run


def test_random_patch_cifar_end_to_end():
    result = run(
        RandomPatchCifarConfig(
            num_filters=64,
            sample_patches=10_000,
            synth_train=320,
            synth_test=80,
            microbatch=64,
            block_size=512,
        )
    )
    # the synthetic task is fully separable for a working pipeline
    assert result["test_accuracy"] > 0.9, result["summary"]


def test_cifar_binary_loader_roundtrip(tmp_path):
    import numpy as np

    from keystone_tpu.loaders.cifar_loader import cifar_loader

    rng = np.random.default_rng(0)
    n = 20
    labels = rng.integers(0, 10, n, dtype=np.uint8)
    images = rng.integers(0, 256, size=(n, 3, 32, 32), dtype=np.uint8)
    records = np.concatenate(
        [labels[:, None], images.reshape(n, -1)], axis=1
    )
    path = tmp_path / "data_batch_1.bin"
    records.tofile(path)
    data = cifar_loader(str(path))
    assert data.data.count == n
    np.testing.assert_array_equal(data.labels.numpy(), labels)
    # HWC conversion: channel-planar source
    np.testing.assert_allclose(
        data.data.numpy()[0][:, :, 0], images[0, 0].astype(np.float32)
    )
