"""End-to-end RandomPatchCifar on the synthetic learnable task (north-star
pipeline, SURVEY.md §3.4), small config for the CPU mesh."""

from keystone_tpu.pipelines.random_patch_cifar import RandomPatchCifarConfig, run


def test_random_patch_cifar_end_to_end():
    result = run(
        RandomPatchCifarConfig(
            num_filters=64,
            sample_patches=10_000,
            synth_train=320,
            synth_test=80,
            microbatch=64,
            block_size=512,
        )
    )
    # the synthetic task is fully separable for a working pipeline
    assert result["test_accuracy"] > 0.9, result["summary"]


def test_cifar_binary_loader_roundtrip(tmp_path):
    import numpy as np

    from keystone_tpu.loaders.cifar_loader import cifar_loader

    rng = np.random.default_rng(0)
    n = 20
    labels = rng.integers(0, 10, n, dtype=np.uint8)
    images = rng.integers(0, 256, size=(n, 3, 32, 32), dtype=np.uint8)
    records = np.concatenate(
        [labels[:, None], images.reshape(n, -1)], axis=1
    )
    path = tmp_path / "data_batch_1.bin"
    records.tofile(path)
    data = cifar_loader(str(path))
    assert data.data.count == n
    np.testing.assert_array_equal(data.labels.numpy(), labels)
    # HWC conversion: channel-planar source
    np.testing.assert_allclose(
        data.data.numpy()[0][:, :, 0], images[0, 0].astype(np.float32)
    )


def test_cifar_loader_on_checked_in_real_format_fixture():
    """100-record fixture in the EXACT CIFAR-10 binary layout (1 label
    byte + 3072 channel-planar bytes — CifarLoader.scala:21-51): record i
    has label i%10 and pixel value row*2 + label*10 + channel*5, so the
    loader's record framing, label extraction, and planar→HWC transpose
    are each pinned to known bytes (VERDICT r3 #6)."""
    import os

    import numpy as np

    from keystone_tpu.loaders.cifar_loader import cifar_loader

    path = os.path.join(os.path.dirname(__file__), "resources", "cifar_mini.bin")
    data = cifar_loader(path)
    assert data.data.count == 100
    labels = np.asarray(data.labels.numpy())
    np.testing.assert_array_equal(labels, np.arange(100) % 10)
    imgs = np.asarray(data.data.numpy())
    assert imgs.shape == (100, 32, 32, 3)
    # record 17 (label 7): channel c pixel at row r = r*2 + 70 + c*5
    r = np.arange(32)
    for c in range(3):
        want = np.clip(r * 2 + 7 * 10 + c * 5, 0, 255).astype(np.float32)
        np.testing.assert_array_equal(imgs[17, :, 5, c], want)


def test_random_patch_pipeline_on_real_images():
    """Fixture-scale REAL-image regression (VERDICT r1 item 2: real CIFAR
    binaries are unobtainable in this zero-egress env, so the full
    featurize+solve pipeline is exercised on natural-image statistics
    instead: 32x32 crops of two checked-in photographs, classified by
    source photo)."""
    import os

    import numpy as np
    from PIL import Image

    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.evaluation import MulticlassClassifierEvaluator
    from keystone_tpu.pipelines.random_patch_cifar import (
        RandomPatchCifarConfig,
        build_pipeline,
    )

    res = os.path.join(os.path.dirname(__file__), "resources")

    def crops(name):
        img = np.asarray(Image.open(os.path.join(res, name)).convert("RGB"),
                         np.float32)
        h, w = img.shape[:2]
        out = [
            img[y : y + 32, x : x + 32]
            for y in range(0, h - 32, 32)
            for x in range(0, w - 32, 32)
        ]
        return np.stack(out)

    a, b = crops("gantrycrane.png"), crops("000012.jpg")
    X = np.concatenate([a, b])
    y = np.concatenate([np.zeros(len(a), np.int32), np.ones(len(b), np.int32)])
    rng = np.random.default_rng(0)
    order = rng.permutation(len(X))
    X, y = X[order], y[order]
    cut = int(len(X) * 0.8)

    class _Split:
        def __init__(self, X, y):
            self.data = Dataset(X)
            self.labels = Dataset(y)

    train, test = _Split(X[:cut], y[:cut]), _Split(X[cut:], y[cut:])
    config = RandomPatchCifarConfig(
        num_filters=32, num_classes=2, sample_patches=5_000, microbatch=64,
        block_size=256,
    )
    predictor = build_pipeline(train, config)
    ev = MulticlassClassifierEvaluator(2)
    acc = ev(predictor(test.data), test.labels).accuracy
    assert acc > 0.85, f"real-image crop classification accuracy {acc}"


def test_calibrated_difficulty_accuracy_band():
    """VERDICT r2 #2: the synthetic task at the bench's calibrated
    difficulty (noise=1.2, confusion=0.6) must land test accuracy in a
    nontrivial band — a solver-quality regression (broken centering, BCD
    convergence, precision) drops below it; an accidentally-trivialized
    generator saturates above it. Calibration measured 0.797 at this
    exact config (n=2000, 128 filters, seed 0; chance = 0.10)."""
    from keystone_tpu.evaluation import MulticlassClassifierEvaluator
    from keystone_tpu.loaders.cifar_loader import synthetic_cifar
    from keystone_tpu.pipelines.random_patch_cifar import (
        RandomPatchCifarConfig,
        build_pipeline,
    )
    from keystone_tpu.workflow import PipelineEnv

    PipelineEnv.reset()
    train, test = synthetic_cifar(2000, 1000, seed=0, noise=1.2, confusion=0.6)
    pred = build_pipeline(train, RandomPatchCifarConfig(num_filters=128))
    acc = MulticlassClassifierEvaluator(10)(pred(test.data), test.labels).accuracy
    assert 0.68 <= acc <= 0.92, f"accuracy {acc} left the calibrated band"


def test_run_fused_matches_pipeline_path():
    """`run_fused` collapses the whole fit (filters → featurize → scaler
    → single-block ridge → eval) into ONE traced program; with
    block_size ≥ d and num_iter=1 it must reproduce the pipeline path's
    accuracy exactly (the scaler fold is a linear reparameterization,
    not an approximation)."""
    from keystone_tpu.evaluation import MulticlassClassifierEvaluator
    from keystone_tpu.loaders.cifar_loader import synthetic_cifar
    from keystone_tpu.pipelines.random_patch_cifar import (
        RandomPatchCifarConfig,
        build_pipeline,
        run_fused,
    )
    from keystone_tpu.workflow import PipelineEnv

    train, test = synthetic_cifar(1000, 500, seed=0, noise=1.2, confusion=0.6)
    config = RandomPatchCifarConfig(num_filters=64)
    res = run_fused(train, test, config)

    PipelineEnv.reset()
    ev = MulticlassClassifierEvaluator(10)
    predictor = build_pipeline(train, config)
    acc = ev(predictor(test.data), test.labels).accuracy
    assert abs(res["test_accuracy"] - acc) < 0.02, (res["test_accuracy"], acc)
    assert res["train_error"] < 0.2


def test_run_fused_multiblock_matches_pipeline():
    """The fused path calls the SAME _bcd_fit_impl as the pipeline's
    BlockLeastSquaresEstimator, so it must agree even when block_size <
    d (multi-block coordinate descent, not a single ridge solve)."""
    from keystone_tpu.evaluation import MulticlassClassifierEvaluator
    from keystone_tpu.loaders.cifar_loader import synthetic_cifar
    from keystone_tpu.pipelines.random_patch_cifar import (
        RandomPatchCifarConfig,
        build_pipeline,
        run_fused,
    )
    from keystone_tpu.workflow import PipelineEnv

    train, test = synthetic_cifar(600, 300, seed=1, noise=1.2, confusion=0.6)
    # d = 2·2·2·32 = 256 features; block_size=64 -> 4 BCD blocks
    config = RandomPatchCifarConfig(num_filters=32, block_size=64)
    res = run_fused(train, test, config)

    PipelineEnv.reset()
    ev = MulticlassClassifierEvaluator(10)
    predictor = build_pipeline(train, config)
    acc = ev(predictor(test.data), test.labels).accuracy
    assert abs(res["test_accuracy"] - acc) < 0.02, (res["test_accuracy"], acc)


def test_fused_conv_vmem_accounting_lane_padding():
    """The fused conv kernel's VMEM block chooser must lane-pad k to 128
    (Mosaic pads the minor dim): ignoring it produced a real scoped-vmem
    OOM at k=16 on v5e (21.5 MB actual vs 8.9 MB estimated)."""
    from keystone_tpu.ops.pallas_kernels import _fused_conv_block_images

    # CIFAR geometry: 27x27 valid conv -> posp=736, dp=128, cells=4
    b16 = _fused_conv_block_images(736, 128, 16, 4)
    b256 = _fused_conv_block_images(736, 128, 256, 4)
    # k=16 must be budgeted like k=64 (lane padding: kp=128 and k2p=128
    # for both — the pre-fix unpadded budget OOM'd live at k=16: 21.5 MB
    # actual vs 8.9 MB estimated). With the per-image sequential pool
    # loop the z/act transients no longer scale with the block, so the
    # block is much larger than the block-diagonal design's 8/4.
    b64 = _fused_conv_block_images(736, 128, 64, 4)
    assert b16 == b64 == 22, (b16, b64)
    assert b256 == 14, b256


def _load_bench():
    """Import bench.py as a module (it lives at the repo root, outside
    the package)."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_mod",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"),
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def test_bench_band_gate():
    """bench.py's record gate: out-of-band accuracy is marked as an
    error and never persists as the stale-fallback record; in-band TPU
    runs persist; CPU runs never persist."""
    bench = _load_bench()

    base = {"images_per_sec": 1000.0, "test_accuracy": 0.85,
            "accuracy_band": [0.72, 0.96], "platform": "tpu"}
    rec, persist = bench.finalize_record(dict(base, accuracy_in_band=True))
    assert persist and "error" not in rec

    rec, persist = bench.finalize_record(
        dict(base, test_accuracy=0.3, accuracy_in_band=False))
    assert not persist and "below calibrated lower bound" in rec["error"]

    rec, persist = bench.finalize_record(
        dict(base, platform="cpu", accuracy_in_band=True))
    assert not persist

    # legacy records (no band fields) still pass through and persist
    rec, persist = bench.finalize_record(
        {"images_per_sec": 500.0, "platform": "tpu"})
    assert persist and "error" not in rec

    # real-data records gate on the north star, not the synthetic band
    real = {"images_per_sec": 1000.0, "test_accuracy": 0.80,
            "accuracy_band": None, "synthetic": False, "platform": "tpu",
            "north_star": {"target_accuracy": 0.84, "accuracy_ok": False},
            "accuracy_in_band": False}
    rec, persist = bench.finalize_record(real)
    assert not persist and "north-star target 0.84" in rec["error"]

    rec, persist = bench.finalize_record(
        dict(real, test_accuracy=0.9, accuracy_in_band=True,
             north_star={"target_accuracy": 0.84, "accuracy_ok": True}))
    assert persist and "error" not in rec


def test_bench_partial_record_ranking():
    """The parent's best-partial selection across retry attempts: a
    later-tier checkpoint (e.g. krr_tier, everything measured except the
    fused tier) must beat an earlier-tier one from another attempt, ties
    go to the newer attempt, and unknown progress values rank lowest."""
    bench = _load_bench()

    d_head = {"progress": "headline", "attempt": 1}
    d_krr = {"progress": "krr_tier", "attempt": 2}
    d_head2 = {"progress": "headline", "attempt": 3}
    d_unknown = {"progress": "someday_tier", "attempt": 4}

    best = bench.pick_better_partial(None, d_head)
    assert best is d_head
    best = bench.pick_better_partial(best, d_krr)
    assert best is d_krr
    # an earlier-tier checkpoint from a later attempt must NOT displace it
    best = bench.pick_better_partial(best, d_head2)
    assert best is d_krr
    # unknown progress ranks 0 and never displaces a ranked one
    best = bench.pick_better_partial(best, d_unknown)
    assert best is d_krr
    # same-tier tie goes to the newer attempt
    d_krr2 = {"progress": "krr_tier", "attempt": 5}
    assert bench.pick_better_partial(d_krr, d_krr2) is d_krr2
    # every tier the child emits is ranked (completeness ordering)
    emitted = ["headline", "staged", "flagship", "featurize_tier",
               "krr_tier", "overlap_tier", "complete"]
    ranks = [bench.PROGRESS_RANK[p] for p in emitted]
    assert ranks == sorted(ranks) and len(set(ranks)) == len(ranks)


def test_bench_tier_errors_surface_and_never_persist():
    """A record whose tier payload carries {"error": ...} (the child's
    failure-isolated tiers) must surface the failure top-level and never
    persist as the stale-fallback record, even in-band on TPU."""
    bench = _load_bench()

    base = {"images_per_sec": 1000.0, "test_accuracy": 0.85,
            "accuracy_band": [0.72, 0.96], "platform": "tpu",
            "accuracy_in_band": True,
            "flagship_bcd_d8192": {"error": "RuntimeError: boom"},
            "flagship_krr": {"fit_seconds": 1.0}}
    rec, persist = bench.finalize_record(base)
    assert not persist
    assert "flagship_bcd_d8192" in rec["error"] and "boom" in rec["error"]
    # healthy tiers still persist
    ok = dict(base, flagship_bcd_d8192={"fit_seconds": 1.0})
    rec, persist = bench.finalize_record(ok)
    assert persist and "error" not in rec


def test_bench_tier_error_scan_ignores_informational_payloads():
    """The error scan is restricted to the known tier keys: a future
    informational dict that happens to carry an "error" field (e.g. a
    diagnostics payload) must NOT block persistence — only real tier
    payloads gate the record."""
    bench = _load_bench()

    base = {"images_per_sec": 1000.0, "test_accuracy": 0.85,
            "accuracy_band": [0.72, 0.96], "platform": "tpu",
            "accuracy_in_band": True,
            # informational payloads with an embedded "error" field
            "tunnel_diagnostics": {"error": "transient wedge at 03:12"},
            "north_star": {"target_accuracy": 0.84, "accuracy_ok": True,
                           "error": "informational only"},
            # healthy real tiers
            "flagship_krr": {"fit_seconds": 1.0},
            "featurize_overlap": {"serial_seconds": 2.0,
                                  "overlapped_seconds": 1.0}}
    rec, persist = bench.finalize_record(base)
    assert persist and "error" not in rec
    # a real tier key carrying an error still gates
    bad = dict(base, featurize_overlap={"error": "ValueError: nope"})
    rec, persist = bench.finalize_record(bad)
    assert not persist and "featurize_overlap" in rec["error"]
    # every gating key the child can emit is covered by the scan list
    assert set(bench.TIER_KEYS) == {
        "flagship_bcd_d8192", "flagship_featurize", "flagship_krr",
        "featurize_overlap", "dispatch_count", "telemetry_overhead",
        "serving_qps", "out_of_core", "compile_count", "fused"}
