"""Pallas kernel correctness (interpret mode on CPU) and fusion peephole.

The reference implementations (`*_reference`) are the XLA paths the
dispatchers use off-TPU; the Pallas kernels must match them bit-for-bit
in structure and numerically to f32 tolerance. The peephole test mirrors
the reference's single-vs-batch parity style (PipelineSuite): the fused
RectifyPool stage must equal running SymmetricRectifier then Pooler
stage-by-stage.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.ops import (
    rbf_block,
    rbf_block_pallas,
    rbf_block_reference,
    rectify_pool,
    rectify_pool_pallas,
    rectify_pool_reference,
)


@pytest.mark.parametrize(
    "n,h,w,k,pool,stride,alpha,max_val",
    [
        (3, 27, 27, 16, 14, 13, 0.25, 0.0),  # CIFAR north-star geometry
        (5, 12, 12, 8, 4, 4, 0.0, 0.0),  # non-overlapping windows
        (2, 10, 14, 4, 5, 3, 0.1, 0.05),  # rectangular, overlap, floor
    ],
)
def test_rectify_pool_pallas_matches_reference(n, h, w, k, pool, stride, alpha, max_val):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, h, w, k)).astype(np.float32))
    want = rectify_pool_reference(x, alpha, max_val, pool, stride)
    got = rectify_pool_pallas(
        x, alpha, max_val, pool, stride, block_n=2, interpret=True
    )
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize(
    "m,n,d",
    [
        (70, 33, 50),  # forces padding on every axis
        (128, 128, 128),  # exactly tiled
        (9, 200, 513),  # k-loop with ragged last step
    ],
)
def test_rbf_block_pallas_matches_reference(m, n, d):
    rng = np.random.default_rng(1)
    X = jnp.asarray(rng.normal(size=(m, d)).astype(np.float32))
    Y = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    gamma = 0.07
    want = rbf_block_reference(X, Y, gamma)
    got = rbf_block_pallas(X, Y, gamma, bm=64, bn=128, bk=256, interpret=True)
    assert got.shape == (m, n)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_dispatchers_fall_back_off_tpu():
    # on the CPU test mesh the dispatcher must route to the XLA path and
    # agree with it exactly
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 8, 8, 4)).astype(np.float32))
    np.testing.assert_array_equal(
        np.asarray(rectify_pool(x, 0.1, 0.0, 4, 2)),
        np.asarray(rectify_pool_reference(x, 0.1, 0.0, 4, 2)),
    )
    X = jnp.asarray(rng.normal(size=(6, 5)).astype(np.float32))
    Y = jnp.asarray(rng.normal(size=(3, 5)).astype(np.float32))
    np.testing.assert_array_equal(
        np.asarray(rbf_block(X, Y, 0.3)), np.asarray(rbf_block_reference(X, Y, 0.3))
    )


def test_fusion_peephole_matches_stagewise():
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.images.core import Pooler, SymmetricRectifier
    from keystone_tpu.nodes.util.fusion import FusedBatchTransformer, _peephole

    rng = np.random.default_rng(3)
    imgs = rng.normal(size=(16, 27, 27, 8)).astype(np.float32)
    rect = SymmetricRectifier(alpha=0.25)
    pool = Pooler(13, 14, pool_fn="sum")

    stages = _peephole([rect, pool])
    assert len(stages) == 1 and type(stages[0]).__name__ == "_RectifyPoolStage"
    # max-pool / pixel_fn poolers must NOT be fused
    assert len(_peephole([rect, Pooler(13, 14, pool_fn="max")])) == 2

    data = Dataset(imgs)
    fused_out = FusedBatchTransformer([rect, pool], microbatch=8).apply_batch(data)
    want = pool.apply_batch(rect.apply_batch(data))
    np.testing.assert_allclose(
        fused_out.numpy(), want.numpy(), rtol=1e-5, atol=1e-5
    )


def test_krr_still_learns_with_static_gamma():
    # XOR learnability, mirroring the reference KernelModelSuite.scala:13-39
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.learning.kernels import KernelRidgeRegression

    rng = np.random.default_rng(4)
    X = rng.uniform(-1, 1, size=(256, 2)).astype(np.float32)
    y = np.where(X[:, 0] * X[:, 1] > 0, 1.0, -1.0).astype(np.float32)[:, None]
    model = KernelRidgeRegression(gamma=4.0, lam=1e-3, block_size=64).fit(
        Dataset(X), Dataset(y)
    )
    preds = np.sign(model.apply_batch(Dataset(X)).numpy()[:, 0])
    assert (preds == y[:, 0]).mean() > 0.95


@pytest.mark.parametrize(
    "n,h,w,c,patch,k,pool,stride,normalize",
    [
        (5, 32, 32, 3, 6, 32, 14, 13, True),   # CIFAR north-star geometry
        (3, 16, 16, 1, 5, 16, 6, 6, False),    # gray, no normalization
        (2, 20, 14, 2, 3, 8, 5, 4, True),      # rectangular
        (3, 16, 16, 1, 2, 8, 5, 5, False),     # npos=225: 16-alignment
        # padding of the patch rows; cells=9 > 8: padded output groups
        (5, 12, 12, 1, 3, 8, 10, 10, True),    # cells=1: g=8 grouping
        (3, 12, 10, 2, 3, 8, 8, 2, False),     # cells=2 (1x2): g=4
    ],
)
def test_conv_rectify_pool_pallas_matches_reference(
    n, h, w, c, patch, k, pool, stride, normalize
):
    """Fused conv+rectify+pool kernel vs the exact XLA path. The kernel
    feeds the MXU bf16 patches (what DEFAULT-precision f32 matmuls
    truncate to anyway); on CPU interpret mode the dot is genuinely
    bf16, so the tolerance covers bf16 product rounding."""
    from keystone_tpu.ops import (
        conv_rectify_pool_pallas,
        conv_rectify_pool_reference,
    )

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.random(size=(n, h, w, c)).astype(np.float32))
    kern = jnp.asarray(
        rng.normal(size=(patch, patch, c, k)).astype(np.float32)
    )
    colsum = jnp.asarray(rng.normal(size=(k,)).astype(np.float32))
    bias = jnp.asarray(rng.normal(size=(k,)).astype(np.float32))
    alpha, max_val = 0.25, 0.0

    want = conv_rectify_pool_reference(
        x, kern, colsum, bias, alpha, max_val, pool, stride, normalize
    )
    g_cmajor = jnp.asarray(
        np.asarray(kern).transpose(2, 0, 1, 3).reshape(-1, k)
    )
    got = conv_rectify_pool_pallas(
        x, g_cmajor, colsum, bias, alpha, max_val, pool, stride,
        normalize, patch, interpret=True,
    )
    assert got.shape == want.shape
    scale = float(jnp.abs(want).max())
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-2 * scale
    )


def test_conv_fusion_peephole_matches_stagewise():
    """The _ConvRectifyPoolStage peephole (off-TPU: reference path) must
    equal running Convolver, SymmetricRectifier, Pooler stage-by-stage
    through a FusedBatchTransformer."""
    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.images.core import (
        Convolver,
        Pooler,
        SymmetricRectifier,
    )
    from keystone_tpu.nodes.util.fusion import FusedBatchTransformer, _peephole

    rng = np.random.default_rng(2)
    imgs = rng.random(size=(6, 16, 16, 3)).astype(np.float32)
    filters = rng.normal(size=(8, 5 * 5 * 3)).astype(np.float32)
    conv = Convolver(filters, 16, 16, 3, normalize_patches=True)
    rect = SymmetricRectifier(alpha=0.1)
    pool = Pooler(4, 5, pool_fn="sum")  # distinct stride/size: catches transposition

    stages = [conv, rect, pool]
    merged = _peephole(stages)
    assert len(merged) == 1, [type(s).__name__ for s in merged]

    fused = FusedBatchTransformer(stages, microbatch=4)
    got = fused.apply_batch(Dataset(imgs)).numpy()

    want = imgs
    want = np.asarray(conv.batch_fn()(jnp.asarray(want)))
    want = np.asarray(rect.batch_fn()(jnp.asarray(want)))
    want = np.asarray(pool.batch_fn()(jnp.asarray(want)))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_conv_fused_stage_ineligible_fallback_reconstructs_hwio(monkeypatch):
    """When the Pallas block geometry can't fit VMEM the fused stage must
    fall back to the reference conv with a correctly reconstructed HWIO
    kernel (inverse of the channel-major packing)."""
    from keystone_tpu.nodes.images.core import Convolver, Pooler, SymmetricRectifier
    from keystone_tpu.nodes.util.fusion import _ConvRectifyPoolStage

    rng = np.random.default_rng(3)
    imgs = jnp.asarray(rng.random(size=(4, 16, 16, 3)).astype(np.float32))
    filters = rng.normal(size=(8, 5 * 5 * 3)).astype(np.float32)
    conv = Convolver(filters, 16, 16, 3, normalize_patches=True)
    stage = _ConvRectifyPoolStage(conv, 0.1, 0.0, 5, 4)

    # force the fused path on and make the geometry ineligible
    monkeypatch.setattr("keystone_tpu.ops.use_fused_conv", lambda: True)
    monkeypatch.setattr(
        "keystone_tpu.ops.pallas_kernels.use_fused_conv", lambda: True
    )
    monkeypatch.setattr(
        "keystone_tpu.ops.pallas_kernels._fused_conv_geometry",
        lambda *a, **k: (0, 1, 8),
    )
    key, params, fn = stage.fuse()
    assert key[-1] is True  # fused flag baked into the program key
    got = np.asarray(fn(params, imgs))

    from keystone_tpu.ops import conv_rectify_pool_reference

    want = np.asarray(
        conv_rectify_pool_reference(
            imgs, conv.kernel, conv.colsum, conv.bias, 0.1, 0.0, 5, 4, True
        )
    )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_fused_conv_canary_demotes_compile_failures(monkeypatch):
    """A kernel geometry whose COMPILE fails (the class a trace-time
    try/except inside an outer jit cannot see) must be demoted to the
    XLA path by the eager per-geometry canary — retried once (transient
    device blips must not demote a geometry forever), then cached as a
    permanent verdict."""
    import keystone_tpu.ops.pallas_kernels as pk

    rng = np.random.default_rng(5)
    imgs = jnp.asarray(rng.random(size=(3, 16, 16, 3)).astype(np.float32))
    kern = jnp.asarray(rng.normal(size=(5, 5, 3, 8)).astype(np.float32))
    colsum = jnp.asarray(rng.normal(size=(8,)).astype(np.float32))
    bias = jnp.asarray(rng.normal(size=(8,)).astype(np.float32))

    calls = {"n": 0}

    def boom(*a, **k):
        calls["n"] += 1
        raise RuntimeError("Mosaic scoped-vmem OOM (simulated)")

    monkeypatch.setattr(pk, "use_fused_conv", lambda: True)
    monkeypatch.setattr(pk, "conv_rectify_pool_pallas", boom)
    monkeypatch.setattr(pk, "_fused_conv_canary", {})

    want = np.asarray(pk.conv_rectify_pool_reference(
        imgs, kern, colsum, bias, 0.1, 0.0, 5, 4, True))
    # call 1: attempt; call 2: retry-once; call 3: cached permanent False
    for _ in range(3):
        got = np.asarray(pk.conv_rectify_pool(
            imgs, kern, colsum, bias, 0.1, 0.0, 5, 4, True))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    assert calls["n"] == 2, calls["n"]

    # a transient failure then a recovery: second attempt enables the path
    pk._fused_conv_canary.clear()
    calls["n"] = 0
    real_pallas = [boom]

    def flaky(*a, **kw):
        fn, real_pallas[0] = real_pallas[0], ok_pallas
        return fn(*a, **kw)

    def ok_pallas(*a, **kw):
        calls["n"] += 1
        return jnp.asarray(want)

    monkeypatch.setattr(pk, "conv_rectify_pool_pallas", flaky)
    got = np.asarray(pk.conv_rectify_pool(
        imgs, kern, colsum, bias, 0.1, 0.0, 5, 4, True))  # canary fails
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    got = np.asarray(pk.conv_rectify_pool(
        imgs, kern, colsum, bias, 0.1, 0.0, 5, 4, True))  # retry passes
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    assert pk._fused_conv_canary and list(
        pk._fused_conv_canary.values()) == [True]


def test_fused_conv_canary_multihost_verdict_is_broadcast(monkeypatch):
    """In a multi-process job a transient blip can hit only SOME hosts,
    leaving them with different local canary verdicts and therefore
    divergent compiled programs in a collective launch. With
    process_count > 1 every process must adopt process 0's verdict
    (broadcast), with no per-process transient-retry marker. (The
    single-process retry fallback is covered by
    test_fused_conv_canary_demotes_compile_failures.)"""
    import jax
    from jax.experimental import multihost_utils

    import keystone_tpu.ops.pallas_kernels as pk

    rng = np.random.default_rng(6)
    imgs = jnp.asarray(rng.random(size=(2, 16, 16, 3)).astype(np.float32))
    kern = jnp.asarray(rng.normal(size=(5, 5, 3, 8)).astype(np.float32))
    colsum = jnp.asarray(rng.normal(size=(8,)).astype(np.float32))
    bias = jnp.asarray(rng.normal(size=(8,)).astype(np.float32))

    calls = {"n": 0}
    broadcasts = []

    def boom(*a, **k):
        calls["n"] += 1
        raise RuntimeError("transient blip (simulated)")

    def fake_broadcast(x):
        # this process plays the non-0 host: process 0's verdict (False
        # here — it also failed) comes back regardless of local state
        broadcasts.append(bool(np.asarray(x)))
        return np.asarray(False)

    monkeypatch.setattr(pk, "use_fused_conv", lambda: True)
    monkeypatch.setattr(pk, "conv_rectify_pool_pallas", boom)
    monkeypatch.setattr(pk, "_fused_conv_canary", {})
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        multihost_utils, "broadcast_one_to_all", fake_broadcast)

    want = np.asarray(pk.conv_rectify_pool_reference(
        imgs, kern, colsum, bias, 0.1, 0.0, 5, 4, True))
    for _ in range(3):
        got = np.asarray(pk.conv_rectify_pool(
            imgs, kern, colsum, bias, 0.1, 0.0, 5, 4, True))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    # ONE local attempt, ONE broadcast, then a permanent cached verdict
    # — never the transient retry marker that made verdicts process-local
    assert calls["n"] == 1, calls["n"]
    assert broadcasts == [False]
    assert list(pk._fused_conv_canary.values()) == [False]

    # a host whose local canary PASSES must still adopt process 0's
    # failing verdict (the divergence the broadcast exists to close)
    pk._fused_conv_canary.clear()
    monkeypatch.setattr(pk, "conv_rectify_pool_pallas",
                        lambda *a, **k: jnp.zeros((2, 2, 2, 8)))
    got = np.asarray(pk.conv_rectify_pool(
        imgs, kern, colsum, bias, 0.1, 0.0, 5, 4, True))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    assert broadcasts[-1] is True  # local verdict was pass...
    assert list(pk._fused_conv_canary.values()) == [False]  # ...p0 wins
