"""Test harness.

Mirrors the reference's `PipelineContext` trait
(src/test/scala/workflow/PipelineContext.scala:9-26): where the reference
runs every "distributed" test on local-mode Spark, we run on a virtual
8-device CPU mesh (XLA host-platform device-count override), exercising
the full shard/collective code path in one process. Each test resets the
process-global `PipelineEnv` so prefix-memoized fitted state cannot leak
between tests.
"""

import os

# Must be set before jax initializes its backends.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def clean_pipeline_env():
    from keystone_tpu.workflow.env import PipelineEnv
    from keystone_tpu.parallel.mesh import reset_default_mesh

    PipelineEnv.reset()
    reset_default_mesh()
    yield
    PipelineEnv.reset()
    reset_default_mesh()
