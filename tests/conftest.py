"""Test harness.

Mirrors the reference's `PipelineContext` trait
(src/test/scala/workflow/PipelineContext.scala:9-26): where the reference
runs every "distributed" test on local-mode Spark, we run on a virtual
8-device CPU mesh, exercising the full shard/collective code path in one
process. Each test resets the process-global `PipelineEnv` so
prefix-memoized fitted state cannot leak between tests.

Platform forcing uses `jax.config` (not env vars): pytest plugins may
import jax before this conftest runs, at which point XLA_FLAGS /
JAX_PLATFORMS are ignored — config updates still work until a backend is
actually initialized.
"""

import os

# Harmless belt-and-braces for subprocesses spawned by tests.
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jaxlib (< 0.5) has no jax_num_cpu_devices config knob; the
    # XLA flag is read at backend initialization, which hasn't happened
    # yet if the session fixture below can still assert the mesh.
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def assert_cpu_mesh():
    devs = jax.devices()
    assert devs[0].platform == "cpu" and len(devs) == 8, (
        f"tests require the 8-device CPU mesh, got {devs}; "
        "a plugin initialized a jax backend before conftest could configure it"
    )
    yield


def pytest_runtest_logreport(report):
    """Record environment-probe skips (jax_num_cpu_devices config knob,
    orbax presence, 2d-mesh L-BFGS numerics — and any future probe) as
    telemetry capability metadata, so a trace/bench artifact produced
    from this process states WHICH capabilities were absent for the run
    instead of silently carrying fewer measurements."""
    if report.when in ("setup", "call") and report.skipped:
        try:
            from keystone_tpu.telemetry import record_capability

            reason = ""
            if isinstance(report.longrepr, tuple) and len(report.longrepr) == 3:
                reason = str(report.longrepr[2])
                if reason.startswith("Skipped: "):
                    reason = reason[len("Skipped: "):]
            record_capability(report.nodeid, False, reason)
        except Exception:
            pass  # telemetry bookkeeping must never fail a test run


@pytest.fixture(autouse=True)
def clean_pipeline_env():
    from keystone_tpu.workflow.env import PipelineEnv
    from keystone_tpu.parallel.mesh import reset_default_mesh

    PipelineEnv.reset()
    reset_default_mesh()
    yield
    PipelineEnv.reset()
    reset_default_mesh()
