"""Numerical solver tests (model: reference BlockWeightedLeastSquaresSuite
zero-gradient checks, LBFGSSuite dense ± intercept, PCASuite patterns).

All run on the 8-virtual-device CPU mesh so Gram reductions exercise the
cross-shard all-reduce path.
"""

import numpy as np
import pytest

from keystone_tpu import Dataset
from keystone_tpu.nodes.learning import (
    BlockLeastSquaresEstimator,
    DenseLBFGSwithL2,
    LeastSquaresEstimator,
    LinearMapEstimator,
    LocalLeastSquaresEstimator,
)
from keystone_tpu.nodes.stats import StandardScaler


def ridge_closed_form(X, Y, lam, intercept=True):
    if intercept:
        xm, ym = X.mean(0), Y.mean(0)
        Xc, Yc = X - xm, Y - ym
    else:
        Xc, Yc = X, Y
    W = np.linalg.solve(Xc.T @ Xc + lam * np.eye(X.shape[1]), Xc.T @ Yc)
    b = (ym - xm @ W) if intercept else np.zeros(Y.shape[1])
    return W, b


@pytest.fixture
def problem():
    rng = np.random.default_rng(42)
    n, d, k = 200, 24, 3
    X = rng.normal(size=(n, d)).astype(np.float32)
    Wtrue = rng.normal(size=(d, k)).astype(np.float32)
    Y = (X @ Wtrue + 0.01 * rng.normal(size=(n, k)) + 1.5).astype(np.float32)
    return X, Y


def test_linear_map_estimator_matches_closed_form(problem):
    X, Y = problem
    lam = 2.0
    est = LinearMapEstimator(lam=lam, fit_intercept=True)
    model = est.fit(Dataset(X), Dataset(Y))
    Wref, bref = ridge_closed_form(X, Y, lam)
    np.testing.assert_allclose(np.asarray(model.W), Wref, atol=2e-2, rtol=1e-2)
    np.testing.assert_allclose(np.asarray(model.b), bref, atol=2e-2, rtol=1e-2)


def test_linear_map_estimator_padding_invariance(problem):
    """197 rows over 8 shards pads to 200; result must match unpadded."""
    X, Y = problem
    m = 197
    model_padded = LinearMapEstimator(1.0).fit(Dataset(X[:m]), Dataset(Y[:m]))
    Wref, bref = ridge_closed_form(X[:m], Y[:m], 1.0)
    np.testing.assert_allclose(np.asarray(model_padded.W), Wref, atol=2e-2, rtol=1e-2)


def test_block_ls_single_block_equals_exact(problem):
    X, Y = problem
    lam = 1.0
    exact = LinearMapEstimator(lam).fit(Dataset(X), Dataset(Y))
    block = BlockLeastSquaresEstimator(block_size=24, num_iter=1, lam=lam).fit(
        Dataset(X), Dataset(Y)
    )
    pred_e = np.asarray(exact.W)
    pred_b = np.asarray(block.W)[: pred_e.shape[0]]
    np.testing.assert_allclose(pred_b, pred_e, atol=5e-3, rtol=1e-2)


def test_block_ls_converges_with_blocks(problem):
    """Multi-block BCD approaches the exact ridge solution; gradient → 0
    (the reference's zero-gradient check,
    BlockWeightedLeastSquaresSuite.scala:142-166)."""
    X, Y = problem
    lam = 1.0
    est = BlockLeastSquaresEstimator(block_size=8, num_iter=20, lam=lam)
    model = est.fit(Dataset(X), Dataset(Y))
    W = np.asarray(model.W)[: X.shape[1]]
    b = np.asarray(model.b)
    # gradient of 0.5||XW+b-Y||^2 + 0.5 lam ||W||^2 wrt W (centered form)
    xm, ym = X.mean(0), Y.mean(0)
    Xc, Yc = X - xm, Y - ym
    grad = Xc.T @ (Xc @ W - Yc) + lam * W
    assert np.abs(grad).max() < 5e-2
    np.testing.assert_allclose(b, ym - xm @ W, atol=1e-3)


def test_block_ls_nondivisible_blocksize(problem):
    """d=24 with block 7 (pads to 28) must still converge
    (reference edge case 'd not divisible by blockSize',
    BlockWeightedLeastSquaresSuite.scala:188)."""
    X, Y = problem
    est = BlockLeastSquaresEstimator(block_size=7, num_iter=20, lam=1.0)
    model = est.fit(Dataset(X), Dataset(Y))
    Wref, bref = ridge_closed_form(X, Y, 1.0)
    np.testing.assert_allclose(np.asarray(model.W)[:24], Wref, atol=5e-2, rtol=5e-2)


def test_lbfgs_dense_with_and_without_intercept(problem):
    """LBFGS shares the (XᵀX + λI) regularization convention with the
    exact solver, so the same λ must give the same model."""
    X, Y = problem
    lam = 20.0
    for intercept in (True, False):
        est = DenseLBFGSwithL2(lam=lam, num_iters=60, fit_intercept=intercept)
        model = est.fit(Dataset(X), Dataset(Y))
        W = np.asarray(model.W)
        if intercept:
            Wref, bref = ridge_closed_form(X, Y, lam)
            np.testing.assert_allclose(np.asarray(model.b), bref, atol=5e-2, rtol=5e-2)
        else:
            Wref, _ = ridge_closed_form(X, Y, lam, intercept=False)
        np.testing.assert_allclose(W, Wref, atol=5e-2, rtol=5e-2)


def test_sparse_gram_on_device_matches_dense():
    """The on-device padded-CSR Gram (blockwise densify + MXU
    accumulate) must equal the dense XᵀX / XᵀY / colsum — including
    empty rows, ragged nnz, and a row count not divisible by the row
    block (sentinel-column padding must contribute nothing)."""
    import scipy.sparse as sp

    from keystone_tpu.nodes.learning.lbfgs import _sparse_gram_on_device

    rng = np.random.default_rng(7)
    n, d, k = 203, 37, 3
    dense = rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.08)
    dense[5] = 0.0  # empty row
    dense[77] = 0.0
    X = sp.csr_matrix(dense.astype(np.float32))
    Y = rng.normal(size=(n, k)).astype(np.float32)
    G, C, s = _sparse_gram_on_device(X, Y, block_rows=64)
    Xd = dense.astype(np.float32)
    np.testing.assert_allclose(np.asarray(G), Xd.T @ Xd, atol=1e-3)
    np.testing.assert_allclose(np.asarray(C), Xd.T @ Y, atol=1e-3)
    np.testing.assert_allclose(np.asarray(s), Xd.sum(axis=0), atol=1e-3)


def test_sparse_lbfgs_outlier_dense_row_falls_back_to_host():
    """One fully-dense row (a ones/bias column pattern) makes the
    width-padded device form O(n·d); the device path must decline and
    the fit must still succeed via the host-scipy Gram path."""
    import scipy.sparse as sp

    from keystone_tpu.data.sparse import SparseDataset
    from keystone_tpu.nodes.learning import SparseLBFGSwithL2
    from keystone_tpu.nodes.learning.lbfgs import _sparse_gram_on_device

    rng = np.random.default_rng(11)
    n, d, k = 5000, 1000, 2
    dense = (rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.002)).astype(
        np.float32
    )
    dense[0] = 1.0  # outlier: one fully dense row -> w = d
    X = sp.csr_matrix(dense)
    # padded bytes = 8·n·d = 40 MB >> 16× the ~11k-nnz data -> declined
    assert _sparse_gram_on_device(X, np.zeros((n, k), np.float32), 256) is None
    Y = rng.normal(size=(n, k)).astype(np.float32)
    model = SparseLBFGSwithL2(lam=1.0, num_iters=120).fit(
        SparseDataset(X), Dataset(Y)
    )
    Wref, bref = ridge_closed_form(dense, Y, 1.0)
    np.testing.assert_allclose(np.asarray(model.W), Wref, atol=1e-1, rtol=1e-1)


def test_sparse_lbfgs_gram_form_matches_ridge():
    import scipy.sparse as sp

    from keystone_tpu.data.sparse import SparseDataset
    from keystone_tpu.nodes.learning import SparseLBFGSwithL2

    rng = np.random.default_rng(3)
    n, d, k = 400, 50, 2
    dense = rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.05)
    X = sp.csr_matrix(dense.astype(np.float32))
    Y = rng.normal(size=(n, k)).astype(np.float32)
    lam = 5.0
    model = SparseLBFGSwithL2(lam=lam, num_iters=80, block_rows=128).fit(
        SparseDataset(X), Dataset(Y)
    )
    Xd = np.asarray(dense, np.float32)
    Wref, bref = ridge_closed_form(Xd, Y, lam)
    np.testing.assert_allclose(np.asarray(model.W), Wref, atol=5e-2, rtol=5e-2)
    np.testing.assert_allclose(np.asarray(model.b), bref, atol=5e-2)


def test_sparse_linear_mapper_matches_dense_apply():
    """SparseLinearMapper (SparseLinearMapper.scala:13-50): sparse batch
    and single-row apply agree with the dense GEMM; SparseLBFGS on sparse
    input returns one."""
    import scipy.sparse as sp

    from keystone_tpu.data.sparse import SparseDataset
    from keystone_tpu.nodes.learning import SparseLBFGSwithL2, SparseLinearMapper

    rng = np.random.default_rng(7)
    n, d, k = 100, 30, 4
    dense = (rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.1)).astype(np.float32)
    X = sp.csr_matrix(dense)
    W = rng.normal(size=(d, k)).astype(np.float32)
    b = rng.normal(size=(k,)).astype(np.float32)

    mapper = SparseLinearMapper(W, b)
    out = mapper.apply_batch(SparseDataset(X)).numpy()
    np.testing.assert_allclose(out, dense @ W + b, atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(mapper.apply(X[3]), dense[3] @ W + b, atol=1e-4)
    np.testing.assert_allclose(mapper.apply(dense[3]), dense[3] @ W + b, atol=1e-4)
    # multi-row sparse apply keeps the batch dimension
    np.testing.assert_allclose(mapper.apply(X[3:6]), dense[3:6] @ W + b, atol=1e-4)
    # dense Dataset apply stays on the device path
    np.testing.assert_allclose(
        mapper.apply_batch(Dataset(dense)).numpy(), dense @ W + b, atol=1e-3
    )

    fitted = SparseLBFGSwithL2(lam=1.0, num_iters=30).fit(
        SparseDataset(X), Dataset(rng.normal(size=(n, k)).astype(np.float32))
    )
    assert isinstance(fitted, SparseLinearMapper)
    assert fitted.apply_batch(SparseDataset(X)).numpy().shape == (n, k)


def test_routing_survives_sparse_input_on_dense_route():
    """A SparseDataset routed to a dense solver must densify, not crash
    (review regression)."""
    import scipy.sparse as sp

    from keystone_tpu.data.sparse import SparseDataset

    rng = np.random.default_rng(5)
    X = sp.csr_matrix(rng.normal(size=(64, 8)).astype(np.float32))  # fully dense
    Y = rng.normal(size=(64, 2)).astype(np.float32)
    est = LeastSquaresEstimator(lam=1.0, num_chips=8)
    model = est.fit(SparseDataset(X), Dataset(Y))
    assert est.chosen != "sparse-lbfgs"  # density 1.0 keeps it off that route
    pred = model.apply_batch(SparseDataset(X))
    assert pred.numpy().shape == (64, 2)


def test_local_least_squares_dual_form():
    """d >> n regime (LocalLeastSquaresEstimator.scala:16-61): primal and
    dual ridge agree."""
    rng = np.random.default_rng(0)
    n, d, k = 40, 200, 2
    X = rng.normal(size=(n, d)).astype(np.float32)
    Y = rng.normal(size=(n, k)).astype(np.float32)
    lam = 3.0
    model = LocalLeastSquaresEstimator(lam).fit(Dataset(X), Dataset(Y))
    Wref = np.linalg.solve(X.T @ X + lam * np.eye(d), X.T @ Y)
    np.testing.assert_allclose(np.asarray(model.W), Wref, atol=1e-2, rtol=1e-2)


def test_standard_scaler(problem):
    X, _ = problem
    model = StandardScaler().fit(Dataset(X))
    np.testing.assert_allclose(np.asarray(model.mean), X.mean(0), atol=1e-4)
    np.testing.assert_allclose(np.asarray(model.std), X.std(0, ddof=1), rtol=1e-3)
    scaled = model.apply_batch(Dataset(X)).numpy()
    np.testing.assert_allclose(scaled.mean(0), 0.0, atol=1e-4)
    np.testing.assert_allclose(scaled.std(0, ddof=1), 1.0, rtol=1e-3)


def test_standard_scaler_padding_invariance():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(37, 5)).astype(np.float32)  # pads to 40 over 8 shards
    model = StandardScaler().fit(Dataset(X))
    np.testing.assert_allclose(np.asarray(model.mean), X.mean(0), atol=1e-4)
    np.testing.assert_allclose(np.asarray(model.std), X.std(0, ddof=1), rtol=1e-3)


# -------------------------------------------------- cost-model routing
# (model: reference LeastSquaresEstimatorSuite.scala:11-95)


def _route(n, d, k, sparsity, chips=16):
    from keystone_tpu.nodes.learning.cost_model import CostProfile

    est = LeastSquaresEstimator(num_chips=chips)

    class FakeSample:
        pass

    p = CostProfile(n=n, d=d, k=k, sparsity=sparsity, num_chips=chips)
    # call the candidate scoring directly via optimize's internals
    import numpy as _np

    rng = _np.random.default_rng(0)
    sample = Dataset(rng.normal(size=(64, d)).astype(_np.float32))
    if sparsity < 1.0:
        arr = sample.numpy()
        mask = rng.random(arr.shape) < sparsity
        sample = Dataset((arr * mask).astype(_np.float32))
    labels = Dataset(rng.normal(size=(64, k)).astype(_np.float32))
    est.optimize(sample, labels, num_per_shard=max(n // chips, 1))
    return est.chosen


def test_routing_big_n_small_d_prefers_exact():
    assert _route(n=2_000_000, d=128, k=10, sparsity=1.0) == "exact"


def test_routing_big_d_prefers_block_or_lbfgs():
    choice = _route(n=100_000, d=16384, k=2, sparsity=1.0)
    assert choice in ("block-ls", "dense-lbfgs")


def test_routing_sparse_prefers_sparse_lbfgs():
    assert _route(n=5_000_000, d=16384, k=2, sparsity=0.004) == "sparse-lbfgs"


def test_calibrate_cost_weights_on_mesh():
    # measured weights must be positive, finite, and usable for routing;
    # on the 8-device CPU mesh the ICI probe actually runs a psum
    from keystone_tpu.nodes.learning.calibrate import calibrate_cost_weights
    from keystone_tpu.nodes.learning.cost_model import CostProfile, ExactSolverCostModel

    w = calibrate_cost_weights(gemm_dim=256, mem_mb=4, iters=2)
    for v in (w.cpu_weight, w.mem_weight, w.network_weight):
        assert np.isfinite(v) and v > 0
    p = CostProfile(n=10_000, d=128, k=4, sparsity=1.0, num_chips=8)
    cost = ExactSolverCostModel().cost(
        p, cpu_weight=w.cpu_weight, mem_weight=w.mem_weight,
        network_weight=w.network_weight,
    )
    assert np.isfinite(cost) and cost > 0


def test_least_squares_calibrated_constructor():
    from keystone_tpu.nodes.learning import LeastSquaresEstimator

    est = LeastSquaresEstimator.calibrated(
        lam=1.0, probe_kwargs=dict(gemm_dim=256, mem_mb=4, iters=2)
    )
    assert est.cpu_weight > 0 and est.mem_weight > 0 and est.network_weight > 0


def test_sparse_lbfgs_iterative_matches_ridge():
    """The matvec L-BFGS path (per-iteration sparse gather/scatter, exact
    quadratic line search) converges to the same ridge solution as the
    closed form — the iteration structure of the reference's sparse
    L-BFGS (LBFGS.scala:14-103, LeastSquaresSparseGradient) rather than
    the one-pass Gram reduction."""
    import scipy.sparse as sp

    from keystone_tpu.data.sparse import SparseDataset
    from keystone_tpu.nodes.learning import SparseLBFGSwithL2

    rng = np.random.default_rng(13)
    n, d, k = 600, 64, 3
    dense = (rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.08)).astype(
        np.float32)
    X = sp.csr_matrix(dense)
    Y = rng.normal(size=(n, k)).astype(np.float32)
    lam = 2.0
    est = SparseLBFGSwithL2(lam=lam, num_iters=80, method="iterative")
    model = est.fit(SparseDataset(X), Dataset(Y))
    Wref, bref = ridge_closed_form(dense, Y, lam)
    np.testing.assert_allclose(np.asarray(model.W), Wref, atol=5e-2, rtol=5e-2)
    np.testing.assert_allclose(np.asarray(model.b), bref, atol=5e-2)
    # loss history is monotone non-increasing after the first steps
    hist = np.asarray(est.loss_history)
    assert hist[-1] <= hist[0]


def test_sparse_lbfgs_iterative_agrees_with_gram_path():
    """Same estimator, both routes forced: the two TPU-native sparse
    designs must agree on the solution (and with no intercept too)."""
    import scipy.sparse as sp

    from keystone_tpu.data.sparse import SparseDataset
    from keystone_tpu.nodes.learning import SparseLBFGSwithL2

    rng = np.random.default_rng(17)
    n, d, k = 500, 48, 2
    dense = (rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.1)).astype(
        np.float32)
    X = sp.csr_matrix(dense)
    Y = rng.normal(size=(n, k)).astype(np.float32)
    for intercept in (True, False):
        m_it = SparseLBFGSwithL2(
            lam=1.0, num_iters=60, method="iterative",
            fit_intercept=intercept).fit(SparseDataset(X), Dataset(Y))
        m_gr = SparseLBFGSwithL2(
            lam=1.0, num_iters=60, method="gram",
            fit_intercept=intercept).fit(SparseDataset(X), Dataset(Y))
        np.testing.assert_allclose(
            np.asarray(m_it.W), np.asarray(m_gr.W), atol=2e-2, rtol=2e-2)


def test_padded_sparse_dataset_device_resident_fit():
    """PaddedSparseDataset: the device-resident sparse layout feeds the
    iterative solver directly (no host CSR in the loop) and reproduces
    the CSR-path solution; from_csr round-trips the padding."""
    import jax.numpy as jnp
    import scipy.sparse as sp

    from keystone_tpu.data.sparse import PaddedSparseDataset, SparseDataset
    from keystone_tpu.nodes.learning import SparseLBFGSwithL2

    rng = np.random.default_rng(19)
    n, d, k = 400, 40, 2
    dense = (rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.12)).astype(
        np.float32)
    X = sp.csr_matrix(dense)
    ds = PaddedSparseDataset.from_csr(X)
    assert ds.count == n and ds.dim == d
    assert ds.nnz == X.nnz
    # padded slots carry the sentinel column id == dim
    assert int(jnp.max(ds.idx)) <= d
    Y = rng.normal(size=(n, k)).astype(np.float32)
    m_pad = SparseLBFGSwithL2(lam=1.0, num_iters=60).fit(ds, Dataset(Y))
    m_csr = SparseLBFGSwithL2(lam=1.0, num_iters=60, method="iterative").fit(
        SparseDataset(X), Dataset(Y))
    np.testing.assert_allclose(
        np.asarray(m_pad.W), np.asarray(m_csr.W), atol=1e-4, rtol=1e-4)


def test_sparse_lbfgs_route_cost_model():
    """Routing mirrors the reference CostModel economics re-derived
    from measured chip rates (scripts/sparse_microbench.py): the TPU
    has no gather hardware (~5 ns/element scalar gathers), so for
    k ≪ d the one-pass densified MXU Gram beats num_iters of gather
    matvecs even at amazon's d=16384 — while hashing-trick shapes
    (d ~ 2^20, shallow rows) still route iterative, where the d² MXU
    term is hopeless."""
    from keystone_tpu.nodes.learning import SparseLBFGSwithL2

    est = SparseLBFGSwithL2(num_iters=20)
    # amazon-shaped: n=65e6, d=16384, k=2, w≈82 → densified MXU Gram
    assert est._route(65_000_000, 16384, 2, 82) == "gram"
    # small-d dense-ish: Gram's one pass wins outright
    assert est._route(400, 50, 2, 6) == "gram"
    # hashing-trick text features: d=2^20, w=50 → iterative
    assert est._route(1_000_000, 1 << 20, 2, 50) == "iterative"
    # explicit override is respected
    assert SparseLBFGSwithL2(method="iterative")._route(
        65_000_000, 16384, 2, 82) == "iterative"


def test_padded_sparse_column_form_paths_agree():
    """Scatter tmatvec (row form) vs gather tmatvec (column form) vs the
    device-built column form (with_column_form argsort path): all three
    produce the same fit. Pinned to a 1-device mesh — under a multi-
    device mesh the solver takes the dp-sharded route instead (covered
    by test_sparse_lbfgs_iterative_dp_sharded_agrees)."""
    import jax
    import jax.numpy as jnp
    import scipy.sparse as sp

    from keystone_tpu.data.sparse import PaddedSparseDataset
    from keystone_tpu.nodes.learning import SparseLBFGSwithL2
    from keystone_tpu.parallel.mesh import make_mesh, use_mesh

    rng = np.random.default_rng(23)
    n, d, k = 500, 64, 2
    dense = (rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.1)).astype(
        np.float32)
    X = sp.csr_matrix(dense)
    Y = rng.normal(size=(n, k)).astype(np.float32)

    with_col = PaddedSparseDataset.from_csr(X)
    assert with_col.cidx is not None
    no_col = PaddedSparseDataset(with_col.idx, with_col.val, d, nnz=X.nnz)
    dev_col = no_col.with_column_form()
    assert dev_col.cidx is not None
    # host-built and device-built column forms value-sum identically per
    # column (slot order within a column may differ; slots are axis 0
    # of the slot-major (wc, d) layout)
    np.testing.assert_allclose(
        np.asarray(jnp.sort(with_col.cval, axis=0)),
        np.asarray(jnp.sort(dev_col.cval, axis=0)), atol=0)

    with use_mesh(make_mesh(jax.devices()[:1])):
        fits = [
            SparseLBFGSwithL2(lam=1.0, num_iters=50).fit(ds, Dataset(Y))
            for ds in (with_col, no_col, dev_col)
        ]
    for m in fits[1:]:
        np.testing.assert_allclose(
            np.asarray(fits[0].W), np.asarray(m.W), atol=1e-4, rtol=1e-4)


def test_sparse_lbfgs_iterative_dp_sharded_agrees():
    """Under a multi-device mesh the iterative route dp-shards rows via
    shard_map (psum where the reference treeReduces gradients,
    LBFGS.scala:97-103); the fit must agree with the 1-device fit and
    with the ridge closed form."""
    import jax
    import scipy.sparse as sp

    from keystone_tpu.data.sparse import SparseDataset
    from keystone_tpu.nodes.learning import SparseLBFGSwithL2
    from keystone_tpu.parallel.mesh import make_mesh, use_mesh

    if len(jax.devices()) < 2:
        import pytest

        pytest.skip("needs multi-device mesh")
    rng = np.random.default_rng(29)
    n, d, k = 603, 48, 2  # not divisible by the 8-device data axis
    dense = (rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.1)).astype(
        np.float32)
    X = sp.csr_matrix(dense)
    Y = rng.normal(size=(n, k)).astype(np.float32)
    est = lambda: SparseLBFGSwithL2(lam=1.0, num_iters=60, method="iterative")
    with use_mesh(make_mesh(jax.devices())):
        m_mesh = est().fit(SparseDataset(X), Dataset(Y))
    with use_mesh(make_mesh(jax.devices()[:1])):
        m_one = est().fit(SparseDataset(X), Dataset(Y))
    np.testing.assert_allclose(
        np.asarray(m_mesh.W), np.asarray(m_one.W), atol=1e-3, rtol=1e-3)
    Wref, bref = ridge_closed_form(dense, Y, 1.0)
    np.testing.assert_allclose(np.asarray(m_mesh.W), Wref, atol=5e-2, rtol=5e-2)
    np.testing.assert_allclose(np.asarray(m_mesh.b), bref, atol=5e-2)


# --------------------------------------------------------------------------
# Donated solver buffers (overlap engine PR): the host-looped steps with
# donate_argnums must produce fits identical to the single-program scan
# forms they replaced (the pre-change solvers, kept as the numerics
# reference / fused-pipeline path).


def test_bcd_donated_epochs_match_scan_form(problem):
    """BlockLeastSquaresEstimator now loops a donated `_bcd_epoch`; the
    result must be allclose-identical to the one-program `_bcd_fit` scan
    (same block_step arithmetic, same op order)."""
    import jax.numpy as jnp

    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
    from keystone_tpu.nodes.learning.block_ls import _bcd_fit

    X, Y = problem
    for bs, iters, center in ((8, 3, True), (7, 2, False)):
        est = BlockLeastSquaresEstimator(
            block_size=bs, num_iter=iters, lam=0.5, fit_intercept=center)
        data, labels = Dataset(X), Dataset(Y)
        model = est.fit(data, labels)
        nb = -(-X.shape[1] // bs)
        d_pad = nb * bs
        Xp = data.array
        if d_pad != X.shape[1]:
            Xp = jnp.pad(Xp, [(0, 0), (0, d_pad - X.shape[1])])
        Wref, bref = _bcd_fit(
            Xp, labels.array, data.mask.astype(Xp.dtype),
            jnp.asarray(0.5, Xp.dtype), bs, nb, iters, center,
            x_sharding=None,
        )
        np.testing.assert_allclose(
            np.asarray(model.W), np.asarray(Wref), atol=1e-5, rtol=1e-5)
        if center:
            np.testing.assert_allclose(
                np.asarray(model.b), np.asarray(bref), atol=1e-5, rtol=1e-5)


def test_lbfgs_donated_steps_match_scan_form(problem):
    """DenseLBFGSwithL2 now loops a donated `_lbfgs_step`; must be
    allclose-identical to the one-program `_lbfgs_fit` scan."""
    import jax.numpy as jnp

    from keystone_tpu.nodes.learning import DenseLBFGSwithL2
    from keystone_tpu.nodes.learning.lbfgs import _lbfgs_fit

    X, Y = problem
    for intercept in (True, False):
        est = DenseLBFGSwithL2(
            lam=3.0, num_iters=25, fit_intercept=intercept)
        data, labels = Dataset(X), Dataset(Y)
        model = est.fit(data, labels)
        Wref, bref, values = _lbfgs_fit(
            data.array, labels.array, data.mask.astype(np.float32),
            jnp.asarray(3.0, jnp.float32),
            jnp.asarray(data.count, jnp.float32),
            25, 10, intercept, x_sharding=None,
        )
        np.testing.assert_allclose(
            np.asarray(model.W), np.asarray(Wref), atol=1e-4, rtol=1e-4)
        if intercept:
            np.testing.assert_allclose(
                np.asarray(model.b), np.asarray(bref), atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(est.loss_history), np.asarray(values),
            atol=1e-3, rtol=1e-5)


def test_krr_donated_step_matches_undonated_reference():
    """`_krr_step` donates (alpha, KA); one step must equal the same
    update computed without donation, and the fit loop's rebinding
    discipline must keep multi-step fits identical to a hand-rolled
    undonated Gauss-Seidel loop."""
    import jax
    import jax.numpy as jnp

    from keystone_tpu.nodes.learning.kernels import (
        KernelRidgeRegression,
        _krr_step,
        _rbf_block,
    )

    rng = np.random.default_rng(7)
    n, d, k = 64, 6, 2
    X = rng.normal(size=(n, d)).astype(np.float32)
    Y = rng.normal(size=(n, k)).astype(np.float32)
    data, labels = Dataset(X), Dataset(Y)

    est = KernelRidgeRegression(gamma=0.5, lam=0.1, block_size=16,
                                num_epochs=2, seed=3)
    model = est.fit(data, labels)

    # hand-rolled undonated reference replaying the same block orders
    n_pad = data.padded_count
    mask = np.asarray(data.mask).astype(np.float32)
    Xp = np.asarray(data.array)
    Yp = np.asarray(labels.array) * mask[:, None]
    alpha = np.zeros((n_pad, k), np.float32)
    KA = np.zeros_like(alpha)
    B = 16
    n_blocks = -(-data.count // B)
    for epoch in range(2):
        perm = np.random.default_rng(3 + epoch).permutation(data.count)
        pad = (-len(perm)) % (n_blocks * B)
        ids = np.concatenate([perm, perm[:pad]]) if pad else perm
        for b in range(n_blocks):
            blk = ids[b * B : (b + 1) * B]
            Kb = np.asarray(
                _rbf_block(jnp.asarray(Xp), jnp.asarray(Xp[blk]), 0.5)
            ) * mask[:, None]
            Kbb = Kb[blk]
            resid = Yp[blk] - KA[blk] - 0.1 * alpha[blk]
            delta = np.linalg.solve(Kbb + 0.1 * np.eye(B, dtype=np.float32),
                                    resid)
            alpha[blk] += delta
            KA = KA + Kb @ delta
    np.testing.assert_allclose(
        np.asarray(model.alpha), alpha, atol=1e-3, rtol=1e-3)

    # single donated step vs an undonated jit of the same update
    alpha0 = jnp.zeros((n_pad, k), jnp.float32)
    KA0 = jnp.zeros_like(alpha0)
    blk = jnp.arange(16, dtype=jnp.int32)
    a1, K1 = _krr_step(
        jnp.asarray(Xp), jnp.asarray(Yp), jnp.asarray(mask),
        alpha0, KA0, jnp.float32(0.1), 0.5, blk, False)
    undonated = jax.jit(
        _krr_step.__wrapped__, static_argnames=("gamma", "use_pal"))
    a2, K2 = undonated(
        jnp.asarray(Xp), jnp.asarray(Yp), jnp.asarray(mask),
        jnp.zeros((n_pad, k), jnp.float32),
        jnp.zeros((n_pad, k), jnp.float32),
        jnp.float32(0.1), 0.5, blk, False)
    np.testing.assert_allclose(np.asarray(a1), np.asarray(a2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(K1), np.asarray(K2), atol=1e-6)
