"""Operator contract auditor (KP5xx) + concurrency effect analyzer
(KP511) — `keystone_tpu/analysis/contracts.py` / `effects.py`.

Marked ``lint``: data-free, device-free (AST walks + `jax.eval_shape`
traces only), mirroring `scripts/lint.sh`'s --audit-operators stage so
CI and pytest cannot drift.
"""

import json
import re
import subprocess
import sys
from functools import partial
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.analysis import Severity
from keystone_tpu.analysis.contracts import (
    audit_class,
    audit_operator,
    audit_registry,
    operator_registry,
)
from keystone_tpu.analysis.effects import (
    class_effects,
    interference_pass,
    operator_effects,
)
from keystone_tpu.analysis.specs import SpecDataset
from keystone_tpu.nodes.stats.random_features import RandomSignNode
from keystone_tpu.workflow.env import dispatch_override
from keystone_tpu.workflow.pipeline import (
    Estimator,
    Pipeline,
    Transformer,
)

pytestmark = pytest.mark.lint

REPO = Path(__file__).resolve().parent.parent


# --------------------------------------------------------------- helpers


class _CleanStage(Transformer):
    """Fusable + chunkable with a structural fuse(): fully contract-clean."""

    fusable = True
    chunkable = True

    def apply(self, x):
        return x * 2.0

    def fuse(self):
        return (("CleanStage",), (), lambda p, xb: xb * 2.0)


class _NoFuseStage(Transformer):
    """The PR-6 bug class: declares fusable, implements no fuse()."""

    fusable = True

    def apply(self, x):
        return x * 2.0


class _StrippedRandomSign(RandomSignNode):
    """A real stats stage with its fuse() stripped off — the exact
    regression PR 6 paid ~5x re-apply cost for."""

    fuse = None


class _GramStage(Transformer):
    """chunkable declared, but the batch path computes a whole-batch
    Gram matrix — f(concat(chunks)) != concat(f(chunks))."""

    chunkable = True

    def apply(self, x):
        return x

    def fuse(self):
        return (("Gram",), (), lambda p, xb: xb @ xb.T)


class _BatchMeanStage(Transformer):
    """chunkable declared, but the batch path reduces over the example
    axis."""

    chunkable = True

    def apply(self, x):
        return x

    def batch_fn(self):
        return lambda xb: jnp.mean(xb, axis=0)


@partial(jax.jit, static_argnames=())
def _undonated_step(W, R):
    return W + R


@partial(jax.jit, donate_argnums=(0,))
def _donated_step(W, R):
    return W + R


def jit(fn=None, **kw):
    """AST stand-in for jax.jit: a real jax.jit with an out-of-range
    donate_argnums raises at decoration time, but the auditor's
    cross-check must still catch the SOURCE shape (the bug a refactor
    introduces by reordering a step's parameters)."""
    return fn if fn is not None else (lambda f: f)


@partial(jit, donate_argnums=(5,))
def _misindexed_step(W, R):
    return W + R


class _UndonatedDonor(Transformer):
    donates_deps = (0,)

    def apply_batch(self, data):
        return _undonated_step(data, data)

    def apply(self, x):
        return x


class _HonestDonor(Transformer):
    donates_deps = (0,)

    def apply_batch(self, data):
        return _donated_step(data, data)

    def apply(self, x):
        return x


class _MisindexedDonor(Transformer):
    donates_deps = (0,)

    def apply_batch(self, data):
        return _misindexed_step(data, data)

    def apply(self, x):
        return x


class _UnmaskedMasker(Transformer):
    """Masks padded rows in the unfused batch path but does not declare
    fuse_masks_output — the padded-row corruption class."""

    fusable = True

    def apply(self, x):
        return x

    def fuse(self):
        return (("UnmaskedMasker",), (), lambda p, xb: xb)

    def apply_batch(self, data):
        return data.with_data(data.array * data.mask[:, None])


class _DeclaredMasker(_UnmaskedMasker):
    fuse_masks_output = True


class _SuppressedNoFuse(Transformer):  # keystone: ignore[KP501]
    """A genuine exception, suppressed explicitly on the class line."""

    fusable = True

    def apply(self, x):
        return x


class _StatefulEstimator(Estimator):
    """fusable_fit promising a fit that yields a fusable-but-opaque
    transformer (no structural fuse on _NoFuseStage)."""

    fusable_fit = True

    def fit(self, data):
        return _NoFuseStage()


class _CleanEstimator(Estimator):
    fusable_fit = True

    def fit(self, data):
        return _CleanStage()


def _rules(diags):
    return sorted({d.rule for d in diags})


# ------------------------------------------------------ KP501 (fuse key)


def test_kp501_flags_fusable_without_fuse():
    diags = audit_operator(_NoFuseStage())
    assert _rules(diags) == ["KP501"]
    assert diags[0].severity == Severity.WARNING
    assert "fuse()" in diags[0].message


def test_kp501_negative_structural_fuse():
    assert audit_operator(_CleanStage(), [(6,)]) == []


def test_kp501_regression_stripped_stats_stage():
    """Stripping fuse() off a real stats stage re-introduces the PR-6
    silent-retrace bug class — the audit makes it un-reintroducible."""
    assert audit_operator(RandomSignNode(6), [(6,)]) == []
    diags = audit_operator(_StrippedRandomSign(6))
    assert _rules(diags) == ["KP501"]


def test_kp501_detects_opaque_key_not_method_presence():
    """Detection inspects the fused program KEY path: a fuse() that
    returns an id-keyed (opaque) component is still flagged."""

    class _OpaqueFuse(Transformer):
        fusable = True

        def apply(self, x):
            return x

        def fuse(self):
            return (("opaque", id(self)), (), lambda p, xb: xb)

    diags = audit_operator(_OpaqueFuse())
    assert _rules(diags) == ["KP501"]
    assert "opaque" in diags[0].message


def test_kp501_via_fusable_fit_output():
    diags = audit_operator(_StatefulEstimator())
    assert _rules(diags) == ["KP501"]
    assert "_NoFuseStage" in diags[0].message
    assert audit_operator(_CleanEstimator()) == []


def test_kp501_suppressed_on_class_line():
    assert audit_operator(_SuppressedNoFuse()) == []


# -------------------------------------------------- KP502 (distributivity)


def test_kp502_flags_non_distributive_batch_path():
    diags = audit_operator(_GramStage(), [(4,)])
    assert _rules(diags) == ["KP502"]
    assert diags[0].severity == Severity.ERROR

    diags = audit_operator(_BatchMeanStage(), [(4,)])
    assert _rules(diags) == ["KP502"]


def test_kp502_negative_distributive_and_host_stages():
    from keystone_tpu.nodes.stats.normalization import (
        ColumnSampler,
        NormalizeRows,
    )

    assert audit_operator(NormalizeRows(), [(6,)]) == []
    # host-code batch path: not provable either way, never flagged
    assert audit_operator(ColumnSampler(4), [(8, 6)]) == []


# ------------------------------------------------------ KP503 (donation)


def test_kp503_flags_undonated_and_misindexed_steps():
    diags = audit_operator(_UndonatedDonor())
    assert _rules(diags) == ["KP503"]
    assert "donate_argnums" in diags[0].message

    diags = audit_operator(_MisindexedDonor())
    assert _rules(diags) == ["KP503"]
    assert "mis-indexed" in diags[0].message


def test_kp503_negative_honest_donor():
    assert audit_operator(_HonestDonor()) == []


class _SubclassedDonor(_HonestDonor):
    """Empty-body subclass: donates_deps AND the donating step resolve
    through the MRO — just as honest as the base."""


def test_kp503_resolves_through_mro():
    assert audit_operator(_SubclassedDonor()) == []


# -------------------------------------------------------- KP504 (masking)


def test_kp504_flags_unmasked_fused_stage():
    diags = audit_operator(_UnmaskedMasker())
    assert _rules(diags) == ["KP504"]
    assert diags[0].severity == Severity.ERROR
    assert "fuse_masks_output" in diags[0].message


class _SubclassedMasker(_UnmaskedMasker):
    """Empty-body subclass: the masking batch path is INHERITED, and so
    is the padded-row contract it breaks."""


def test_kp504_sees_inherited_masking_batch_path():
    diags = audit_operator(_SubclassedMasker())
    assert _rules(diags) == ["KP504"], diags


def test_kp504_negative_declared_and_mask_aware():
    from keystone_tpu.nodes.stats.scalers import StandardScalerModel
    from keystone_tpu.nodes.util.fusion import FusedBatchTransformer

    assert audit_operator(_DeclaredMasker()) == []
    assert audit_operator(
        StandardScalerModel(np.zeros(4, np.float32),
                            np.ones(4, np.float32))) == []
    # the fusion machinery threads masks through by construction
    assert audit_operator(FusedBatchTransformer([_CleanStage()])) == []


# -------------------------------------------------- registry-wide sweep


def test_registry_audit_is_clean():
    """Acceptance: the full built-in operator registry carries zero
    unsuppressed KP5xx findings."""
    findings, stats = audit_registry()
    assert not findings, "\n".join(
        f"{cls.__qualname__}: {d}" for cls, d in findings)
    assert stats["classes"] > 80
    assert stats["probed"] > 40


def test_registry_discovers_node_and_fusion_classes():
    names = {c.__name__ for c in operator_registry()}
    assert {"RandomSignNode", "StandardScalerModel", "FusedBatchTransformer",
            "MegafusedBatchTransformer", "LinearMapper",
            "GrayScaler"} <= names


def test_audit_class_reports_probe_status():
    diags, probed = audit_class(RandomSignNode)
    assert diags == [] and probed
    # no probe, no declared contracts: class-level checks only, clean
    from keystone_tpu.workflow.operators import DelegatingOperator

    diags, _ = audit_class(DelegatingOperator)
    assert diags == []


# ---------------------------------------------- validate() integration


def test_validate_full_surfaces_kp501():
    pipe = _StrippedRandomSign(6).to_pipeline()
    report = pipe.validate((6,), raise_on_error=False)
    assert report.by_rule("KP501"), str(report)
    # suppression channel
    assert not pipe.validate(
        (6,), ignore=["KP501"], raise_on_error=False).by_rule("KP501")


def test_validate_full_surfaces_kp502_as_error():
    pipe = _GramStage().to_pipeline()
    report = pipe.validate((4,), raise_on_error=False)
    kp502 = report.by_rule("KP502")
    assert kp502 and kp502[0].severity == Severity.ERROR


def test_validate_structure_tier_skips_contracts():
    pipe = _StrippedRandomSign(6).to_pipeline()
    report = pipe.validate((6,), level="structure", raise_on_error=False)
    assert not report.by_rule("KP501")


# ------------------------------------------------- effects + KP511


class _EffectfulCounter(Transformer):
    """Deliberately effectful: mutates instance state at apply time."""

    chunkable = True

    def __init__(self):
        self.calls = 0

    def apply(self, x):
        self.calls = self.calls + 1
        return x


class _MemoizedStage(Transformer):
    """The sanctioned instance-memo idiom: not an effect."""

    def apply(self, x):
        got = self.__dict__.get("_memo")
        if got is None:
            self.__dict__["_memo"] = got = 2.0
        return x * got


class _SuppressedEffect(Transformer):
    def apply(self, x):
        self.last = x  # keystone: ignore[KP511]
        return x


def test_effect_inference_finds_self_writes():
    effects = class_effects(_EffectfulCounter)
    assert any(e.kind == "self_write" and e.target == "attr:calls"
               for e in effects)
    assert class_effects(_MemoizedStage) == ()
    assert class_effects(_SuppressedEffect) == ()
    assert class_effects(_CleanStage) == ()


class _MutatorCounter(Transformer):
    """Review regression: the mutator-call spelling of instance-state
    mutation (`self.seen.append(x)`) races exactly like the subscript
    assignment and must infer the same self_write effect."""

    chunkable = True

    def __init__(self):
        self.seen = []

    def apply(self, x):
        self.seen.append(x)
        return x


class _DictMemoMutator(Transformer):
    """Mutator calls on the sanctioned self.__dict__ chain are memo
    maintenance, not shared-state mutation."""

    def apply(self, x):
        self.__dict__.setdefault("_hits", []).append(1)
        return x


def test_effect_inference_finds_self_container_mutators():
    effects = class_effects(_MutatorCounter)
    assert any(e.kind == "self_write" and e.target == "attr:seen"
               for e in effects)
    assert class_effects(_DictMemoMutator) == ()
    # and the graph pass turns the shared mutator instance into KP511
    shared = _MutatorCounter()
    diags = interference_pass(
        _effectful_gather_pipeline(shared).apply(
            SpecDataset((4,), count=8)).graph)
    assert diags and all(d.rule == "KP511" for d in diags)


def test_operator_effects_sees_composite_components():
    from keystone_tpu.nodes.util.fusion import FusedBatchTransformer

    inner = _EffectfulCounter()
    eff = operator_effects(FusedBatchTransformer([inner]))
    assert id(inner) in eff


def _effectful_gather_pipeline(shared):
    """Two parallel branches forcing the SAME effectful instance — the
    concurrent scheduler may run them simultaneously."""
    left = shared.to_pipeline() >> Transformer.from_function(
        lambda x: x + 1.0, name="L")
    right = shared.to_pipeline() >> Transformer.from_function(
        lambda x: x - 1.0, name="R")
    return Pipeline.gather([left, right])


def test_kp511_true_positive_under_concurrent_scheduler():
    shared = _EffectfulCounter()
    pipe = _effectful_gather_pipeline(shared)
    with dispatch_override(True, workers=4):
        report = pipe.validate((4,), raise_on_error=False)
    kp511 = report.by_rule("KP511")
    assert kp511, str(report)
    assert kp511[0].severity == Severity.WARNING
    assert "simultaneously" in kp511[0].message


def test_kp511_true_negative_with_scheduler_off():
    """KEYSTONE_CONCURRENT_DISPATCH=0 totally orders every pair: the
    race cannot occur and the diagnostic must not fire."""
    shared = _EffectfulCounter()
    pipe = _effectful_gather_pipeline(shared)
    with dispatch_override(False):
        report = pipe.validate((4,), raise_on_error=False)
    assert not report.by_rule("KP511"), str(report)


def test_kp511_ordered_chain_does_not_fire():
    """A dependency chain orders the two effectful vertices — the
    scheduler serializes them, so there is no race to flag."""
    shared = _EffectfulCounter()
    pipe = shared.to_pipeline() >> Transformer.from_function(
        lambda x: x * 2.0, name="mid") >> shared
    with dispatch_override(True, workers=4):
        report = pipe.validate((4,), raise_on_error=False)
    assert not report.by_rule("KP511"), str(report)


def test_kp511_distinct_instances_do_not_fire():
    """Two DIFFERENT instances writing their own state never race."""
    left = _EffectfulCounter().to_pipeline()
    right = _EffectfulCounter().to_pipeline()
    pipe = Pipeline.gather([left, right])
    with dispatch_override(True, workers=4):
        report = pipe.validate((4,), raise_on_error=False)
    assert not report.by_rule("KP511"), str(report)


def test_concurrent_relation_matches_dag_order():
    from keystone_tpu.workflow.executor import concurrent_relation

    shared = _EffectfulCounter()
    pipe = _effectful_gather_pipeline(shared)
    applied = pipe.apply(SpecDataset((4,), count=8))
    g = applied.graph
    unordered = concurrent_relation(g)
    # the two branch-head vertices hold the same operator instance
    heads = [n for n in g.operators if g.get_operator(n) is shared]
    assert len(heads) == 2
    assert unordered(heads[0], heads[1])
    # a vertex is ordered against its own downstream consumer
    from keystone_tpu.workflow.analysis import children

    kid = next(iter(children(g, heads[0])))
    assert not unordered(heads[0], kid)


def test_interference_pass_direct():
    shared = _EffectfulCounter()
    pipe = _effectful_gather_pipeline(shared)
    applied = pipe.apply(SpecDataset((4,), count=8))
    diags = interference_pass(applied.graph)
    assert diags and all(d.rule == "KP511" for d in diags)


# ------------------------------------------------------------- doc sync


def _catalog_codes():
    text = (REPO / "ANALYSIS.md").read_text()
    return {m.group(1) for m in
            re.finditer(r"^\|\s*(K[PJ]\d{3,4})\s*\|", text, re.M)}


def test_analysis_md_documents_every_rule():
    """Doc-sync: every KP/KJ code emitted by diagnostics.py/jaxlint.py
    has a row in ANALYSIS.md and vice versa — the catalog can no longer
    run one PR behind."""
    import importlib.util

    from keystone_tpu.analysis.diagnostics import RULES as KP_RULES

    spec = importlib.util.spec_from_file_location(
        "jaxlint", REPO / "scripts" / "jaxlint.py")
    jaxlint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(jaxlint)

    emitted = set(KP_RULES) | set(jaxlint.RULES)
    documented = _catalog_codes()
    missing = emitted - documented
    stale = documented - emitted
    assert not missing, f"rules emitted but undocumented: {sorted(missing)}"
    assert not stale, f"rules documented but never emitted: {sorted(stale)}"


# ------------------------------------------------------------------ CLI


def test_audit_cli_json_output():
    import os

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "keystone_tpu.analysis",
         "--audit-operators", "--json"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["findings"] == []
    assert payload["audited_classes"] > 80


def test_jaxlint_json_output(tmp_path):
    bad = tmp_path / "nodes" / "bad.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "class T:\n"
        "    def apply(self, x):\n"
        "        self.state = x\n"
        "        return x\n")
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "jaxlint.py"), "--json",
         str(bad)],
        capture_output=True, text=True)
    assert out.returncode == 1
    payload = json.loads(out.stdout)
    assert payload["total"] == 1
    assert payload["findings"][0]["rule"] == "KJ008"
