"""Concurrent DAG scheduler + expanded-fusion correctness suite.

Covers the dispatch-bounded execution contract:
  - deterministic results across worker counts 1/2/4 (and vs serial);
  - exception propagation identical to the serial recursive force;
  - memo/prefix single-force guarantee under concurrency;
  - overlap-engine streaming still active inside fused chains;
  - fusion never crosses a fan-out; chain discovery is insensitive to
    node-id iteration order;
  - the acceptance gate: ≥2× programs-per-run reduction on at least two
    example pipelines, outputs allclose-identical to the serial unfused
    path (keystone_tpu.dispatch_bench, the `dispatch_count` bench tier);
  - the nodes/stats chunkable audit (every elementwise stats transformer
    declares it).
"""

import threading

import numpy as np
import pytest

from keystone_tpu import Dataset, HostDataset, Pipeline, PipelineEnv, Transformer
from keystone_tpu.telemetry import counter
from keystone_tpu.workflow import Estimator
from keystone_tpu.workflow.env import dispatch_override, overlap_override
from keystone_tpu.workflow.optimizer import DefaultOptimizer


# --------------------------------------------------------------------------
# determinism across worker counts


def _gather_pipeline(width=4):
    branches = [
        Transformer.from_function((lambda k: lambda x: x * (k + 1.0))(i),
                                  name=f"scale{i}")
        for i in range(width)
    ]
    from keystone_tpu.nodes.util import VectorCombiner

    return Pipeline.gather(branches) >> VectorCombiner()


def test_deterministic_across_worker_counts():
    ds = Dataset.from_numpy(
        np.arange(32, dtype=np.float32).reshape(8, 4))
    pipe = _gather_pipeline()
    with dispatch_override(False):
        reference = pipe(ds).get().numpy()
    for workers in (1, 2, 4):
        PipelineEnv.reset()
        with dispatch_override(True, workers=workers):
            out = pipe(ds).get().numpy()
        np.testing.assert_array_equal(out, reference)


def test_scheduler_actually_ran():
    PipelineEnv.reset()
    ds = Dataset.from_numpy(np.ones((8, 4), np.float32))
    runs = counter("dispatch.scheduler_runs")
    before = runs.value
    with dispatch_override(True, workers=4):
        _gather_pipeline()(ds).get()
    assert runs.value > before


# --------------------------------------------------------------------------
# exception propagation


class _Boom(Transformer):
    def apply(self, x):
        raise RuntimeError("boom at force time")


@pytest.mark.parametrize("workers", [1, 2, 4])
def test_exception_propagation_matches_serial(workers):
    ds = Dataset.from_numpy(np.ones((8, 4), np.float32))
    from keystone_tpu.nodes.util import VectorCombiner

    pipe = Pipeline.gather([
        Transformer.from_function(lambda x: x, name="ok"),
        _Boom().to_pipeline(),
    ]) >> VectorCombiner()

    with dispatch_override(False):
        with pytest.raises(RuntimeError, match="boom at force time"):
            pipe(ds).get()

    PipelineEnv.reset()
    with dispatch_override(True, workers=workers):
        res = pipe(ds)
        with pytest.raises(RuntimeError, match="boom at force time"):
            res.get()
        # retry semantics identical to serial: the failing expression
        # stays unforced, a second force re-raises
        with pytest.raises(RuntimeError, match="boom at force time"):
            res.get()


# --------------------------------------------------------------------------
# memo/prefix single-force guarantee under concurrency


class _CountingEstimator(Estimator):
    def __init__(self):
        self.fits = 0
        self._lock = threading.Lock()

    def fit(self, data):
        with self._lock:
            self.fits += 1
        mu = float(np.mean(data.numpy()))
        return Transformer.from_function(lambda x: x - mu, name="center")


def test_single_force_and_fit_once_under_concurrency():
    """A CSE-shared featurize node with two consumers is forced exactly
    once; re-applying the pipeline never refits (prefix reuse), all with
    the worker pool on."""
    forces = []
    lock = threading.Lock()

    shared = Transformer.from_function(lambda x: x * 2.0, name="shared")
    orig_batch = shared.batch_transform

    def counting_batch(inputs):
        with lock:
            forces.append(threading.get_ident())
        return orig_batch(inputs)

    shared.batch_transform = counting_batch

    est = _CountingEstimator()
    train = Dataset.from_numpy(np.ones((8, 2), np.float32))
    # both gather branches route through the SAME transformer instance:
    # CSE merges them into one node with two consumers
    from keystone_tpu.nodes.util import VectorCombiner

    featurize = Pipeline.gather([
        shared.to_pipeline() >> Transformer.from_function(
            lambda x: x + 1.0, name="a"),
        shared.to_pipeline() >> Transformer.from_function(
            lambda x: x + 2.0, name="b"),
    ]) >> VectorCombiner()
    pipe = featurize.and_then(est, train)

    with dispatch_override(True, workers=4):
        out1 = pipe(train).get().numpy()
        assert len(forces) == 1, "shared node forced more than once"
        assert est.fits == 1
        out2 = pipe(train).get().numpy()  # fresh executor, same prefixes
    assert est.fits == 1, "prefix reuse failed: estimator refit"
    np.testing.assert_array_equal(out1, out2)


# --------------------------------------------------------------------------
# overlap streaming stays active inside fused chains


class _ChunkProducer(Transformer):
    """A bucketed host-batch stage that yields per-chunk results (the
    SIFT/grid-descriptor pattern)."""

    def apply(self, x):
        return np.asarray(x, np.float32) * 2.0

    def apply_batch_stream(self, data):
        from keystone_tpu.utils import batching

        return batching.map_host_batched_stream(
            data.items, lambda xb: np.asarray(xb) * 2.0, chunk=2)


def test_streaming_flows_through_fused_chain():
    """NormalizeRows >> SignedHellingerMapper fuses into one
    FusedBatchTransformer; fed by a chunk-producing host stage it must
    keep yielding multiple index-carrying chunks (no silent
    materialization at the fusion boundary), with values identical to
    the serial unfused path."""
    from keystone_tpu.nodes.stats import NormalizeRows, SignedHellingerMapper

    rng = np.random.default_rng(0)
    items = [rng.normal(size=(6,)).astype(np.float32) for _ in range(8)]
    pipe = (_ChunkProducer().to_pipeline()
            >> NormalizeRows() >> SignedHellingerMapper())

    with overlap_override(False):
        PipelineEnv.get().set_optimizer(DefaultOptimizer(fuse=False))
        serial = pipe(HostDataset(items)).get()
    PipelineEnv.reset()

    with overlap_override(True, prefetch_depth=1), \
            dispatch_override(True, workers=4):
        res = pipe(HostDataset(items))
        # the optimized plan fused the two elementwise stages
        fused_labels = [
            op.label for op in res.executor.optimized_graph.operators.values()
            if op.label.startswith("Fused[")
        ]
        assert any("NormalizeRows" in l and "SignedHellingerMapper" in l
                   for l in fused_labels), fused_labels
        seen = {}
        n_chunks = 0
        for idxs, payload in res.stream():
            assert idxs is not None, "stream materialized at the fused stage"
            n_chunks += 1
            for i, item in zip(idxs, payload):
                seen[i] = item
        assert n_chunks >= 2, "producer chunks were collapsed"
    for i in range(len(items)):
        np.testing.assert_allclose(
            np.asarray(serial.items[i]), np.asarray(seen[i]), rtol=1e-5)


def test_fused_batch_transformer_chunkable_property():
    from keystone_tpu.nodes.stats import NormalizeRows, SignedHellingerMapper
    from keystone_tpu.nodes.util.basic import Densify
    from keystone_tpu.nodes.util.fusion import FusedBatchTransformer

    assert FusedBatchTransformer(
        [NormalizeRows(), SignedHellingerMapper()]).chunkable
    assert not FusedBatchTransformer(
        [NormalizeRows(), Densify()]).chunkable


# --------------------------------------------------------------------------
# fusion rule regressions


def _fusable_fn(name):
    class _F(Transformer):
        fusable = True

        def __init__(self):
            self._name = name

        @property
        def label(self):
            return self._name

        def apply(self, x):
            return x + 1.0

    return _F()


def test_fusion_never_crosses_fanout():
    """A node with two children terminates the chain: [A, B] fuses, the
    two fan-out consumers C and D stay separate."""
    from keystone_tpu.workflow.fusion_rule import NodeFusionRule
    from keystone_tpu.workflow.graph import Graph
    from keystone_tpu.workflow.operators import DatasetOperator

    g = Graph()
    g, data = g.add_node(
        DatasetOperator(Dataset.from_numpy(np.ones((4, 2), np.float32))), [])
    g, a = g.add_node(_fusable_fn("A"), [data])
    g, b = g.add_node(_fusable_fn("B"), [a])
    g, c = g.add_node(_fusable_fn("C"), [b])
    g, d = g.add_node(_fusable_fn("D"), [b])
    g, _ = g.add_sink(c)
    g, _ = g.add_sink(d)

    g2, _ = NodeFusionRule().apply((g, {}))
    labels = sorted(op.label for op in g2.operators.values()
                    if not op.label.startswith("Dataset"))
    assert labels == ["C", "D", "Fused[A >> B]"], labels


def test_chain_discovery_insensitive_to_id_order():
    """The same logical chain built with ascending and with descending
    node ids must fuse identically (discovery walks to the head from any
    member)."""
    from keystone_tpu.workflow.fusion_rule import NodeFusionRule
    from keystone_tpu.workflow.graph import Graph
    from keystone_tpu.workflow.operators import DatasetOperator

    ds = Dataset.from_numpy(np.ones((4, 2), np.float32))

    def fused_labels(g):
        g2, _ = NodeFusionRule().apply((g, {}))
        return sorted(op.label for op in g2.operators.values()
                      if op.label.startswith("Fused["))

    # ascending ids along the chain
    g = Graph()
    g, data = g.add_node(DatasetOperator(ds), [])
    g, a = g.add_node(_fusable_fn("A"), [data])
    g, b = g.add_node(_fusable_fn("B"), [a])
    g, c = g.add_node(_fusable_fn("C"), [b])
    g, _ = g.add_sink(c)
    forward = fused_labels(g)

    # descending ids: C gets the smallest node id, A the largest
    g = Graph()
    g, data = g.add_node(DatasetOperator(ds), [])
    g, c = g.add_node(_fusable_fn("C"), [data])  # deps fixed up below
    g, b = g.add_node(_fusable_fn("B"), [data])
    g, a = g.add_node(_fusable_fn("A"), [data])
    g = g.set_dependencies(b, [a]).set_dependencies(c, [b])
    g, _ = g.add_sink(c)
    reverse = fused_labels(g)

    assert forward == reverse == ["Fused[A >> B >> C]"]


def test_fused_chain_fit_produces_clean_fitted_pipeline():
    """Pipeline.fit() resolves FusedChainOperator nodes: the fitted
    pipeline carries the baked fused transformer, applies identically to
    the unfitted pipeline, and contains no estimator machinery."""
    from keystone_tpu.nodes.learning import LinearMapEstimator
    from keystone_tpu.nodes.stats import StandardScaler
    from keystone_tpu.nodes.util import MaxClassifier

    rng = np.random.default_rng(3)
    X = rng.normal(size=(16, 5)).astype(np.float32)
    Y = (2.0 * np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)] - 1.0)
    train = Dataset.from_numpy(X)

    pipe = (Transformer.from_function(lambda x: x * 1.0, name="ident")
            .to_pipeline()
            .and_then(StandardScaler(), train)
            .and_then(LinearMapEstimator(0.1), train, Dataset.from_numpy(Y))
            >> MaxClassifier())
    lazy = pipe(train).get().numpy()
    fitted = pipe.fit()
    out = fitted(train).numpy()
    np.testing.assert_array_equal(lazy, out)


# --------------------------------------------------------------------------
# acceptance gate: programs-per-run reduction + output identity


@pytest.mark.parametrize("example", ["RandomPatchCifar", "MnistRandomFFT"])
def test_dispatch_reduction_at_least_2x(example):
    """dispatch.programs_executed for the example's apply run drops ≥2×
    under the new optimizer plan vs the PR-3 baseline plan (and a
    fortiori vs the serial unfused path), with outputs
    allclose-identical to the serial unfused path (ISSUE 4 acceptance;
    the bench's `dispatch_count` tier records the same numbers)."""
    from keystone_tpu.dispatch_bench import measure_example

    base = measure_example(example, "serial_unfused")
    legacy = measure_example(example, "legacy")
    opt = measure_example(example, "optimized")
    assert opt["apply_run_programs"] > 0
    for name, ref in (("serial unfused", base), ("PR-3 legacy", legacy)):
        ratio = ref["apply_run_programs"] / opt["apply_run_programs"]
        assert ratio >= 2.0, (
            f"{example} vs {name}: {ref['apply_run_programs']} -> "
            f"{opt['apply_run_programs']} programs ({ratio:.2f}x)")
    # fit run must shrink too, never grow
    assert opt["fit_run_programs"] <= base["fit_run_programs"]
    np.testing.assert_allclose(
        opt["train_pred"], base["train_pred"], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        opt["test_pred"], base["test_pred"], rtol=1e-5, atol=1e-5)


def test_fused_chain_masks_padded_rows():
    """Padded-row regression (review finding): a fused chain containing
    a masking stage (StandardScalerModel) feeding a mask-less
    normal-equations fit must produce the same model as the unfused
    path when count is NOT a multiple of the device count (43 on the
    8-device mesh → 5 padded rows)."""
    from keystone_tpu.nodes.learning import LinearMapEstimator
    from keystone_tpu.nodes.stats import NormalizeRows, StandardScaler
    from keystone_tpu.nodes.util import ClassLabelIndicatorsFromInt

    rng = np.random.default_rng(7)
    n, d, k = 43, 6, 3
    X = np.abs(rng.normal(size=(n, d))).astype(np.float32) + 1.0
    y = rng.integers(0, k, n).astype(np.int32)

    def run(fuse):
        PipelineEnv.reset()
        PipelineEnv.get().set_optimizer(DefaultOptimizer(fuse=fuse))
        train = Dataset.from_numpy(X)
        labels = ClassLabelIndicatorsFromInt(k)(Dataset.from_numpy(y)).get()
        pipe = (NormalizeRows().to_pipeline()
                .and_then(StandardScaler(), train)
                .and_then(LinearMapEstimator(0.1), train, labels))
        out = pipe(train).get().numpy()
        PipelineEnv.reset()
        return out

    with overlap_override(False), dispatch_override(False):
        reference = run(fuse=False)
    fused = run(fuse=True)
    np.testing.assert_allclose(fused, reference, rtol=1e-5, atol=1e-6)


def test_gather_diamond_fuses_to_one_program():
    """The MnistRandomFFT-shaped gather diamond collapses: branches +
    zip + VectorCombiner become one Gather[...] program, values
    identical to the unfused path (including a padded count)."""
    from keystone_tpu.nodes.stats import LinearRectifier, RandomSignNode
    from keystone_tpu.nodes.util import VectorCombiner

    rng = np.random.default_rng(9)
    X = rng.normal(size=(21, 8)).astype(np.float32)  # 21: padded to 24
    pipe = Pipeline.gather([
        RandomSignNode(8, seed=i).to_pipeline() >> LinearRectifier(0.0)
        for i in range(3)
    ]) >> VectorCombiner()

    with overlap_override(False), dispatch_override(False):
        PipelineEnv.get().set_optimizer(DefaultOptimizer(fuse=False))
        reference = pipe(Dataset.from_numpy(X)).get().numpy()
    PipelineEnv.reset()
    res = pipe(Dataset.from_numpy(X))
    labels = [op.label
              for op in res.executor.optimized_graph.operators.values()]
    assert any("Gather[" in l for l in labels), labels
    np.testing.assert_allclose(res.get().numpy(), reference, rtol=1e-6)


def test_legacy_plan_matches_serial_outputs():
    """The PR-3-shaped legacy plan (fuse_apply=False) remains available
    and numerically identical — the bench tier's middle column."""
    from keystone_tpu.dispatch_bench import measure_example

    base = measure_example("RandomPatchCifar", "serial_unfused")
    legacy = measure_example("RandomPatchCifar", "legacy")
    assert legacy["apply_run_programs"] <= base["apply_run_programs"]
    np.testing.assert_allclose(
        legacy["test_pred"], base["test_pred"], rtol=1e-5, atol=1e-5)


# --------------------------------------------------------------------------
# nodes/stats chunkable audit (lint-style)


def test_stats_transformers_declare_chunkable():
    """Every elementwise transformer in nodes/stats/ must declare
    ``chunkable = True`` (PR 2 found ColumnSampler missing it; this
    pins the sweep). A new stats transformer must be classified here —
    elementwise or whole-dataset — or this test fails."""
    import inspect

    from keystone_tpu.nodes import stats as stats_pkg
    from keystone_tpu.nodes.stats import (
        normalization, random_features, scalers)
    from keystone_tpu.workflow.pipeline import Transformer

    ELEMENTWISE = {
        "NormalizeRows", "SignedHellingerMapper", "ColumnSampler",
        "CosineRandomFeatures", "RandomSignNode", "PaddedFFT",
        "LinearRectifier", "StandardScalerModel",
    }
    WHOLE_DATASET = {"Sampler"}  # reshapes the example axis: not chunkable

    found = set()
    for mod in (normalization, random_features, scalers):
        for name, cls in inspect.getmembers(mod, inspect.isclass):
            if not issubclass(cls, Transformer) or cls is Transformer:
                continue
            if cls.__module__ != mod.__name__:
                continue
            found.add(name)
            if name in ELEMENTWISE:
                assert getattr(cls, "chunkable", False), (
                    f"{name} is elementwise but does not declare "
                    "chunkable = True (KP302: streams silently "
                    "materialize at this stage)")
            elif name in WHOLE_DATASET:
                assert not getattr(cls, "chunkable", False), (
                    f"{name} reshapes the example axis; chunkable would "
                    "be wrong")
            else:
                raise AssertionError(
                    f"unclassified stats transformer {name}: add it to "
                    "ELEMENTWISE or WHOLE_DATASET in this test")
    assert ELEMENTWISE | WHOLE_DATASET <= found | {"ColumnSampler"}
