"""Static pipeline analyzer tests (keystone_tpu/analysis/).

The acceptance contract: `Pipeline.validate()` statically rejects a
shape-mismatched pipeline and flags a donated-buffer reuse fixture with
ZERO device allocation (asserted via `jax.live_arrays()` around the
validate call — everything routes through `jax.eval_shape`)."""

import jax
import numpy as np
import pytest

from keystone_tpu.analysis import (
    PipelineValidationError,
    Severity,
    SpecDataset,
    UNKNOWN,
    validate_graph,
)
from keystone_tpu.analysis.examples import EXAMPLES, build_example
from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
from keystone_tpu.nodes.stats import (
    LinearRectifier,
    PaddedFFT,
    RandomSignNode,
    StandardScaler,
)
from keystone_tpu.nodes.util import Cacher, MaxClassifier, VectorCombiner
from keystone_tpu.workflow import (
    DatasetOperator,
    DelegatingOperator,
    ExpressionOperator,
    Expression,
    GatherTransformerOperator,
    Graph,
    GraphExecutor,
    Pipeline,
    Transformer,
    TransformerOperator,
)
from keystone_tpu.workflow.analysis import children, descendants
from keystone_tpu.workflow.expressions import TransformerExpression


def _no_new_device_arrays():
    """Context asserting the wrapped block allocates nothing on device."""
    import contextlib

    @contextlib.contextmanager
    def ctx():
        before = {id(a) for a in jax.live_arrays()}
        yield
        fresh = [a for a in jax.live_arrays() if id(a) not in before]
        assert not fresh, (
            f"static validation allocated {len(fresh)} device array(s): "
            f"{[tuple(a.shape) for a in fresh]}"
        )

    return ctx()


# ------------------------------------------------------------ spec tier


def test_shape_mismatch_rejected_with_zero_device_allocation():
    pipe = RandomSignNode(8).to_pipeline() >> LinearRectifier(0.0)
    with _no_new_device_arrays():
        with pytest.raises(PipelineValidationError) as exc:
            pipe.validate((16,))
    report = exc.value.report
    assert any(d.rule == "KP101" for d in report.errors)
    # a PipelineValidationError is a ValueError (pre-analyzer contract)
    assert isinstance(exc.value, ValueError)


def test_matching_pipeline_validates_and_propagates_specs():
    branches = [
        RandomSignNode(16, seed=i) >> PaddedFFT() >> LinearRectifier(0.0)
        for i in range(2)
    ]
    pipe = Pipeline.gather(branches) >> VectorCombiner()
    with _no_new_device_arrays():
        report = pipe.validate((16,))
    assert report.ok
    out = report.specs[pipe.sink]
    # two rfft halves of a 16-wide padded FFT, concatenated
    assert tuple(out.element.shape) == (16,)


def test_estimator_fit_spec_and_count_mismatch():
    feat = RandomSignNode(8).to_pipeline()
    data = SpecDataset((8,), np.float32, count=32, name="d")
    good = SpecDataset((3,), np.float32, count=32, name="l")
    pred = feat.and_then(
        BlockLeastSquaresEstimator(8, 1, 0.1), data, good) >> MaxClassifier()
    report = pred.validate((8,))
    assert report.ok
    assert tuple(report.specs[pred.sink].element.shape) == ()  # argmax label

    bad = SpecDataset((3,), np.float32, count=33, name="l2")
    pred2 = feat.and_then(BlockLeastSquaresEstimator(8, 1, 0.1), data, bad)
    with pytest.raises(PipelineValidationError) as exc:
        pred2.validate((8,))
    assert any(d.rule == "KP102" for d in exc.value.report.errors)


def test_unknown_specs_propagate_without_false_errors():
    class _HostOnly(Transformer):
        def apply(self, x):
            return x.upper()  # host string code; tracer cannot enter

    pipe = _HostOnly().to_pipeline() >> _HostOnly()
    report = pipe.validate(None)
    assert report.ok
    assert report.specs[pipe.sink] is UNKNOWN or \
        report.specs[pipe.sink].element is UNKNOWN


# ------------------------------------------------------ structural tier


def _two_node_cycle():
    class _Id(TransformerOperator):
        def batch_transform(self, inputs):
            return inputs[0]

    g = Graph()
    g, src = g.add_source()
    g, a = g.add_node(_Id(), [src])
    g, b = g.add_node(_Id(), [a])
    g = g.set_dependencies(a, [b])
    g, sink = g.add_sink(b)
    return g, sink


def test_cycle_detected_statically_and_at_executor():
    g, sink = _two_node_cycle()
    report = validate_graph(g, level="structure")
    assert any(d.rule == "KP001" for d in report.errors)
    with pytest.raises(PipelineValidationError):
        GraphExecutor(g, optimize=False).execute(sink)


def test_duplicated_dependency_is_not_a_false_cycle():
    # CSE merges identical gather branches, leaving the Gather node with
    # the same dependency twice — toposort must not report a cycle and
    # the executor must still force the pipeline.
    t = RandomSignNode(8)
    pipe = Pipeline.gather([t.to_pipeline(), t.to_pipeline()])
    assert pipe.validate((8,), raise_on_error=False).ok
    from keystone_tpu.data.dataset import Dataset

    out = pipe(Dataset(np.ones((4, 8), np.float32))).get()
    assert len(out) == 4


def test_structural_error_reraised_on_retry():
    g, sink = _two_node_cycle()
    ex = GraphExecutor(g, optimize=False)
    with pytest.raises(PipelineValidationError):
        ex.execute(sink)
    with pytest.raises(PipelineValidationError):  # not silently skipped
        ex.execute(sink)


def test_fit_before_use_flagged():
    class _Dense(Transformer):
        def apply(self, x):
            return x

    g = Graph()
    g, data = g.add_node(
        DatasetOperator(SpecDataset((4,), count=8), "x"), [])
    g, est = g.add_node(StandardScaler(), [data])
    g, bad = g.add_node(_Dense(), [est])  # estimator output used as data
    g, sink = g.add_sink(bad)
    report = validate_graph(g, level="structure")
    assert any(d.rule == "KP003" and d.severity == Severity.ERROR
               for d in report.errors)


def test_delegate_without_estimator_flagged():
    g = Graph()
    g, data = g.add_node(
        DatasetOperator(SpecDataset((4,), count=8), "x"), [])
    g, delegate = g.add_node(DelegatingOperator(), [data, data])
    g, sink = g.add_sink(delegate)
    report = validate_graph(g, level="structure")
    assert any(d.rule == "KP004" for d in report.errors)
    # the executor's automatic structural gate keeps the old
    # ValueError-at-force contract, just earlier and with a rule id
    with pytest.raises(ValueError):
        GraphExecutor(g, optimize=False).execute(sink)


def test_dangling_source_warns():
    g = Graph()
    g, _src = g.add_source()
    g, data = g.add_node(
        DatasetOperator(SpecDataset((4,), count=8), "x"), [])
    g, sink = g.add_sink(data)
    report = validate_graph(g, level="structure")
    assert any(d.rule == "KP005" for d in report.warnings)
    assert report.ok  # warnings only


# --------------------------------------------------------- hazard tier


class _StreamOrigin(Transformer):
    """Fixture stream producer (overridden streaming batch path)."""

    def apply(self, x):
        return x

    def apply_batch_stream(self, data):
        yield list(range(len(data.items))), list(data.items)


class _DenseStage(Transformer):
    def apply(self, x):
        return x


def test_donated_buffer_reuse_flagged_with_zero_device_allocation():
    class _DonatingSolver(TransformerOperator):
        donates_deps = (0,)

        def batch_transform(self, inputs):
            return inputs[0]

    g = Graph()
    g, producer = g.add_node(
        DatasetOperator(SpecDataset((128,), count=64), "X"), [])
    g, donor = g.add_node(_DonatingSolver(), [producer])
    g, sink1 = g.add_sink(donor)
    g, sink2 = g.add_sink(producer)  # producer still reachable: hazard
    with _no_new_device_arrays():
        report = validate_graph(g)
    kp301 = report.by_rule("KP301")
    assert kp301 and kp301[0].severity == Severity.ERROR
    # suppression channel
    assert validate_graph(g, ignore=["KP301"]).ok


def test_donation_reuse_within_same_node_flagged():
    class _DonatingSolver(TransformerOperator):
        donates_deps = (0,)

        def batch_transform(self, inputs):
            return inputs[0]

    g = Graph()
    g, producer = g.add_node(
        DatasetOperator(SpecDataset((128,), count=64), "X"), [])
    # the node reads the donated buffer AGAIN at dep index 1 (the
    # duplicated-dep topology CSE-merged branches produce)
    g, donor = g.add_node(_DonatingSolver(), [producer, producer])
    g, sink = g.add_sink(donor)
    report = validate_graph(g)
    kp301 = report.by_rule("KP301")
    assert kp301 and "dependency index 1" in kp301[0].message


def test_donation_without_reuse_is_clean():
    class _DonatingSolver(TransformerOperator):
        donates_deps = (0,)

        def batch_transform(self, inputs):
            return inputs[0]

    g = Graph()
    g, producer = g.add_node(
        DatasetOperator(SpecDataset((128,), count=64), "X"), [])
    g, donor = g.add_node(_DonatingSolver(), [producer])
    g, sink = g.add_sink(donor)
    assert not validate_graph(g).by_rule("KP301")


def test_streaming_materialization_warning():
    pipe = _StreamOrigin().to_pipeline() >> _DenseStage()
    report = pipe.validate(None, raise_on_error=False)
    assert report.by_rule("KP302")

    class _Chunkable(_DenseStage):
        chunkable = True

    ok = _StreamOrigin().to_pipeline() >> _Chunkable()
    assert not ok.validate(None, raise_on_error=False).by_rule("KP302")


def test_cache_on_streaming_stage_warning():
    pipe = _StreamOrigin().to_pipeline() >> Cacher("c")
    report = pipe.validate(None, raise_on_error=False)
    assert report.by_rule("KP303")


# --------------------------------------------------------- memory tier


def test_memory_budget_warnings():
    big = SpecDataset((1024, 256), np.float32, count=256, name="big")  # 256 MiB
    pipe = _DenseStage().to_pipeline() >> Cacher("keep")
    applied = pipe.apply(big)
    report = applied.validate(
        level="memory", hbm_budget_bytes=64 << 20, raise_on_error=False)
    rules = {d.rule for d in report.warnings}
    assert "KP201" in rules and "KP202" in rules
    assert report.memory.peak_bytes >= 256 << 20
    # a generous budget is quiet
    quiet = applied.validate(
        level="memory", hbm_budget_bytes=16 << 30, raise_on_error=False)
    assert not quiet.warnings


# ------------------------------------------------- examples + CLI gate


@pytest.mark.lint
@pytest.mark.parametrize("name", sorted(EXAMPLES))
def test_example_pipelines_validate(name):
    pipeline, source_spec = build_example(name)
    report = pipeline.validate(source_spec, raise_on_error=False)
    assert not report.errors, "\n".join(map(str, report.errors))


# ------------------------------------------------ reverse-adjacency index


def test_users_index_matches_children_descendants():
    branches = [
        RandomSignNode(16, seed=i) >> PaddedFFT() >> LinearRectifier(0.0)
        for i in range(3)
    ]
    g = (Pipeline.gather(branches) >> VectorCombiner()).graph

    def brute_children(vid):
        out = set()
        for n, deps in g.dependencies.items():
            if vid in deps:
                out.add(n)
        for s, d in g.sink_dependencies.items():
            if d == vid:
                out.add(s)
        return out

    for vid in list(g.operators) + list(g.sources):
        assert set(g.users_of(vid)) == brute_children(vid)
        assert children(g, vid) == brute_children(vid)
    # descendants of the source reach every node and sink
    assert descendants(g, next(iter(g.sources))) == (
        set(g.operators) | set(g.sink_dependencies))


# ------------------------------------------------------- label audit


def test_operator_labels_stable_unique_and_diagnostic_keyed():
    ops = {
        "dataset-a": DatasetOperator(SpecDataset((2,), count=2), "a"),
        "dataset-b": DatasetOperator(SpecDataset((2,), count=2), "b"),
        "gather": GatherTransformerOperator(),
        "delegate": DelegatingOperator(),
        "saved-1": ExpressionOperator(Expression.of(1), "s1"),
        "saved-2": ExpressionOperator(
            TransformerExpression(lambda: None), "s2"),
        "cacher-1": Cacher("c1"),
        "cacher-2": Cacher("c2"),
        "fn": Transformer.from_function(lambda x: x, name="fn1"),
        "sign": RandomSignNode(4),
        "scaler": StandardScaler(),
        "solver": BlockLeastSquaresEstimator(2, 1),
        "argmax": MaxClassifier(),
    }
    labels = {k: op.label for k, op in ops.items()}
    for k, lab in labels.items():
        assert isinstance(lab, str) and lab, f"{k} has an empty label"
        assert ops[k].label == lab, f"{k} label is unstable"
    # named operators must not collide
    assert labels["dataset-a"] != labels["dataset-b"]
    assert labels["saved-1"] != labels["saved-2"]
    assert labels["cacher-1"] != labels["cacher-2"]
    assert labels["gather"] == "Gather"

    # diagnostics key on label@vertex: unique across every example graph
    for name in sorted(EXAMPLES):
        g = build_example(name)[0].graph
        anchors = {
            f"{g.get_operator(n).label}@{n}" for n in g.operators
        }
        assert len(anchors) == len(g.operators), name
