// keystone_io — native host-side data-plane kernels.
//
// The reference's native layer (src/main/cpp, Makefile:1-121) accelerates
// compute (VLFeat SIFT, enceval GMM/FV) — those moved to XLA where they
// belong on TPU. What remains host-bound on a TPU system is the ingest
// path: parsing binary/CSV datasets into pinned float32 batches fast
// enough to keep the chips fed (SURVEY.md §7 hard part (f)). This library
// provides multithreaded parsers exposed via a C ABI for ctypes:
//
//   - CIFAR binary records  -> float32 NHWC images + int32 labels
//   - dense float CSV       -> float32 row-major matrix
//   - whitespace tokenization offsets for a UTF-8 corpus buffer
//
// Build: make -C native   (produces libkeystone_io.so)

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- CIFAR

// records: n x (1 label byte + 3072 channel-planar bytes)
// out_images: n*32*32*3 float32 (NHWC), out_labels: n int32
// Returns 0 on success.
int ks_parse_cifar(const uint8_t* records, int64_t n_records,
                   float* out_images, int32_t* out_labels, int num_threads) {
  if (!records || !out_images || !out_labels || n_records < 0) return 1;
  const int64_t rec = 1 + 3072;
  if (num_threads < 1) num_threads = 1;

  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const uint8_t* r = records + i * rec;
      out_labels[i] = r[0];
      const uint8_t* px = r + 1;
      float* img = out_images + i * 3072;
      // channel-planar (3,32,32) -> HWC (32,32,3)
      for (int y = 0; y < 32; ++y) {
        for (int x = 0; x < 32; ++x) {
          const int p = y * 32 + x;
          float* o = img + (p * 3);
          o[0] = static_cast<float>(px[p]);
          o[1] = static_cast<float>(px[1024 + p]);
          o[2] = static_cast<float>(px[2048 + p]);
        }
      }
    }
  };

  if (num_threads == 1 || n_records < 1024) {
    worker(0, n_records);
  } else {
    std::vector<std::thread> ts;
    int64_t chunk = (n_records + num_threads - 1) / num_threads;
    for (int t = 0; t < num_threads; ++t) {
      int64_t lo = t * chunk;
      int64_t hi = lo + chunk > n_records ? n_records : lo + chunk;
      if (lo >= hi) break;
      ts.emplace_back(worker, lo, hi);
    }
    for (auto& t : ts) t.join();
  }
  return 0;
}

// ----------------------------------------------------------------- CSV

// Count rows and columns of a dense delimited float file already in
// memory. Returns 0 on success; *out_rows/*out_cols receive the shape.
int ks_csv_shape(const char* buf, int64_t len, char delim,
                 int64_t* out_rows, int64_t* out_cols) {
  if (!buf || !out_rows || !out_cols) return 1;
  int64_t rows = 0, cols = 0, cur_cols = 1;
  bool any = false;
  for (int64_t i = 0; i < len; ++i) {
    char c = buf[i];
    if (c == delim) {
      ++cur_cols;
    } else if (c == '\n') {
      if (any) {
        if (cols == 0) cols = cur_cols;
        else if (cols != cur_cols) return 2;  // ragged
        ++rows;
      }
      cur_cols = 1;
      any = false;
    } else if (c != '\r' && c != ' ' && c != '\t') {
      any = true;
    }
  }
  if (any) {  // trailing row without newline
    if (cols == 0) cols = cur_cols;
    else if (cols != cur_cols) return 2;
    ++rows;
  }
  *out_rows = rows;
  *out_cols = cols;
  return 0;
}

// Parse the dense float CSV into out (rows*cols float32), multithreaded
// by row ranges (rows are found by scanning newline offsets first).
int ks_parse_csv(const char* buf, int64_t len, char delim,
                 int64_t rows, int64_t cols, float* out, int num_threads) {
  if (!buf || !out) return 1;
  // index row starts
  std::vector<int64_t> starts;
  starts.reserve(rows + 1);
  starts.push_back(0);
  for (int64_t i = 0; i < len; ++i)
    if (buf[i] == '\n') starts.push_back(i + 1);
  // drop trailing empty segments
  while (starts.size() > 1 && starts.back() >= len) starts.pop_back();
  if (static_cast<int64_t>(starts.size()) < rows) return 3;

  std::atomic<int> err{0};
  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      const char* p = buf + starts[r];
      const char* end = (r + 1 < static_cast<int64_t>(starts.size()))
                            ? buf + starts[r + 1]
                            : buf + len;
      float* o = out + r * cols;
      for (int64_t c = 0; c < cols; ++c) {
        // strict field scan: empty fields (',,' or trailing ',') are an
        // error, never silently filled from the next row (strtof on its
        // own would skip the newline and shift all following values)
        while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
        if (p >= end || *p == delim || *p == '\n') { err.store(5); return; }
        char* next = nullptr;
        o[c] = strtof(p, &next);
        if (next == p) { err.store(4); return; }
        p = next;
        while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
        if (c + 1 < cols) {
          if (p < end && *p == delim) ++p;
          else { err.store(6); return; }
        }
      }
    }
  };

  if (num_threads < 1) num_threads = 1;
  if (num_threads == 1 || rows < 256) {
    worker(0, rows);
  } else {
    std::vector<std::thread> ts;
    int64_t chunk = (rows + num_threads - 1) / num_threads;
    for (int t = 0; t < num_threads; ++t) {
      int64_t lo = t * chunk;
      int64_t hi = lo + chunk > rows ? rows : lo + chunk;
      if (lo >= hi) break;
      ts.emplace_back(worker, lo, hi);
    }
    for (auto& t : ts) t.join();
  }
  return err.load();
}

// ------------------------------------------------------------ tokenize

// Whitespace-tokenize a UTF-8 buffer: writes (start, end) byte offsets
// into out_spans (capacity max_tokens pairs). Returns token count, or -1
// on error. A second call with a larger buffer handles overflow.
int64_t ks_tokenize_ws(const char* buf, int64_t len,
                       int64_t* out_spans, int64_t max_tokens) {
  if (!buf || !out_spans) return -1;
  int64_t count = 0;
  int64_t i = 0;
  while (i < len) {
    while (i < len && (buf[i] == ' ' || buf[i] == '\n' || buf[i] == '\t' ||
                       buf[i] == '\r')) ++i;
    if (i >= len) break;
    int64_t start = i;
    while (i < len && buf[i] != ' ' && buf[i] != '\n' && buf[i] != '\t' &&
           buf[i] != '\r') ++i;
    if (count < max_tokens) {
      out_spans[2 * count] = start;
      out_spans[2 * count + 1] = i;
    }
    ++count;
  }
  return count;
}

}  // extern "C"
