// keystone_io — native host-side data-plane kernels.
//
// The reference's native layer (src/main/cpp, Makefile:1-121) accelerates
// compute (VLFeat SIFT, enceval GMM/FV) — those moved to XLA where they
// belong on TPU. What remains host-bound on a TPU system is the ingest
// path: parsing binary/CSV datasets into pinned float32 batches fast
// enough to keep the chips fed (SURVEY.md §7 hard part (f)). This library
// provides multithreaded parsers exposed via a C ABI for ctypes:
//
//   - CIFAR binary records  -> float32 NHWC images + int32 labels
//   - dense float CSV       -> float32 row-major matrix
//   - whitespace tokenization offsets for a UTF-8 corpus buffer
//
// Build: make -C native   (produces libkeystone_io.so)

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- CIFAR

// records: n x (1 label byte + 3072 channel-planar bytes)
// out_images: n*32*32*3 float32 (NHWC), out_labels: n int32
// Returns 0 on success.
int ks_parse_cifar(const uint8_t* records, int64_t n_records,
                   float* out_images, int32_t* out_labels, int num_threads) {
  if (!records || !out_images || !out_labels || n_records < 0) return 1;
  const int64_t rec = 1 + 3072;
  if (num_threads < 1) num_threads = 1;

  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const uint8_t* r = records + i * rec;
      out_labels[i] = r[0];
      const uint8_t* px = r + 1;
      float* img = out_images + i * 3072;
      // channel-planar (3,32,32) -> HWC (32,32,3)
      for (int y = 0; y < 32; ++y) {
        for (int x = 0; x < 32; ++x) {
          const int p = y * 32 + x;
          float* o = img + (p * 3);
          o[0] = static_cast<float>(px[p]);
          o[1] = static_cast<float>(px[1024 + p]);
          o[2] = static_cast<float>(px[2048 + p]);
        }
      }
    }
  };

  if (num_threads == 1 || n_records < 1024) {
    worker(0, n_records);
  } else {
    std::vector<std::thread> ts;
    int64_t chunk = (n_records + num_threads - 1) / num_threads;
    for (int t = 0; t < num_threads; ++t) {
      int64_t lo = t * chunk;
      int64_t hi = lo + chunk > n_records ? n_records : lo + chunk;
      if (lo >= hi) break;
      ts.emplace_back(worker, lo, hi);
    }
    for (auto& t : ts) t.join();
  }
  return 0;
}

// ----------------------------------------------------------------- CSV

// Count rows and columns of a dense delimited float file already in
// memory. Returns 0 on success; *out_rows/*out_cols receive the shape.
int ks_csv_shape(const char* buf, int64_t len, char delim,
                 int64_t* out_rows, int64_t* out_cols) {
  if (!buf || !out_rows || !out_cols) return 1;
  int64_t rows = 0, cols = 0, cur_cols = 1;
  bool any = false;
  for (int64_t i = 0; i < len; ++i) {
    char c = buf[i];
    if (c == delim) {
      ++cur_cols;
    } else if (c == '\n') {
      if (any) {
        if (cols == 0) cols = cur_cols;
        else if (cols != cur_cols) return 2;  // ragged
        ++rows;
      }
      cur_cols = 1;
      any = false;
    } else if (c != '\r' && c != ' ' && c != '\t') {
      any = true;
    }
  }
  if (any) {  // trailing row without newline
    if (cols == 0) cols = cur_cols;
    else if (cols != cur_cols) return 2;
    ++rows;
  }
  *out_rows = rows;
  *out_cols = cols;
  return 0;
}

// Parse the dense float CSV into out (rows*cols float32), multithreaded
// by row ranges (rows are found by scanning newline offsets first).
int ks_parse_csv(const char* buf, int64_t len, char delim,
                 int64_t rows, int64_t cols, float* out, int num_threads) {
  if (!buf || !out) return 1;
  // index row starts
  std::vector<int64_t> starts;
  starts.reserve(rows + 1);
  starts.push_back(0);
  for (int64_t i = 0; i < len; ++i)
    if (buf[i] == '\n') starts.push_back(i + 1);
  // drop trailing empty segments
  while (starts.size() > 1 && starts.back() >= len) starts.pop_back();
  if (static_cast<int64_t>(starts.size()) < rows) return 3;

  std::atomic<int> err{0};
  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      const char* p = buf + starts[r];
      const char* end = (r + 1 < static_cast<int64_t>(starts.size()))
                            ? buf + starts[r + 1]
                            : buf + len;
      float* o = out + r * cols;
      for (int64_t c = 0; c < cols; ++c) {
        // strict field scan: empty fields (',,' or trailing ',') are an
        // error, never silently filled from the next row (strtof on its
        // own would skip the newline and shift all following values)
        while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
        if (p >= end || *p == delim || *p == '\n') { err.store(5); return; }
        char* next = nullptr;
        o[c] = strtof(p, &next);
        if (next == p) { err.store(4); return; }
        p = next;
        while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
        if (c + 1 < cols) {
          if (p < end && *p == delim) ++p;
          else { err.store(6); return; }
        }
      }
    }
  };

  if (num_threads < 1) num_threads = 1;
  if (num_threads == 1 || rows < 256) {
    worker(0, rows);
  } else {
    std::vector<std::thread> ts;
    int64_t chunk = (rows + num_threads - 1) / num_threads;
    for (int t = 0; t < num_threads; ++t) {
      int64_t lo = t * chunk;
      int64_t hi = lo + chunk > rows ? rows : lo + chunk;
      if (lo >= hi) break;
      ts.emplace_back(worker, lo, hi);
    }
    for (auto& t : ts) t.join();
  }
  return err.load();
}

// ------------------------------------------------------------ tokenize

// Whitespace-tokenize a UTF-8 buffer: writes (start, end) byte offsets
// into out_spans (capacity max_tokens pairs). Returns token count, or -1
// on error. A second call with a larger buffer handles overflow.
int64_t ks_tokenize_ws(const char* buf, int64_t len,
                       int64_t* out_spans, int64_t max_tokens) {
  if (!buf || !out_spans) return -1;
  int64_t count = 0;
  int64_t i = 0;
  while (i < len) {
    while (i < len && (buf[i] == ' ' || buf[i] == '\n' || buf[i] == '\t' ||
                       buf[i] == '\r')) ++i;
    if (i >= len) break;
    int64_t start = i;
    while (i < len && buf[i] != ' ' && buf[i] != '\n' && buf[i] != '\t' &&
           buf[i] != '\r') ++i;
    if (count < max_tokens) {
      out_spans[2 * count] = start;
      out_spans[2 * count + 1] = i;
    }
    ++count;
  }
  return count;
}

// ------------------------------------------------------------- tar (ustar)
//
// The reference streams training archives with commons-compress
// (loaders/ImageLoaderUtils.scala:56-94). Here: an in-memory ustar index
// over an mmap'able buffer — offsets let Python slice entries zero-copy.

static int64_t tar_octal(const uint8_t* p, int n) {
  // GNU base-256 extension: high bit of first byte set.
  if (p[0] & 0x80) {
    int64_t v = p[0] & 0x7f;
    for (int i = 1; i < n; ++i) v = (v << 8) | p[i];
    return v;
  }
  int64_t v = 0;
  for (int i = 0; i < n; ++i) {
    const uint8_t c = p[i];
    if (c == 0 || c == ' ') { if (v) break; else continue; }
    if (c < '0' || c > '7') return -1;
    v = v * 8 + (c - '0');
  }
  return v;
}

// Scan a tar buffer. Fills up to `cap` entries: data offset, data size,
// and the entry name (NUL-terminated, truncated to name_cap incl. NUL).
// Returns the total number of regular-file entries (may exceed cap), or
// -1 on a malformed archive.
int64_t ks_tar_index(const uint8_t* buf, int64_t len, int64_t* out_offsets,
                     int64_t* out_sizes, char* out_names, int64_t name_cap,
                     int64_t cap) {
  if (!buf || len < 0) return -1;
  int64_t pos = 0, count = 0;
  char longname[4096];
  bool have_longname = false;
  while (pos + 512 <= len) {
    const uint8_t* h = buf + pos;
    bool empty = true;
    for (int i = 0; i < 512 && empty; ++i) empty = (h[i] == 0);
    if (empty) break;  // end-of-archive marker
    const int64_t size = tar_octal(h + 124, 12);
    // overflow-safe bounds check (size can be attacker-controlled)
    if (size < 0 || size > len - 512 - pos) return -1;
    const uint8_t type = h[156];
    const int64_t data = pos + 512;
    if (type == 'L') {  // GNU longname: data block holds the real name
      int64_t m = size < (int64_t)sizeof(longname) - 1
                      ? size : (int64_t)sizeof(longname) - 1;
      memcpy(longname, buf + data, m);
      longname[m] = 0;
      have_longname = true;
    } else if (type == 'x' || type == 'X') {
      // PAX extended header (Python tarfile's default format): records are
      // "<len> key=value\n"; a "path" record overrides the next entry's name.
      const uint8_t* p = buf + data;
      int64_t rem = size;
      while (rem > 0) {
        int64_t rl = 0, di = 0;
        while (di < rem && p[di] >= '0' && p[di] <= '9') {
          rl = rl * 10 + (p[di] - '0');
          ++di;
        }
        if (di >= rem || p[di] != ' ' || rl <= 0 || rl > rem) break;
        const uint8_t* kv = p + di + 1;
        const int64_t kvlen = rl - di - 1;
        if (kvlen > 5 && memcmp(kv, "path=", 5) == 0) {
          int64_t m = kvlen - 5;
          if (m > 0 && kv[5 + m - 1] == '\n') --m;
          if (m > (int64_t)sizeof(longname) - 1) m = sizeof(longname) - 1;
          memcpy(longname, kv + 5, m);
          longname[m] = 0;
          have_longname = true;
        }
        p += rl;
        rem -= rl;
      }
    } else if (type == 0 || type == '0') {  // regular file
      if (count < cap) {
        out_offsets[count] = data;
        out_sizes[count] = size;
        char* dst = out_names + count * name_cap;
        if (have_longname) {
          strncpy(dst, longname, name_cap - 1);
          dst[name_cap - 1] = 0;
        } else {
          // POSIX ustar ("ustar\0"): optional 155-byte prefix at 345.
          // Old-GNU ("ustar  ") reuses that region for atime — skip it.
          char name[101], prefix[156];
          memcpy(name, h, 100); name[100] = 0;
          memcpy(prefix, h + 345, 155); prefix[155] = 0;
          const bool posix_ustar = memcmp(h + 257, "ustar\0", 6) == 0;
          if (posix_ustar && prefix[0])
            snprintf(dst, name_cap, "%s/%s", prefix, name);
          else {
            strncpy(dst, name, name_cap - 1);
            dst[name_cap - 1] = 0;
          }
        }
      }
      have_longname = false;
      ++count;
    } else if (type != 'g') {
      // 'g' (pax global) keeps any pending longname; others consume it
      have_longname = false;
    }
    pos = data + ((size + 511) / 512) * 512;
  }
  return count;
}

}  // extern "C"

// ------------------------------------------------------------ JPEG decode

#include <csetjmp>
#include <jpeglib.h>

namespace {
struct KsJpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jump;
};
void ks_jpeg_error_exit(j_common_ptr cinfo) {
  longjmp(reinterpret_cast<KsJpegErr*>(cinfo->err)->jump, 1);
}

// Decode one JPEG to float32 RGB HWC into out (capacity out_cap floats).
// Writes dims; returns 0 ok, 1 decode error, 2 capacity exceeded.
int decode_one(const uint8_t* data, int64_t len, float* out, int64_t out_cap,
               int32_t* h, int32_t* w, int32_t* c) {
  jpeg_decompress_struct cinfo;
  KsJpegErr jerr;
  // Declared before setjmp: a longjmp from mid-decode must not skip the
  // destructor of a live vector (UB + leak per corrupt image).
  std::vector<uint8_t> row;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = ks_jpeg_error_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const int W = cinfo.output_width, H = cinfo.output_height;
  const int C = cinfo.output_components;  // 3 after JCS_RGB
  *h = H; *w = W; *c = C;
  if (static_cast<int64_t>(H) * W * C > out_cap) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return 2;
  }
  row.resize(static_cast<size_t>(W) * C);
  uint8_t* rowp = row.data();
  while (cinfo.output_scanline < cinfo.output_height) {
    const int y = cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &rowp, 1);
    float* o = out + static_cast<int64_t>(y) * W * C;
    for (int i = 0; i < W * C; ++i) o[i] = static_cast<float>(rowp[i]);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}
}  // namespace

extern "C" {

// Header-only scan: dims of one JPEG without full decode.
int ks_jpeg_dims(const uint8_t* data, int64_t len, int32_t* h, int32_t* w,
                 int32_t* c) {
  jpeg_decompress_struct cinfo;
  KsJpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = ks_jpeg_error_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  *h = cinfo.image_height;
  *w = cinfo.image_width;
  *c = 3;  // decoded as JCS_RGB
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Multithreaded batch decode from one backing buffer (e.g. a tar file):
// image i lives at buf[offsets[i] : offsets[i]+sizes[i]] and decodes into
// out[out_offsets[i] : out_offsets[i]+out_caps[i]] (float32 RGB HWC).
// Per-image status in out_status (0 ok / 1 bad jpeg / 2 overflow); dims in
// out_dims (n x 3: h, w, c). Returns count of successful decodes.
int64_t ks_jpeg_decode_batch(const uint8_t* buf, const int64_t* offsets,
                             const int64_t* sizes, int64_t n, float* out,
                             const int64_t* out_offsets,
                             const int64_t* out_caps, int32_t* out_dims,
                             int32_t* out_status, int num_threads) {
  if (!buf || !offsets || !sizes || !out || n < 0) return -1;
  if (num_threads < 1) num_threads = 1;
  std::atomic<int64_t> next(0), ok(0);
  auto worker = [&]() {
    for (;;) {
      const int64_t i = next.fetch_add(1);
      if (i >= n) return;
      int32_t h = 0, w = 0, c = 0;
      const int rc = decode_one(buf + offsets[i], sizes[i],
                                out + out_offsets[i], out_caps[i], &h, &w, &c);
      out_dims[3 * i] = h; out_dims[3 * i + 1] = w; out_dims[3 * i + 2] = c;
      out_status[i] = rc;
      if (rc == 0) ok.fetch_add(1);
    }
  };
  if (num_threads == 1 || n < 2) {
    worker();
  } else {
    std::vector<std::thread> ts;
    const int t = static_cast<int>(std::min<int64_t>(num_threads, n));
    for (int k = 0; k < t; ++k) ts.emplace_back(worker);
    for (auto& th : ts) th.join();
  }
  return ok.load();
}

}  // extern "C"
