"""Benchmark: RandomPatchCifar featurize+solve throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Baseline: the driver-defined north star is RandomPatchCifar over 50 000
CIFAR images reaching >=84% accuracy in <60 s on a v5e-16 pod, i.e.
833 images/sec across 16 chips (BASELINE.md). vs_baseline compares this
single-chip warm throughput against the full-pod 833 img/s target, so
vs_baseline > 1.0 means one chip alone already beats the whole-pod
reference rate.

Wedge resilience: the TPU here sits behind the axon tunnel, which can
wedge for hours (any device op hangs until killed). This driver-facing
entry therefore NEVER touches the device in-process. It
  1. probes device liveness in a subprocess with a hard timeout,
  2. runs the workload in a killable child process (``--child``) that
     emits phase markers as it progresses,
  3. retries within a deadline, and
  4. ALWAYS prints valid JSON — on persistent failure the record carries
     an "error" plus the last-known-good measurement from
     BENCH_LAST_GOOD.json (marked "stale": true) instead of a traceback.

Uses the learnable synthetic CIFAR task (no dataset egress in this
environment — see BENCH notes); pass --train-path for real CIFAR binaries.
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
LAST_GOOD = os.path.join(REPO, "BENCH_LAST_GOOD.json")
BASELINE_IMGS_PER_SEC = 833.0  # north-star pod rate: 50k imgs / 60 s on v5e-16

PROBE_SRC = (
    "import os, jax;"
    "jax.config.update('jax_platforms', 'cpu') "
    "if os.environ.get('KEYSTONE_BACKEND') == 'cpu' else None;"
    "import jax.numpy as jnp;"
    "print('devices', jax.devices());"
    "print('probe_sum', float(jnp.ones((2, 2)).sum()))"
)


def log(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def probe_device(timeout_s: float) -> bool:
    """True iff a trivial device op completes within timeout_s (run in a
    subprocess so a wedged tunnel cannot hang this process)."""
    try:
        r = subprocess.run(
            [sys.executable, "-u", "-c", PROBE_SRC],
            timeout=timeout_s, capture_output=True, text=True, cwd=REPO,
        )
        ok = r.returncode == 0 and "probe_sum" in r.stdout
        log(f"liveness probe: {'ok' if ok else 'failed'}"
            + ("" if ok else f" (rc={r.returncode}, {r.stderr.strip()[-200:]})"))
        return ok
    except subprocess.TimeoutExpired:
        log(f"liveness probe: timed out after {timeout_s:.0f}s (tunnel wedged)")
        return False


def run_child(args, timeout_s: float):
    """Run the measured workload in a child; returns (detail dict | None,
    phases list). Phase markers let a killed run report partial progress."""
    cmd = [
        sys.executable, "-u", os.path.abspath(__file__), "--child",
        "--n-train", str(args.n_train), "--n-test", str(args.n_test),
        "--num-filters", str(args.num_filters),
        "--flagship-n", str(args.flagship_n),
        "--flagship-d", str(args.flagship_d),
        "--flagship-k", str(args.flagship_k),
    ]
    if args.skip_flagship:
        cmd += ["--skip-flagship"]
    cmd += ["--featurize-batch", str(args.featurize_batch),
            "--featurize-reps", str(args.featurize_reps),
            "--krr-n", str(args.krr_n), "--krr-d", str(args.krr_d),
            "--krr-k", str(args.krr_k)]
    if args.skip_featurize_tier:
        cmd += ["--skip-featurize-tier"]
    if args.skip_krr:
        cmd += ["--skip-krr"]
    cmd += ["--overlap-n", str(args.overlap_n),
            "--overlap-chunk", str(args.overlap_chunk)]
    if args.skip_overlap_tier:
        cmd += ["--skip-overlap-tier"]
    if args.skip_ooc_tier:
        cmd += ["--skip-ooc-tier"]
    if args.skip_dispatch_tier:
        cmd += ["--skip-dispatch-tier"]
    if args.skip_telemetry_tier:
        cmd += ["--skip-telemetry-tier"]
    if args.skip_serving_tier:
        cmd += ["--skip-serving-tier"]
    if args.skip_compile_tier:
        cmd += ["--skip-compile-tier"]
    if args.cifar_dir:
        cmd += ["--cifar-dir", args.cifar_dir]
    if args.train_path:
        cmd += ["--train-path", args.train_path]
    if args.test_path:
        cmd += ["--test-path", args.test_path]
    import threading

    phases = []
    detail = [None]
    last_progress = [time.monotonic()]

    def consume(pipe):
        # Reader thread: a wedged child stops producing output without
        # exiting, so the parent must never block on readline itself.
        for line in pipe:
            line = line.strip()
            try:
                if line.startswith("BENCH_PHASE "):
                    phases.append(json.loads(line[len("BENCH_PHASE "):]))
                    last_progress[0] = time.monotonic()
                    log(f"phase: {phases[-1]}")
                elif line.startswith("BENCH_DETAIL "):
                    # The child emits a detail record per completed phase
                    # (headline → staged → complete); keep the latest so
                    # a mid-run wedge still yields a live partial record
                    # instead of a stale fallback.
                    detail[0] = json.loads(line[len("BENCH_DETAIL "):])
                    last_progress[0] = time.monotonic()
                    log(f"detail checkpoint: progress="
                        f"{detail[0].get('progress', 'complete')}")
            except ValueError as e:
                log(f"unparseable child line {line[:120]!r}: {e}")

    proc = None
    try:
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=sys.stderr, text=True, cwd=REPO
        )
        reader = threading.Thread(target=consume, args=(proc.stdout,), daemon=True)
        reader.start()
        deadline = time.monotonic() + timeout_s
        last_progress[0] = time.monotonic()
        while True:
            try:
                proc.wait(timeout=5.0)
                break
            except subprocess.TimeoutExpired:
                now = time.monotonic()
                if now >= deadline:
                    log(f"child timed out after {timeout_s:.0f}s; killing")
                    proc.kill()
                    proc.wait()
                    reader.join(timeout=10.0)
                    return detail[0], phases
                # phase-progress watchdog: a tunnel wedge mid-compile
                # stops phase markers without killing the child; killing
                # early (instead of burning the whole run timeout) buys
                # extra retries inside the driver's deadline
                if now - last_progress[0] > args.phase_timeout:
                    log(f"no phase progress for {args.phase_timeout:.0f}s "
                        "(tunnel wedged mid-phase); killing child early")
                    proc.kill()
                    proc.wait()
                    reader.join(timeout=10.0)
                    return detail[0], phases
        reader.join(timeout=10.0)
        if proc.returncode != 0:
            log(f"child exited rc={proc.returncode}")
        return detail[0], phases
    except Exception as e:  # never let an exception skip the JSON record
        log(f"child failed: {e!r}")
        return detail[0], phases
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait()


def emit(record):
    print(json.dumps(record), flush=True)


# Child checkpoints ranked by completeness: a later-tier partial must
# never lose to an earlier-tier one across retry attempts (the fused
# tier's big cold compile runs last precisely so a wedge there leaves a
# krr_tier-ranked checkpoint holding every measured tier).
PROGRESS_RANK = {"headline": 1, "staged": 2, "flagship": 3,
                 "featurize_tier": 4, "krr_tier": 5, "overlap_tier": 6,
                 "ooc_tier": 7, "dispatch_tier": 8, "telemetry_tier": 9,
                 "serving_tier": 10, "compile_tier": 11, "complete": 12}

# The tier payload keys a child detail may carry. finalize_record's
# error scan is restricted to exactly these: a future informational
# payload that happens to contain an "error" field (e.g. a north_star
# sub-dict) must not silently block persistence.
TIER_KEYS = ("flagship_bcd_d8192", "flagship_featurize", "flagship_krr",
             "featurize_overlap", "out_of_core", "dispatch_count",
             "telemetry_overhead", "serving_qps", "compile_count",
             "fused")


def progress_rank(detail) -> int:
    return PROGRESS_RANK.get(detail.get("progress", "complete"), 0)


def pick_better_partial(best, detail):
    """The detail to keep across attempts: the latest of the
    highest-ranked checkpoints (ties go to the newer attempt)."""
    if best is None or progress_rank(detail) >= progress_rank(best):
        return detail
    return best


def result_record(detail, extra=None):
    imgs_per_sec = detail["images_per_sec"]
    rec = {
        "metric": "cifar_randompatch_train_images_per_sec",
        "value": round(imgs_per_sec, 2),
        "unit": "images/sec (1 chip, warm)",
        "vs_baseline": round(imgs_per_sec / BASELINE_IMGS_PER_SEC, 4),
        "detail": detail,
    }
    if extra:
        rec.update(extra)
    return rec


def _ledger_diff_verdict(detail):
    """Run-over-run decision-ledger diff: when the last-good record
    names a ledger artifact that still exists, diff it against THIS
    run's ledger (`telemetry --diff` machinery) and return the verdict
    — regression count, the removed/drifted decision names, and the
    kill-switch env vars the diff suspects. None when either side
    lacks a readable ledger. Purely informational: a perf record's
    numbers stand on their own, the verdict tells the reader WHICH
    optimizer decision changed underneath them."""
    try:
        cur = detail.get("ledger_artifact")
        if not cur or not os.path.exists(cur):
            return None
        with open(LAST_GOOD) as f:
            prev_rec = json.load(f)
        prev = (prev_rec.get("detail") or {}).get("ledger_artifact")
        if not prev or not os.path.exists(prev) \
                or os.path.abspath(prev) == os.path.abspath(cur):
            return None
        from keystone_tpu.telemetry.ledger import diff_runs, read_ledger

        diff = diff_runs(read_ledger(prev), read_ledger(cur))
        return {
            "baseline_ledger": prev,
            "current_ledger": cur,
            "regressions": int(diff["regressions"]),
            "decisions_removed": [
                f"{d['kind']}[{d['labels']}]"
                for d in diff["decisions_removed"]],
            "decisions_added": [
                f"{d['kind']}[{d['labels']}]"
                for d in diff["decisions_added"]],
            "prediction_drift": [
                f"{d['kind']}[{d['labels']}].{d['metric']}: "
                f"{d['a']} -> {d['b']}"
                for d in diff["prediction_drift"]],
            "config_flips": [
                f"{c['env']}: {c['a']} -> {c['b']}"
                for c in diff["config_flips"]],
            "suspect_kill_switches": sorted({
                d["suspect_env"] for d in diff["decisions_removed"]
                if d.get("suspect_env")}),
        }
    except Exception:
        return None


def finalize_record(detail):
    """Gate a child measurement: returns (record, persist_as_last_good).

    An out-of-band accuracy (solver-quality regression on the calibrated
    task) is emitted loudly marked with "error" and must NEVER become
    the stale-fallback record; CPU runs never persist either. A record
    whose tier payloads carry {"error": ...} (failure-isolated tiers,
    child_main) surfaces them top-level and does not persist — a
    deterministically broken tier must not silently poison the fallback
    record while monitoring reads a clean exit."""
    rec = result_record(detail)
    verdict = _ledger_diff_verdict(detail)
    if verdict is not None:
        rec["ledger_diff"] = verdict
    if not detail.get("accuracy_in_band", True):
        band = detail.get("accuracy_band") or [None]
        bound = (band[0] if detail.get("synthetic", True)
                 else (detail.get("north_star") or {}).get("target_accuracy"))
        rec["error"] = (
            f"test_accuracy {detail.get('test_accuracy')} below "
            f"{'calibrated lower bound' if detail.get('synthetic', True) else 'north-star target'} "
            f"{bound}")
        return rec, False
    tier_errors = {k: detail[k]["error"] for k in TIER_KEYS
                   if isinstance(detail.get(k), dict)
                   and "error" in detail[k]}
    if tier_errors:
        rec["error"] = "tier failures: " + "; ".join(
            f"{k}: {e}" for k, e in sorted(tier_errors.items()))
        return rec, False
    # precision accuracy band: the mixed-precision policy's outputs must
    # sit inside the declared tolerance band vs the serial unfused f32
    # reference (dispatch_bench's `precision` plan verdict). A policy
    # that busts the band is an accuracy regression, not a perf win —
    # loud error, never the stale-fallback record.
    dispatch_tier = detail.get("dispatch_count")
    if isinstance(dispatch_tier, dict) \
            and dispatch_tier.get("precision_in_band") is False:
        rec["error"] = (
            "precision policy busted the declared tolerance band vs the "
            "serial unfused f32 reference (dispatch_count tier "
            "precision_in_band=false)")
        return rec, False
    # decision-ledger verdict: every enforced optimizer decision the
    # measured plans made must appear in the ledger with a prediction
    # the observed program counts agree with (dispatch_count tier's
    # `decisions_reconciled`). A plan the ledger cannot account for is
    # an observability regression, not a perf win.
    if isinstance(dispatch_tier, dict) \
            and dispatch_tier.get("decisions_reconciled") is False:
        rec["error"] = (
            "optimizer decisions and the decision ledger disagree: a "
            "megafused 1-program apply run lacks a matching megafusion "
            "decision record (dispatch_count tier "
            "decisions_reconciled=false)")
        return rec, False
    return rec, detail.get("platform") != "cpu"


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--cifar-dir",
                   help="directory with real CIFAR-10 binaries "
                        "(data_batch_*.bin + test_batch.bin); when present "
                        "the bench consumes them and asserts the north star "
                        "(>=84%% accuracy, <60 s train); otherwise it falls "
                        "back to the calibrated synthetic task")
    p.add_argument("--train-path")
    p.add_argument("--test-path")
    p.add_argument("--n-train", type=int, default=50_000)
    p.add_argument("--n-test", type=int, default=10_000)
    p.add_argument("--num-filters", type=int, default=256)
    p.add_argument("--flagship-n", type=int, default=120_000)
    p.add_argument("--flagship-d", type=int, default=8192)
    p.add_argument("--flagship-k", type=int, default=138)
    p.add_argument("--skip-flagship", action="store_true")
    p.add_argument("--featurize-batch", type=int, default=16384)
    p.add_argument("--featurize-reps", type=int, default=120)
    p.add_argument("--skip-featurize-tier", action="store_true")
    p.add_argument("--krr-n", type=int, default=98_304)
    p.add_argument("--krr-d", type=int, default=440)
    p.add_argument("--krr-k", type=int, default=138)
    p.add_argument("--skip-krr", action="store_true")
    p.add_argument("--overlap-n", type=int, default=16_384)
    p.add_argument("--overlap-chunk", type=int, default=2048)
    p.add_argument("--skip-overlap-tier", action="store_true")
    p.add_argument("--skip-ooc-tier", action="store_true")
    p.add_argument("--skip-dispatch-tier", action="store_true")
    p.add_argument("--skip-telemetry-tier", action="store_true")
    p.add_argument("--skip-serving-tier", action="store_true")
    p.add_argument("--skip-compile-tier", action="store_true")
    p.add_argument("--liveness-timeout", type=float, default=90.0)
    p.add_argument("--run-timeout", type=float, default=1500.0)
    p.add_argument("--phase-timeout", type=float, default=900.0,
                   help="kill the child if no phase marker arrives for "
                        "this long (mid-phase tunnel wedge); generous "
                        "enough for a fully cold multi-minute compile "
                        "of the 50k-scale programs")
    p.add_argument("--retry-wait", type=float, default=120.0)
    p.add_argument("--attempts", type=int, default=3)
    p.add_argument("--deadline", type=float, default=3300.0,
                   help="total seconds before giving up and emitting the "
                        "error record (sized so a third attempt still "
                        "fits a full --phase-timeout cold-compile window "
                        "after two wedged ones)")
    args = p.parse_args()

    if args.child:
        return child_main(args)

    # The child registers atexit cleanup for its cifar_train_* symlink
    # staging dir, but the watchdog kills wedged children with SIGKILL
    # (atexit never runs) — sweep strays here. Only dirs older than one
    # full deadline window: anything younger may belong to a concurrent
    # bench invocation whose child is still using it.
    import glob
    import shutil
    import tempfile
    for stray in glob.glob(os.path.join(tempfile.gettempdir(), "cifar_train_*")):
        try:
            if time.time() - os.path.getmtime(stray) > args.deadline:
                shutil.rmtree(stray, ignore_errors=True)
        except OSError:
            pass

    t_start = time.monotonic()
    error = None
    best = None  # best LIVE (possibly partial) detail seen this window
    for attempt in range(1, args.attempts + 1):
        remaining = args.deadline - (time.monotonic() - t_start)
        if remaining <= args.liveness_timeout:
            error = error or "deadline exhausted before a live-device attempt"
            break
        log(f"attempt {attempt}/{args.attempts} "
            f"({remaining:.0f}s of deadline left)")
        if not probe_device(min(args.liveness_timeout, remaining)):
            error = "device liveness probe failed (axon tunnel wedged)"
            if attempt < args.attempts:
                time.sleep(min(args.retry_wait,
                               max(0.0, args.deadline - (time.monotonic() - t_start))))
            continue
        remaining = args.deadline - (time.monotonic() - t_start)
        detail, phases = run_child(args, min(args.run_timeout, remaining))
        bad_dir = next((ph for ph in phases
                        if ph.get("phase") == "cifar_dir_unusable"), None)
        if bad_dir is not None:
            # a bad --cifar-dir fails deterministically; retrying burns
            # minutes with no chance of success (ADVICE r4)
            emit(error_record(f"--cifar-dir {bad_dir.get('dir')!r} unusable: "
                              + bad_dir.get("reason", "missing CIFAR batches")))
            return 2
        if detail is not None:
            best = pick_better_partial(best, detail)
            if progress_rank(detail) >= PROGRESS_RANK["complete"]:
                rec, persist = finalize_record(detail)
                if persist:
                    try:
                        with open(LAST_GOOD, "w") as f:
                            json.dump(rec, f, indent=1)
                    except OSError as e:
                        log(f"could not persist last-good record: {e}")
                emit(rec)
                return 0
        error = ("workload run failed/timed out"
                 + (f"; last phase: {phases[-1]}" if phases else " before any phase"))
        if attempt < args.attempts:
            time.sleep(max(0.0, min(args.retry_wait,
                                    args.deadline - (time.monotonic() - t_start))))

    if best is not None:
        # A live-but-incomplete measurement beats a stale carry-over:
        # emit it, marked partial, but never persist it as last-good.
        rec, _ = finalize_record(best)
        rec["partial"] = best.get("progress")
        note = f"incomplete run ({best.get('progress')}): {error}"
        rec["error"] = f"{rec['error']}; {note}" if "error" in rec else note
        emit(rec)
        return 0

    # Persistent failure: valid JSON with the last-known-good measurement.
    stale = None
    if os.path.exists(LAST_GOOD):
        try:
            with open(LAST_GOOD) as f:
                stale = json.load(f)
        except (OSError, ValueError):
            stale = None
    if stale is not None:
        stale.setdefault("detail", {})
        stale["detail"]["stale"] = True
        stale["error"] = error
        emit(stale)
    else:
        emit(error_record(error))
    return 0


def error_record(error):
    """Zero-value record in the headline metric's shape, for failures."""
    return {
        "metric": "cifar_randompatch_train_images_per_sec",
        "value": 0.0,
        "unit": "images/sec (1 chip, warm)",
        "vs_baseline": 0.0,
        "error": error,
    }


def phase(name, **kw):
    print("BENCH_PHASE " + json.dumps({"phase": name, **kw}), flush=True)


# Calibrated synthetic-task difficulty (see loaders.cifar_loader.
# synthetic_cifar): class templates partially mixed toward confusers +
# heavy pixel noise place the best attainable accuracy in a nontrivial
# band, so solver-quality regressions (centering, BCD convergence,
# precision) FAIL the bench instead of hiding behind a separable task.
# Calibration (CPU mesh, 2026-07): noise=1.2/confusion=0.6 → test acc
# 0.745-0.797 at n=2-3k, rising with n; chance = 0.10. The regression
# gate is ONE-SIDED (accuracy >= lower bound): the upper edge was
# calibrated only at n=2-3k and accuracy legitimately rises with n, so a
# good large-n run must not be stamped an error (ADVICE r3). The upper
# bound stays informational in the record as acc_above_calibrated_band.
BENCH_NOISE = 1.2
BENCH_CONFUSION = 0.6
ACC_BAND = (0.72, 0.96)

V5E_PEAK_FLOPS = 1.97e14  # bf16 MXU
V5E_PEAK_BW = 8.19e11     # HBM bytes/s


def _ledger_artifact():
    """The decision-ledger JSONL path this run appends to: explicit
    ``KEYSTONE_LEDGER``, else the traced run's default
    ``<trace>.ledger.jsonl`` companion, else None (untraced, unarmed
    runs keep decisions in memory only)."""
    try:
        from keystone_tpu.telemetry import ledger

        return ledger.resolve_ledger_path()
    except Exception:
        return None


def _roofline(flops, bytes_, seconds):
    return {
        "gflops": round(flops / 1e9, 1),
        "gbytes": round(bytes_ / 1e9, 2),
        "attained_tflops": round(flops / seconds / 1e12, 2),
        "attained_gbs": round(bytes_ / seconds / 1e9, 1),
        "pct_peak_flops": round(100 * flops / seconds / V5E_PEAK_FLOPS, 1),
        "pct_peak_bw": round(100 * bytes_ / seconds / V5E_PEAK_BW, 1),
        "seconds": round(seconds, 4),
    }


def _flagship_bcd(n, d, k, block, iters):
    """Reference-scale solver metric (VERDICT r2 #6): multi-block,
    multi-iter BCD at d≥8192 exercising the block loop + tp sharding at
    scale. Mirrors the TIMIT-shaped row of the reference's solver sweep
    (scripts/solver-comparisons-final.csv; BASELINE.md: TIMIT Block
    d=8192 = 580 555 ms on 16x r3.4xlarge at n=2.2e6)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator

    from jax.sharding import NamedSharding, PartitionSpec as P

    from keystone_tpu.parallel import mesh as meshlib

    rng = np.random.default_rng(0)
    # Generate ON DEVICE, directly into the Dataset's sharding: random
    # data (the solve's arithmetic profile is label-independent) via
    # jitted PRNG instead of a ~4 GB host device_put — the tunnel is
    # both slow for and, if the process dies mid-transfer, wedgeable by
    # bulk host→device traffic. out_shardings matters: without it the
    # full array would materialize unsharded on one chip before the
    # Dataset reshard (OOM at reference scale on a pod).
    m = meshlib.current_mesh()
    shards = meshlib.n_data_shards(m)
    n = -(-n // shards) * shards  # pad to whole rows per shard
    row_sh = NamedSharding(m, P(meshlib.DATA_AXIS))

    def gen(key, rows, cols):
        sh = meshlib.feature_sharding(m, cols) or row_sh
        f = jax.jit(
            lambda kk: jax.random.normal(kk, (rows, cols), jnp.float32),
            out_shardings=sh,
        )
        return f(key)

    X = gen(jax.random.PRNGKey(0), n, d)
    Y = gen(jax.random.PRNGKey(1), n, k)
    data, labels = Dataset(X), Dataset(Y)
    del X, Y
    est = BlockLeastSquaresEstimator(block_size=block, num_iter=iters, lam=1e-2)

    def fit_once():
        # fresh values defeat the axon transport's byte-identical-program
        # memo; the scalar pull fences the perturbation out of the timed
        # window and the post-fit pull is the true sync
        eps = float(rng.random()) * 1e-6
        d2 = data.map_batches(lambda x: x * (1.0 + eps)).sync()
        t0 = time.perf_counter()
        model = est.fit(d2, labels)
        np.asarray(model.W[:1, :1])  # raw array: scalar pull is the sync
        return time.perf_counter() - t0

    fit_once()  # warm/compile
    secs = fit_once()
    B = min(block, d)  # effective block width (solver clamps to d)
    nb = -(-d // B)
    flops = iters * nb * (2.0 * n * B * (B + 2 * k) + (2 / 3) * B**3)
    bytes_ = iters * nb * 4.0 * n * (B + k)
    ref_ms = 580_555.0  # TIMIT Block d=8192 (csv:25), n=2.2e6
    n_scale = n / 2_200_000.0
    return {
        "n": n, "d": d, "k": k, "block_size": block,
        "effective_block": B, "num_iter": iters,
        "fit_seconds": round(secs, 3),
        "scaled_fit_seconds_at_ref_n": round(secs / n_scale, 2),
        "reference_ms_16xr3.4xlarge": ref_ms,
        "speedup_vs_reference_n_scaled": round(
            ref_ms / 1e3 / (secs / n_scale), 1),
        "roofline": _roofline(flops, bytes_, secs),
    }


def _flagship_featurize(batch, reps, num_filters, patch=6):
    """Compute-bound featurize tier (VERDICT r4 #2): the fused
    conv+rectify+pool kernel chained `reps` times inside ONE XLA program,
    timed at `reps` and `reps//2` and DIFFERENCED — per-execution tunnel
    RTT (~65-95 ms), dispatch, and sync costs cancel exactly, leaving
    pure kernel throughput. This is the in-record proof that the kernels,
    not the transport, bound the headline featurize rate (the headline's
    0.23 s stage is only ~2-3 RTTs deep). Matches Convolver.scala:20-221
    economics at the same 32×32×3 shapes."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.ops import conv_rectify_pool

    rng = np.random.default_rng(2)
    kernel = jnp.asarray(
        rng.normal(size=(patch, patch, 3, num_filters)).astype(np.float32) * 0.1)
    colsum = kernel.reshape(-1, num_filters).sum(axis=0)
    bias = jnp.zeros((num_filters,), jnp.float32)
    images = jax.jit(
        lambda k: jax.random.uniform(k, (batch, 32, 32, 3), jnp.float32, 0, 255)
    )(jax.random.PRNGKey(0))

    def chained(r):
        @jax.jit
        def run(x, seed):
            def body(i, acc):
                # acc-dependent input defeats CSE across reps; the
                # perturbation is one fused elementwise op
                xi = x * (1.0 + (seed + acc * 1e-30) * 1e-12)
                pooled = conv_rectify_pool(
                    xi, kernel, colsum, bias, 0.25, 0.0, 14, 13, True)
                return acc + jnp.sum(pooled) * 1e-12

            return jax.lax.fori_loop(0, r, body, jnp.float32(0.0))

        # fresh seed per call defeats the transport's byte-identical memo
        def timed():
            t0 = time.perf_counter()
            out = run(images, float(np.random.default_rng().random()))
            float(out)  # scalar pull = sync
            return time.perf_counter() - t0

        timed()  # warm/compile at this rep count
        return min(timed(), timed())

    t_full = chained(reps)
    t_half = chained(reps // 2)
    per_rep = (t_full - t_half) / (reps - reps // 2)
    pos = (32 - patch + 1) ** 2
    d_patch = patch * patch * 3
    posp, dp = -(-pos // 8) * 8, -(-d_patch // 128) * 128
    flops = 2.0 * batch * pos * d_patch * (num_filters + 1)
    bytes_ = batch * (2.0 * posp * dp * 2 + 32 * 32 * 3 * 4
                      + 8 * num_filters * 4)
    return {
        "batch": batch, "num_filters": num_filters, "reps": reps,
        "seconds_full_chain": round(t_full, 3),
        "seconds_half_chain": round(t_half, 3),
        "per_rep_seconds": round(per_rep, 5),
        "images_per_sec_kernel_only": round(batch / per_rep, 1),
        "method": "differenced chained reps (RTT/dispatch cancel)",
        "roofline": _roofline(flops, bytes_, per_rep),
    }


def _flagship_krr(n, d, k, block, epochs=2, gamma=0.01, lam=0.1):
    """KRR flagship row (VERDICT r4 #3): RBF column-block generation +
    Gauss-Seidel dual BCD at n ≈ 100k — the reference's flagship kernel
    solver (KernelRidgeRegression.scala:37-275, arXiv:1602.05310). The
    per-block structure matches the reference loop exactly: kernel
    col-block gen → residual → local (B×B) solve → model + K·α update;
    here each block is one jitted `_krr_step` whose async dispatches
    pipeline through the host loop (no per-block host sync), where the
    reference paid a treeReduce + driver solve per block."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.learning import KernelRidgeRegression

    n = -(-n // block) * block

    @jax.jit
    def gen(key):
        kx, ky = jax.random.split(key)
        X = jax.random.normal(kx, (n, d), jnp.float32)
        Y = jax.random.normal(ky, (n, k), jnp.float32)
        return X, Y

    X, Y = gen(jax.random.PRNGKey(3))
    data, labels = Dataset(X), Dataset(Y)
    est = KernelRidgeRegression(
        gamma=gamma, lam=lam, block_size=block, num_epochs=epochs)
    rng = np.random.default_rng()

    def fit_once():
        eps = float(rng.random()) * 1e-6
        d2 = data.map_batches(lambda x: x * (1.0 + eps)).sync()
        t0 = time.perf_counter()
        model = est.fit(d2, labels)
        np.asarray(model.alpha[:1, :1])  # scalar pull = sync
        return time.perf_counter() - t0

    fit_once()  # warm/compile
    secs = min(fit_once(), fit_once())
    blocks = n // block
    # per block: K col-block GEMM (2nBd) + exp epilogue, residual+update
    # GEMM (2nBk), local solve (B³/3), K_bb gather
    flops = epochs * blocks * (
        2.0 * n * block * d + 2.0 * n * block * k + block**3 / 3.0)
    bytes_ = epochs * blocks * (
        2.0 * n * block * 4 + n * d * 4 + n * k * 4 * 2)
    return {
        "n": n, "d": d, "k": k, "block_size": block, "epochs": epochs,
        "blocks_per_epoch": blocks,
        "fit_seconds": round(secs, 3),
        "samples_per_sec": round(n * epochs / secs, 1),
        "roofline": _roofline(flops, bytes_, secs),
        "structure": ("per block: RBF col-block gen -> residual -> "
                      "(BxB) solve -> alpha & K.alpha update "
                      "(KernelRidgeRegression.scala:37-275)"),
    }


def _flagship_overlap(n, chunk, num_filters, patch=6, block=512, iters=2,
                      num_classes=10):
    """Serial-vs-overlapped featurize→solve tier (overlap engine PR):
    the SAME chunked host workload — n host-resident images featurized
    through the fused conv kernel via `map_host_batched`, stacked, then
    BCD-solved — timed once with the overlap engine disabled (stack →
    dispatch → blocking pull per chunk, the pre-change behavior) and
    once enabled (background thread stages/uploads chunk k+1 while the
    device runs chunk k; result pulls deferred and drained in order).
    The paths are numerically identical (asserted in
    tests/test_overlap.py); the delta is pure pipelining of host stack,
    host→device upload, device compute, and device→host pull."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
    from keystone_tpu.ops import conv_rectify_pool
    from keystone_tpu.utils import batching
    from keystone_tpu.workflow.env import execution_config, overlap_override

    rng = np.random.default_rng(5)
    items = [rng.uniform(0, 255, size=(32, 32, 3)).astype(np.float32)
             for _ in range(n)]
    labels = Dataset(
        (2.0 * np.eye(num_classes, dtype=np.float32)[
            rng.integers(0, num_classes, size=n)] - 1.0))
    kernel = jnp.asarray(
        rng.normal(size=(patch, patch, 3, num_filters)).astype(np.float32)
        * 0.1)
    colsum = kernel.reshape(-1, num_filters).sum(axis=0)
    bias = jnp.zeros((num_filters,), jnp.float32)

    @jax.jit
    def feat(xb):
        pooled = conv_rectify_pool(
            xb / 255.0, kernel, colsum, bias, 0.25, 0.0, 14, 13, True)
        return pooled.reshape(xb.shape[0], -1)

    est = BlockLeastSquaresEstimator(block_size=block, num_iter=iters,
                                     lam=1e-2)

    class _Fresh:
        """Lazy per-item perturbation: fresh values defeat the
        transport's byte-identical-program memo, and the multiply is
        paid at chunk-STACK time — on the producer thread in the
        overlapped path, inline in the serial path — so it is part of
        the chunked host work the engine must hide, not a constant
        added to both timings outside the dispatcher."""

        __slots__ = ("x", "eps")

        def __init__(self, x, eps):
            self.x = x
            self.eps = eps

        @property
        def shape(self):
            return self.x.shape

        def __array__(self, dtype=None):
            return np.asarray(self.x * self.eps, dtype or np.float32)

    def run_once():
        eps = 1.0 + float(np.random.default_rng().random()) * 1e-6
        t0 = time.perf_counter()
        feats = batching.map_host_batched(
            [_Fresh(x, eps) for x in items], feat, chunk=chunk)
        model = est.fit(Dataset(np.stack(feats)), labels)
        np.asarray(model.W[:1, :1])  # scalar pull = sync
        return time.perf_counter() - t0

    with overlap_override(False):
        run_once()  # warm/compile
        t_serial = min(run_once(), run_once())
    with overlap_override(True):
        run_once()  # warm the producer-thread path
        t_overlap = min(run_once(), run_once())
    return {
        "n": n, "chunk": chunk, "n_chunks": -(-n // chunk),
        "num_filters": num_filters,
        "prefetch_depth": execution_config().prefetch_depth,
        "serial_seconds": round(t_serial, 4),
        "overlapped_seconds": round(t_overlap, 4),
        "speedup": round(t_serial / t_overlap, 3),
        "images_per_sec_serial": round(n / t_serial, 1),
        "images_per_sec_overlapped": round(n / t_overlap, 1),
        "structure": ("map_host_batched(featurize) -> stack -> BCD "
                      "solve; serial = blocking pull per chunk, "
                      "overlapped = double-buffered dispatch + deferred "
                      "in-order drains"),
    }


def _out_of_core_bench(n=81_920, dim=128, k=8, shard_rows=8192,
                       window=1024, lam=1e-3):
    """Out-of-core featurize→solve tier (planner-governed host spill
    PR): a synthetic dataset 8× a synthetic HBM budget streams through
    the windowed spill prefetcher — shards load on demand, each window
    pads onto the PR-5 pow-2 ladder, normal-equation accumulators
    (AᵀA, Aᵀb — tiny) stay device-resident, and the full design matrix
    is NEVER materialized on device. Gates: observed peak device
    residency ≤ the budget during the windowed pass; the windowed
    solution is allclose to the unconstrained (fully materialized) arm
    at window-multiple AND ragged counts with exact index coverage;
    the warm re-run performs 0 cold compiles (every window shape is a
    ladder shape already compiled); and the unified planner, asked to
    plan under a budget the device cache busts, prices the spill
    alternative (feasible) against the device cache (INF) — the
    KEYSTONE_OOC_SPILL=0 arm scores no spill entry and keeps an empty
    spill set. Overlapped-vs-serial reload wall-clock is recorded
    (`overlap_beats_serial`); host-only meshes report it without
    gating — the pipelining win is a device-transfer property."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.loaders import synthetic_out_of_core
    from keystone_tpu.telemetry import compiles_snapshot
    from keystone_tpu.telemetry.compile_events import (
        install_compile_listeners,
    )
    from keystone_tpu.utils.batching import stream_spill_windows
    from keystone_tpu.workflow.env import overlap_override
    from keystone_tpu.workflow.executor import drain_warmups

    install_compile_listeners()
    dataset_bytes = n * dim * 4
    budget = dataset_bytes // 8

    rng = np.random.default_rng(17)
    W = jnp.asarray(
        rng.standard_normal((dim, dim)).astype(np.float32) * 0.05)
    theta = jnp.asarray(rng.standard_normal((dim, k)).astype(np.float32))
    eye = jnp.eye(dim, dtype=jnp.float32)

    @jax.jit
    def accum(ata, atb, xb):
        f = jnp.maximum(xb @ W, 0.0)
        # zero pad rows featurize to zero rows: they add nothing to
        # either accumulator, so padded windows need no masking
        return ata + f.T @ f, atb + f.T @ (xb @ theta)

    @jax.jit
    def solve(ata, atb):
        return jnp.linalg.solve(ata + lam * eye, atb)

    def solve_windowed(source, count, track_peak=False):
        ata = jnp.zeros((dim, dim), jnp.float32)
        atb = jnp.zeros((dim, k), jnp.float32)
        seen = []
        peak = 0
        for idxs, win in stream_spill_windows(source.row_loader, count,
                                              window=window):
            ata, atb = accum(ata, atb, win)
            seen.extend(idxs)
            if track_peak:
                ata.block_until_ready()
                live = sum(int(a.nbytes) for a in jax.live_arrays())
                peak = max(peak, live)
        out = solve(ata, atb)
        return np.asarray(out), seen, peak

    def solve_resident(source, count):
        x = jnp.asarray(source.numpy())
        f = jnp.maximum(x @ W, 0.0)
        out = jnp.linalg.solve(f.T @ f + lam * eye, f.T @ (x @ theta))
        return np.asarray(out)

    # --- the big out-of-core pass: 8× the budget, windowed, gated
    big = synthetic_out_of_core(n, dim, shard_rows=shard_rows, seed=17)
    with overlap_override(True):
        theta_big, seen, _ = solve_windowed(big, n)  # cold/compile
        drain_warmups()
        before = compiles_snapshot()
        t0 = time.perf_counter()
        theta_big, seen, peak = solve_windowed(big, n, track_peak=True)
        t_warm = time.perf_counter() - t0
        drain_warmups()
        after = compiles_snapshot()
    warm_cold_compiles = (after["programs_compiled"]
                          - before["programs_compiled"])
    coverage_ok = (sorted(seen) == list(range(n)))

    # --- serial vs overlapped reload wall-clock (same windowed pass)
    with overlap_override(False):
        solve_windowed(big, n)  # warm the serial path
        t_serial = min(
            _timed(lambda: solve_windowed(big, n)) for _ in range(2))
    with overlap_override(True):
        t_overlap = min(
            _timed(lambda: solve_windowed(big, n)) for _ in range(2))

    # --- allclose vs the unconstrained arm at multiple AND ragged
    # counts (small enough to materialize honestly)
    allclose = {}
    for count in (4 * window, 4 * window + 1, 3 * window - 413):
        src = synthetic_out_of_core(count, dim, shard_rows=4096,
                                    seed=29 + count)
        got, idxs, _ = solve_windowed(src, count)
        want = solve_resident(src, count)
        allclose[str(count)] = bool(
            sorted(idxs) == list(range(count))
            and np.allclose(got, want, rtol=2e-4, atol=2e-4))

    # --- the planner's spill axis: under a budget the device cache
    # busts, the spill placement prices feasible where device prices
    # INF; with the axis off nothing spills (the kill-switch shape)
    planner = _ooc_planner_probe()

    problems = []
    if peak > budget:
        problems.append(
            f"windowed pass peak device residency {peak} bytes exceeds "
            f"the {budget}-byte budget (dataset {dataset_bytes} bytes)")
    if not coverage_ok:
        problems.append("windowed index coverage != range(n)")
    if warm_cold_compiles:
        problems.append(
            f"warm windowed re-run performed {warm_cold_compiles} cold "
            "compile(s)")
    if not all(allclose.values()):
        problems.append(f"windowed vs resident allclose failed: "
                        f"{allclose}")
    if planner.get("error"):
        problems.append(planner["error"])
    res = {
        "n": n, "dim": dim, "k": k, "window": window,
        "shard_rows": shard_rows,
        "dataset_bytes": dataset_bytes,
        "hbm_budget_bytes": budget,
        "dataset_over_budget": round(dataset_bytes / budget, 2),
        "peak_device_bytes": int(peak),
        "peak_under_budget": bool(peak <= budget),
        "warm_seconds": round(t_warm, 4),
        "warm_cold_compiles": int(warm_cold_compiles),
        "rows_per_sec_warm": round(n / t_warm, 1),
        "serial_seconds": round(t_serial, 4),
        "overlapped_seconds": round(t_overlap, 4),
        "overlap_speedup": round(t_serial / t_overlap, 3),
        "overlap_beats_serial": bool(t_overlap < t_serial),
        "allclose_vs_resident": allclose,
        "planner": planner,
        "structure": ("synthetic_out_of_core shards -> "
                      "stream_spill_windows (pad ladder, double-buffered"
                      " host->device reload) -> jit normal-equation "
                      "accumulate -> device solve; design matrix never "
                      "device-materialized"),
    }
    if problems:
        res["error"] = "; ".join(problems)
    return res


def _timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _ooc_planner_probe():
    """Pure spec arithmetic: ask the unified planner for a plan whose
    only way to keep a demanded-twice value is the host spill tier, and
    check the ledger-bound menu prices BOTH placements — device cache
    INF (busts the budget), host spill feasible — while the
    KEYSTONE_OOC_SPILL=0 arm scores no spill entry at all."""
    from keystone_tpu.analysis import as_source_spec
    from keystone_tpu.analysis.examples import build_example
    from keystone_tpu.analysis.plan_ir import plan_unified
    from keystone_tpu.analysis.propagate import spec_pass

    pipeline, source_spec = build_example("MnistRandomFFT")
    specs, _ = spec_pass(
        pipeline.graph, {pipeline.source: as_source_spec(source_spec)})
    budget = 32 << 10
    on = plan_unified(pipeline.graph, specs, hbm_budget_bytes=budget,
                      allow_spill=True, include_boundary_policies=False)
    off = plan_unified(pipeline.graph, specs, hbm_budget_bytes=budget,
                       allow_spill=False, include_boundary_policies=False)
    spill_entries = [c for c in (on.scored_candidates if on else [])
                     if str(c.get("entry", "")).startswith("spill_")]
    off_spill_entries = [c for c in (off.scored_candidates if off else [])
                        if str(c.get("entry", "")).startswith("spill_")]
    out = {
        "budget_bytes": budget,
        "spill_alternatives_scored": len(spill_entries),
        "spill_alternatives_feasible": sum(
            1 for c in spill_entries if c.get("feasible")),
        "chosen_spills": len(getattr(on.chosen, "spills", ()) if on
                             else ()),
        "kill_switch_spill_entries": len(off_spill_entries),
        "kill_switch_chosen_spills": len(
            getattr(off.chosen, "spills", ()) if off else ()),
    }
    if not spill_entries:
        out["error"] = ("planner scored no spill alternatives under a "
                        "cache-busting budget")
    elif off_spill_entries or out["kill_switch_chosen_spills"]:
        out["error"] = ("KEYSTONE_OOC_SPILL=0 arm still scored or chose "
                        "spill placements")
    return out


def _telemetry_overhead(name="MnistRandomFFT", batch=64, reps=30):
    """Live-telemetry-plane overhead tier (ISSUE 18): warm
    `FittedPipeline.apply` wall with the plane ARMED — flight-ring span
    tee + streaming latency sketches + a conformance watchdog holding a
    generous bound (the tier prices instrumentation, not breach
    handling) — vs DISARMED (``live_telemetry=False``, the kill-switch
    fast path), median of ``reps`` warm applies per side at a serving
    batch size. The plane's standing budget is <5% of the warm serving
    path; ``overhead_in_budget`` is the verdict finalize_record can
    gate on. The two sides are interleaved request-by-request so host
    load/thermal drift cancels out of the comparison."""
    import statistics

    import numpy as np

    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.dispatch_bench import EXAMPLES
    from keystone_tpu.telemetry.flight import (
        ensure_flight,
        flight_recorder,
        reset_flight,
    )
    from keystone_tpu.telemetry.streaming import reset_live
    from keystone_tpu.telemetry.watchdog import (
        arm_watchdog,
        disarm_watchdog,
    )
    from keystone_tpu.workflow import PipelineEnv
    from keystone_tpu.workflow.env import config_override

    PipelineEnv.reset()
    predictor, train, test = EXAMPLES[name]()
    fitted = predictor.fit()
    X = np.concatenate([np.asarray(test.numpy()),
                        np.asarray(train.numpy())])

    def make_batch(i):
        off = (i * batch) % max(1, len(X) - batch)
        return Dataset.from_numpy(np.ascontiguousarray(X[off:off + batch]))

    def apply_once(i):
        t0 = time.perf_counter()
        np.asarray(fitted.apply(make_batch(i)).numpy())
        return time.perf_counter() - t0

    disarm_watchdog()
    reset_live()
    reset_flight()
    ensure_flight()
    # a bound no warm apply can breach: every request is checked and
    # teed, none takes the breach slow path (dump + ledger write)
    arm_watchdog({
        "slo_seconds": 3600.0,
        "certified": True,
        "shapes": [{"batch": 1 << 20, "predicted_seconds": 3600.0}],
    }, pipeline=name)
    try:
        # warm both paths, then INTERLEAVE the sides: back-to-back
        # pairs share whatever load/thermal drift the host is under, so
        # the medians difference out everything except the plane itself
        with config_override(live_telemetry=False):
            apply_once(0)
        apply_once(1)
        off_s, on_s = [], []
        for i in range(reps):
            with config_override(live_telemetry=False):
                off_s.append(apply_once(2 + 2 * i))
            on_s.append(apply_once(3 + 2 * i))
        t_disarmed = statistics.median(off_s)
        t_armed = statistics.median(on_s)
        # the plane's true cost is microseconds against a noisy
        # multi-ms apply wall (per-apply warm-thread spawn, lock
        # scheduling): the median of PAIRWISE deltas differences that
        # noise out pair by pair, where a ratio of independent medians
        # would flap by far more than the 5% budget
        delta = statistics.median(b - a for a, b in zip(off_s, on_s))
        rec = flight_recorder()
        spans_held = len(rec.spans) if rec is not None else 0
    finally:
        disarm_watchdog()
        reset_live()
        reset_flight()
    overhead = delta / t_disarmed if t_disarmed > 0 else 0.0
    return {
        "example": name, "batch": batch, "reps": reps,
        "disarmed_seconds": round(t_disarmed, 5),
        "armed_seconds": round(t_armed, 5),
        "seconds": round(t_armed, 5),
        "overhead_seconds": round(delta, 6),
        "overhead_pct": round(100.0 * overhead, 2),
        "overhead_in_budget": bool(overhead < 0.05),
        "flight_spans_held": spans_held,
        "method": ("interleaved warm applies, disarmed "
                   "(live_telemetry=False) vs armed (flight tee + "
                   "sketches + non-breaching watchdog); overhead = "
                   "median pairwise delta"),
    }


def _serving_qps_example(name, build, reps, clients, offered_qps,
                         max_batch, slo_ms, speedup_floor):
    """One example through the serving_qps tier: sustained concurrent
    load at a fixed offered QPS through the REAL certified runtime
    (`serving.ServingRuntime`), coalesced vs kill-switch
    (``serving_coalesce=False`` — per-request dispatch) in the SAME
    process, same payloads, same offered load. The SLO gate IS the
    certificate: every ladder shape the coalesced run dispatches must
    hold observed p99 ≤ its certified KP903 bound, with 0 cold compiles
    and 0 watchdog breaches inside the measured window; the kill-switch
    side must reproduce per-request dispatch bit-for-bit against direct
    `FittedPipeline.apply`, and coalescing must sustain ≥
    ``speedup_floor``× its attained throughput."""
    import threading

    import numpy as np

    from keystone_tpu.analysis.serving import ServingEnvelope
    from keystone_tpu.telemetry.metrics import (
        histogram,
        metrics_delta,
        registry,
    )
    from keystone_tpu.telemetry.streaming import latency_sketch, reset_live
    from keystone_tpu.telemetry.watchdog import (
        active_watchdog,
        arm_watchdog,
        disarm_watchdog,
    )
    from keystone_tpu.workflow import PipelineEnv
    from keystone_tpu.workflow.env import config_override

    PipelineEnv.reset()
    disarm_watchdog()
    reset_live()
    registry().histograms.pop("serving.coalesced_batch", None)
    envelope = ServingEnvelope(max_batch=max_batch,
                               slo_seconds=slo_ms / 1e3)
    make_runtime, payloads, reference = build(envelope)
    total = clients * reps

    def fire(rt, results):
        """Open-loop paced load: request k is scheduled at t0 +
        k/offered_qps; a client behind schedule fires immediately
        (offered load never degrades to the server's pace). Returns
        (wall_seconds, errors)."""
        errors = []
        t0 = time.perf_counter()

        def client(cid):
            for i in range(reps):
                k = cid + clients * i
                due = t0 + k / offered_qps
                delay = due - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                try:
                    results[k] = rt.submit(payloads[k % len(payloads)])
                except Exception as e:
                    errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=client, args=(c,), daemon=True)
                   for c in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0, errors

    problems = []
    res = {"example": name, "clients": clients, "requests": total,
           "offered_qps": offered_qps, "max_batch": max_batch,
           "slo_ms": slo_ms}

    # ---- coalesced side: certified runtime, micro-batching on
    rt = make_runtime().start()
    try:
        res["ladder"] = rt.stats()["ladder"]
        bounds = {int(s["batch"]): float(s["predicted_seconds"])
                  for s in rt.certificate.shapes}
        # prime every ladder-adjacent code path, then open a FRESH
        # measured window: zeroed sketches and watchdog counters, so
        # the gates judge steady-state serving, not ramp-up
        prime: dict = {}
        fire(rt, prime)
        reset_live()
        arm_watchdog(rt.certificate.as_record(), pipeline="fitted_pipeline")
        registry().histograms.pop("serving.coalesced_batch", None)
        rt._batcher._coalesced = histogram("serving.coalesced_batch")
        coalesced: dict = {}
        with metrics_delta() as delta:
            wall, errors = fire(rt, coalesced)
        if errors:
            problems.append(f"coalesced run errors: {errors[:3]}")
        cold = delta.counter("dispatch.programs_compiled")
        if cold:
            problems.append(
                f"{int(cold)} cold compile(s) inside the warm measured "
                "window (the certificate promises 0)")
        wd = active_watchdog()
        digest = wd.describe() if wd is not None else {}
        if digest.get("breaches", 0):
            problems.append(
                f"{digest['breaches']} conformance breach(es) in the "
                "measured window")
        stats = rt.stats()
        if stats["dispatched_outside_ladder"]:
            problems.append("dispatched shapes outside the certified "
                            f"ladder: {stats['dispatched_outside_ladder']}")
        shapes = []
        for shape in stats["dispatched_shapes"]:
            sk = latency_sketch("fitted_pipeline", int(shape))
            if sk is None or sk.count == 0:
                continue
            bound = bounds.get(int(shape))
            if bound is None:
                covering = [b for b in bounds if b >= int(shape)]
                bound = bounds[min(covering)] if covering else None
            p99 = sk.quantile(0.99)
            holds = bound is not None and p99 <= bound
            if not holds:
                problems.append(
                    f"shape {int(shape)}: observed p99 "
                    f"{p99 * 1e3:.2f}ms over the certified KP903 bound "
                    f"{(bound or 0) * 1e3:.2f}ms")
            shapes.append({
                "chunk_shape": int(shape),
                "p50_ms": round(sk.quantile(0.50) * 1e3, 3),
                "p99_ms": round(p99 * 1e3, 3),
                "reps": int(sk.count),
                "bound_ms": (round(bound * 1e3, 3)
                             if bound is not None else None),
                "holds": bool(holds),
            })
        hist = registry().histograms.get("serving.coalesced_batch")
        res.update({
            "coalesced_wall_seconds": round(wall, 3),
            "coalesced_rps": round(total / wall, 1),
            "dispatches": int(delta.counter("serving.dispatches")),
            "shed": int(delta.counter("serving.shed_total")),
            "cold_compiles": int(cold),
            "watchdog": {"checked": digest.get("checked", 0),
                         "breaches": digest.get("breaches", 0)},
            "shapes": shapes,
            "coalesced_batch": hist.snapshot() if hist else None,
        })
    finally:
        rt.stop()

    # ---- kill-switch side: per-request dispatch, same offered load
    reset_live()
    with config_override(serving_coalesce=False):
        rt2 = make_runtime().start()
        try:
            perreq: dict = {}
            wall2, errors2 = fire(rt2, perreq)
            if errors2:
                problems.append(f"kill-switch run errors: {errors2[:3]}")
            if rt2._batcher._thread is not None:
                problems.append("kill switch did not disable the "
                                "dispatcher thread")
        finally:
            rt2.stop()
    res.update({
        "killswitch_wall_seconds": round(wall2, 3),
        "killswitch_rps": round(total / wall2, 1),
    })

    # bit-for-bit: the kill switch IS per-request dispatch — its rows
    # must equal direct FittedPipeline.apply on the same payloads
    mismatched = sum(
        1 for k in sorted(perreq)[:64]
        if not np.array_equal(np.asarray(perreq[k]),
                              np.asarray(reference(payloads[k % len(payloads)]))))
    if mismatched:
        problems.append(f"kill-switch output diverged from direct "
                        f"per-request apply on {mismatched} request(s)")
    res["killswitch_bit_for_bit"] = mismatched == 0
    # coalesced rows must agree with the per-request rows numerically
    drifted = sum(
        1 for k in sorted(coalesced)[:256]
        if k in perreq and not np.allclose(
            np.asarray(coalesced[k]), np.asarray(perreq[k]),
            rtol=1e-5, atol=1e-5))
    if drifted:
        problems.append(f"coalesced rows drifted from per-request rows "
                        f"on {drifted} request(s)")

    speedup = (res["coalesced_rps"] / res["killswitch_rps"]
               if res["killswitch_rps"] else 0.0)
    res["speedup"] = round(speedup, 2)
    res["speedup_floor"] = speedup_floor
    if speedup < speedup_floor:
        problems.append(
            f"coalesced throughput {res['coalesced_rps']} rps is only "
            f"{speedup:.2f}x the per-request baseline "
            f"{res['killswitch_rps']} rps (floor {speedup_floor}x)")
    if problems:
        res["error"] = "; ".join(problems)
    reset_live()
    disarm_watchdog()
    PipelineEnv.reset()
    return res


def _serving_qps(clients=16, reps=50, slo_ms=1000.0):
    """The serving_qps tier: the certified serving runtime under
    sustained concurrent load, coalesced vs kill-switch, for the two
    covered modalities — MnistRandomFFT (ndarray ingress, pure device
    tail) and Newsgroups (text ingress: fitted host front-end runs per
    request on the client thread, the device tail serves behind the
    certificate)."""
    import numpy as np

    from keystone_tpu.data.dataset import Dataset

    def mnist_build(envelope):
        from keystone_tpu.dispatch_bench import EXAMPLES
        from keystone_tpu.serving import NdarrayIngress, ServingRuntime

        predictor, train, test = EXAMPLES["MnistRandomFFT"]()
        fitted = predictor.fit()
        X = np.concatenate([np.asarray(test.numpy()),
                            np.asarray(train.numpy())])
        payloads = [np.ascontiguousarray(X[i]) for i in range(len(X))]

        def make_runtime():
            return ServingRuntime(fitted, NdarrayIngress(X.shape[1:]),
                                  envelope=envelope, name="MnistRandomFFT")

        def reference(p):
            out = fitted.apply(Dataset.from_numpy(p[np.newaxis]))
            return np.asarray(out.numpy())[0]

        return make_runtime, payloads, reference

    def newsgroups_build(envelope):
        from keystone_tpu.pipelines.text_pipelines import (
            build_newsgroups_predictor,
            synthetic_corpus,
        )
        from keystone_tpu.serving import (
            NdarrayIngress,
            ServingRuntime,
            TextIngress,
            split_fitted_at,
        )

        labels, docs = synthetic_corpus(600, 4, seed=0)
        fitted = build_newsgroups_predictor(docs, labels, 4).fit()
        host_ops, tail = split_fitted_at(fitted, "NaiveBayesModel")
        ingress = TextIngress(host_ops)
        # Pre-featurize the payload pool: the host text front-end runs
        # per-request on the caller's thread IDENTICALLY in both modes,
        # so leaving it in the measured loop only dilutes the
        # coalescing delta this gate exists to measure. The live
        # TextIngress request path is covered by test_serving_runtime
        # and `scripts/serving_latency.py --runtime`; here the tier
        # drives the certified device tail directly.
        payloads = [ingress.accept(d) for d in list(docs.items)[:256]]
        element = payloads[0].shape

        def make_runtime():
            return ServingRuntime(tail, NdarrayIngress(element),
                                  envelope=envelope,
                                  name="NewsgroupsPipeline")

        def reference(row):
            out = tail.apply(Dataset.from_numpy(row[np.newaxis]))
            return np.asarray(out.numpy()
                              if hasattr(out, "numpy") else out)[0]

        return make_runtime, payloads, reference

    t0 = time.perf_counter()
    examples = {
        "MnistRandomFFT": _serving_qps_example(
            "MnistRandomFFT", mnist_build, reps=reps, clients=clients,
            offered_qps=4000.0, max_batch=16, slo_ms=slo_ms,
            speedup_floor=4.0),
        "NewsgroupsPipeline": _serving_qps_example(
            "NewsgroupsPipeline", newsgroups_build, reps=reps,
            clients=clients, offered_qps=4000.0, max_batch=16,
            slo_ms=slo_ms, speedup_floor=4.0),
    }
    rec = {"examples": examples,
           "seconds": round(time.perf_counter() - t0, 2)}
    errors = [f"{n}: {e['error']}" for n, e in examples.items()
              if e.get("error")]
    if errors:
        rec["error"] = "; ".join(errors)
    return rec


def child_main(args):
    """The measured workload. Runs in a killable subprocess; prints phase
    markers and finally one BENCH_DETAIL line."""
    if os.environ.get("KEYSTONE_BACKEND") == "cpu":  # debug/test path; the
        # programmatic override works where env-var platform forcing can
        # hang under the axon sitecustomize (see keystone_tpu/__main__.py)
        import jax

        jax.config.update("jax_platforms", "cpu")
    phase("import")
    from keystone_tpu.pipelines.random_patch_cifar import (
        RandomPatchCifarConfig,
        build_pipeline,
        run_fused,
        run_staged,
    )
    from keystone_tpu.loaders.cifar_loader import cifar_loader, synthetic_cifar
    from keystone_tpu.evaluation import MulticlassClassifierEvaluator
    from keystone_tpu.workflow import PipelineEnv
    import jax

    phase("devices", platform=jax.devices()[0].platform,
          n=len(jax.devices()))

    config = RandomPatchCifarConfig(num_filters=args.num_filters)
    train_path, test_path = args.train_path, args.test_path
    north_star_gate = False  # the >=84% gate is calibrated for FULL
    # CIFAR-10 via --cifar-dir; arbitrary --train-path data keeps the
    # old always-pass behavior (no calibrated target exists for it)
    if args.cifar_dir:
        cdir = os.path.abspath(args.cifar_dir)
        batches = sorted(
            f for f in os.listdir(cdir)
            if f.startswith("data_batch") and f.endswith(".bin")
        ) if os.path.isdir(cdir) else []
        tb = os.path.join(cdir, "test_batch.bin")
        if batches and os.path.exists(tb):
            # standard CIFAR-10 binary layout (CifarLoader.scala:13-52):
            # the loader handles a directory of *.bin; point train at
            # the data batches and test at the held-out batch
            train_path = (os.path.join(cdir, batches[0])
                          if len(batches) == 1 else cdir)
            if len(batches) > 1:
                # directory mode globs every .bin incl. test_batch; stage
                # train batches alone via a temp dir of symlinks
                import atexit
                import shutil
                import tempfile

                tdir = tempfile.mkdtemp(prefix="cifar_train_")
                atexit.register(shutil.rmtree, tdir, ignore_errors=True)
                for f in batches:
                    os.symlink(os.path.join(cdir, f), os.path.join(tdir, f))
                train_path = tdir
            test_path = tb
            north_star_gate = True
        else:
            # LOUD: a typo'd/empty --cifar-dir must not silently report
            # calibrated-band success on the synthetic task
            print(f"BENCH ERROR: --cifar-dir {args.cifar_dir!r} has no "
                  "data_batch_*.bin + test_batch.bin; refusing to fall "
                  "back silently", file=sys.stderr, flush=True)
            phase("cifar_dir_unusable", dir=args.cifar_dir,
                  reason="no data_batch_*.bin + test_batch.bin")
            return 2
    if train_path:
        train = cifar_loader(train_path)
        test = cifar_loader(test_path or train_path)
        synthetic = False
    else:
        train, test = synthetic_cifar(
            args.n_train, args.n_test,
            noise=BENCH_NOISE, confusion=BENCH_CONFUSION,
        )
        synthetic = True
    phase("data", n_train=train.data.count, n_test=test.data.count,
          synthetic=synthetic)

    # Warm-up at the SAME shapes (jit caches are shape-keyed, and the
    # fused-program cache is global/structural): one untimed staged pass
    # + one untimed pipeline pass compile every program both timed paths
    # use, so the measurements reflect steady-state TPU throughput.
    evaluator = MulticlassClassifierEvaluator(config.num_classes)
    run_staged(train, config, evaluator)
    PipelineEnv.reset()
    warm_pipe = build_pipeline(train, config)
    evaluator(warm_pipe(train.data), train.labels)
    phase("warm_done")

    # Headline: the real pipeline path end-to-end, async dispatch free to
    # overlap stages (what a user's run costs).
    PipelineEnv.reset()
    t0 = time.perf_counter()
    predictor = build_pipeline(train, config)
    train_metrics = evaluator(predictor(train.data), train.labels)
    elapsed = time.perf_counter() - t0
    phase("timed_done", seconds=round(elapsed, 3))
    test_metrics = evaluator(predictor(test.data), test.labels)

    acc = test_metrics.accuracy
    north_star = None
    if synthetic:
        in_band = acc >= ACC_BAND[0]
    elif not north_star_gate:
        in_band = True  # ad-hoc --train-path data: no calibrated target
    else:
        # real CIFAR present: the driver-defined north star becomes the
        # gate — >=84% test accuracy, <60 s train (BASELINE.md; the 60 s
        # target is the v5e-16 pod budget, so single-chip time is
        # recorded against it but only accuracy fails the record)
        north_star = {
            "target_accuracy": 0.84,
            "target_seconds_v5e16": 60.0,
            "accuracy_ok": bool(acc >= 0.84),
            "train_seconds_single_chip": round(elapsed, 3),
            "time_ok_single_chip": bool(elapsed < 60.0),
        }
        in_band = north_star["accuracy_ok"]
    detail = {
        "progress": "headline",
        "n_train": train.data.count,
        "train_seconds": round(elapsed, 3),
        "images_per_sec": round(train.data.count / elapsed, 2),
        "train_error": round(train_metrics.error, 4),
        "test_accuracy": round(acc, 4),
        "accuracy_band": list(ACC_BAND) if synthetic else None,
        "north_star": north_star,
        "accuracy_in_band": in_band,
        "acc_above_calibrated_band": bool(synthetic and acc > ACC_BAND[1]),
        "task_difficulty": {"noise": BENCH_NOISE, "confusion": BENCH_CONFUSION},
        "num_filters": config.num_filters,
        "synthetic": synthetic,
        "platform": jax.devices()[0].platform,
        "data_note": (None if not synthetic else
                      "real CIFAR-10 binaries are not obtainable in this "
                      "zero-egress environment; synthetic learnable task at "
                      "identical shapes/scale with CALIBRATED difficulty "
                      "(see BENCH notes in README)"),
        # With KEYSTONE_TRACE set the child's ambient tracer writes a
        # Chrome trace (all tiers' spans: node forces, stream chunks,
        # solver iterations, queue stalls) at exit; the record carries
        # the path so BENCH rounds keep span-level detail
        # (`scripts/perf_table.py --trace <path>` to render).
        "trace_artifact": os.environ.get("KEYSTONE_TRACE") or None,
        # The decision ledger the same run appends (KEYSTONE_LEDGER, or
        # derived alongside the trace artifact): every optimizer
        # decision the tiers enforced, with predicted costs —
        # `python -m keystone_tpu.telemetry --ledger <path>` renders it,
        # `--diff` compares two rounds' ledgers.
        "ledger_artifact": _ledger_artifact(),
    }
    # Checkpoint: a wedge during the staged/flagship phases still leaves
    # a live headline measurement in the parent's hands.
    print("BENCH_DETAIL " + json.dumps(detail), flush=True)

    # Stage breakdown: same components, scalar-pull sync after each
    # stage, so the stages SUM to the staged end-to-end by construction
    # (VERDICT r2 #1/#4 — no unaccounted time).
    PipelineEnv.reset()
    stages, _, _ = run_staged(train, config, evaluator)
    staged_total = sum(stages.values())
    phase("staged_done", seconds=round(staged_total, 3))

    # Per-stage roofline vs v5e peaks (featurize/solve dominate; the
    # fused conv kernel's HBM traffic is patches bf16 write+read +
    # images read + pooled write).
    n = train.data.count
    F, p = config.num_filters, config.patch_size
    pos = (32 - p + 1) ** 2
    d_patch = p * p * 3
    posp, dp = -(-pos // 8) * 8, -(-d_patch // 128) * 128
    d = 8 * F
    k = config.num_classes
    B = min(config.block_size, d)
    conv_flops = 2.0 * n * pos * d_patch * (F + 1)
    conv_bytes = n * (2.0 * posp * dp * 2 + 32 * 32 * 3 * 4 + 8 * F * 4)
    scaler_bytes = 3.0 * n * d * 4
    solve_flops = 2.0 * n * d * B + (2.0 / 3.0) * B**3 + 6.0 * n * d * k
    solve_bytes = 3.0 * n * d * 4
    pred_flops = 2.0 * n * d * k
    rooflines = {
        "featurize": _roofline(conv_flops, conv_bytes, stages["featurize"]),
        "scaler": _roofline(n * d * 4.0, scaler_bytes, stages["scaler"]),
        "bcd_solve": _roofline(solve_flops, solve_bytes, stages["bcd_solve"]),
        "predict_eval": _roofline(pred_flops, n * d * 4.0,
                                  stages["predict_eval"]),
    }

    total_flops = conv_flops + solve_flops
    detail.update({
        "progress": "staged",
        "stages_seconds": {kk: round(vv, 4) for kk, vv in stages.items()},
        "stages_sum_seconds": round(staged_total, 3),
        "rooflines": rooflines,
        "analytic_tflops": round(total_flops / 1e12, 2),
        "mfu_vs_v5e_peak": round(total_flops / elapsed / V5E_PEAK_FLOPS, 4),
    })
    print("BENCH_DETAIL " + json.dumps(detail), flush=True)

    def run_tier(key, start_phase, done_phase, seconds_key, fn):
        """Failure-isolated tier: a tier that raises records
        {"error": ...} instead of killing the child and losing every
        later tier's measurement (finalize_record surfaces tier errors
        top-level and refuses to persist such a record). ``key`` is the
        detail key the caller will store the result under; it MUST be
        registered in TIER_KEYS or the error gate would silently skip
        it — fail loudly here instead of persisting a broken record."""
        assert key in TIER_KEYS, (
            f"tier detail key {key!r} is not in bench.TIER_KEYS; "
            "finalize_record would ignore its errors — register it")
        phase(start_phase)
        try:
            res = fn()
        except Exception as e:
            res = {"error": f"{type(e).__name__}: {e}"}
        phase(done_phase, seconds=res.get(seconds_key, "error"))
        return res

    def flagship_fn():
        res = _flagship_bcd(
            n=args.flagship_n, d=args.flagship_d, k=args.flagship_k,
            block=4096, iters=3,
        )
        # honest f32 ceiling: the solver pins HIGHEST matmul precision
        # (6-pass bf16x3 on the MXU, ≈ peak/6), so percent-of-bf16-peak
        # understates MXU occupancy by that factor for the Gram GEMMs
        r = res["roofline"]
        r["pct_peak_flops_f32_highest"] = round(
            100 * r["attained_tflops"] * 1e12 / (V5E_PEAK_FLOPS / 6.0), 1)
        return res

    flagship = None
    if not args.skip_flagship:
        flagship = run_tier("flagship_bcd_d8192", "flagship_solver",
                            "flagship_done", "fit_seconds", flagship_fn)
    detail.update({"progress": "flagship", "flagship_bcd_d8192": flagship})
    print("BENCH_DETAIL " + json.dumps(detail), flush=True)

    feat_tier = None
    if not args.skip_featurize_tier:
        feat_tier = run_tier(
            "flagship_featurize", "featurize_tier",
            "featurize_tier_done", "per_rep_seconds",
            lambda: _flagship_featurize(
                batch=args.featurize_batch, reps=args.featurize_reps,
                num_filters=config.num_filters))
    detail.update({"progress": "featurize_tier",
                   "flagship_featurize": feat_tier})
    print("BENCH_DETAIL " + json.dumps(detail), flush=True)

    krr = None
    if not args.skip_krr:
        krr = run_tier(
            "flagship_krr", "krr_solver", "krr_done", "fit_seconds",
            lambda: _flagship_krr(
                n=args.krr_n, d=args.krr_d, k=args.krr_k, block=4096))
    detail.update({"progress": "krr_tier", "flagship_krr": krr})
    print("BENCH_DETAIL " + json.dumps(detail), flush=True)

    overlap = None
    if not args.skip_overlap_tier:
        overlap = run_tier(
            "featurize_overlap", "overlap_tier", "overlap_done",
            "overlapped_seconds",
            lambda: _flagship_overlap(
                n=args.overlap_n, chunk=args.overlap_chunk,
                num_filters=config.num_filters))
    detail.update({"progress": "overlap_tier",
                   "featurize_overlap": overlap})
    print("BENCH_DETAIL " + json.dumps(detail), flush=True)

    # Out-of-core tier: featurize→solve over a synthetic dataset 8× a
    # synthetic HBM budget through the windowed spill prefetcher —
    # peak device residency gated under the budget, windowed solution
    # allclose to the materialized arm at multiple AND ragged counts,
    # warm re-run at 0 cold compiles, and the unified planner pricing
    # the host-spill placement against the INF device cache.
    ooc_tier = None
    if not args.skip_ooc_tier:
        ooc_tier = run_tier(
            "out_of_core", "ooc_tier", "ooc_tier_done", "warm_seconds",
            _out_of_core_bench)
    detail.update({"progress": "ooc_tier", "out_of_core": ooc_tier})
    print("BENCH_DETAIL " + json.dumps(detail), flush=True)

    # Dispatch-count tier: programs-per-run for the example pipelines
    # under serial-unfused / PR-3-legacy / optimized plans (the
    # execution-count budget PERF.md round 4 proved the tunnel charges
    # for). Platform-independent — the counts are a property of the
    # optimizer plan, so CPU and TPU runs record the same numbers.
    def dispatch_fn():
        import time as _t

        from keystone_tpu.dispatch_bench import dispatch_count_report

        t0 = _t.perf_counter()
        rep = dispatch_count_report()
        rep["seconds"] = round(_t.perf_counter() - t0, 2)
        problems = []
        if not rep["all_outputs_match"]:
            problems.append("optimized/legacy/megafused plan predictions "
                            "diverged from the serial unfused path")
        if rep.get("examples_at_one_program", 0) < 2:
            problems.append("megafusion did not reach 1 program/apply run "
                            "on at least two example pipelines")
        if problems:
            rep["error"] = "; ".join(problems)
        return rep

    dispatch_tier = None
    if not args.skip_dispatch_tier:
        dispatch_tier = run_tier(
            "dispatch_count", "dispatch_tier", "dispatch_tier_done",
            "seconds", dispatch_fn)
    detail.update({"progress": "dispatch_tier",
                   "dispatch_count": dispatch_tier})
    print("BENCH_DETAIL " + json.dumps(detail), flush=True)

    # Telemetry-overhead tier: the live plane's warm-serving cost,
    # armed vs disarmed (ISSUE 18's <5% standing budget). Platform
    # independent in spirit — the measured delta is host-side Python
    # (ring tee, sketch insert, conformance compare), not device work.
    telemetry_tier = None
    if not args.skip_telemetry_tier:
        telemetry_tier = run_tier(
            "telemetry_overhead", "telemetry_tier", "telemetry_tier_done",
            "seconds", _telemetry_overhead)
    detail.update({"progress": "telemetry_tier",
                   "telemetry_overhead": telemetry_tier})
    print("BENCH_DETAIL " + json.dumps(detail), flush=True)

    # Serving-QPS tier: the certified serving runtime under sustained
    # concurrent load at fixed offered QPS, coalesced vs the
    # KEYSTONE_SERVING_COALESCE=0 kill switch in the same run. The SLO
    # gate IS the certificate: per-shape observed p99 must sit under
    # the KP903 bound, with 0 cold compiles and 0 conformance breaches
    # inside the measured window, and coalescing must sustain >=4x the
    # per-request-dispatch throughput at equal offered load.
    serving_tier = None
    if not args.skip_serving_tier:
        serving_tier = run_tier(
            "serving_qps", "serving_tier", "serving_tier_done",
            "seconds", _serving_qps)
    detail.update({"progress": "serving_tier",
                   "serving_qps": serving_tier})
    print("BENCH_DETAIL " + json.dumps(detail), flush=True)

    # Compile-count tier: cold-vs-warm compiles + wall clock for the
    # example pipelines against a fresh persistent-cache dir, plus the
    # host ragged-tail microbench. The warm run must perform 0 cold
    # compiles and beat the cold run end-to-end, with outputs identical
    # at multiple AND ragged counts (ISSUE 5 acceptance).
    def compile_fn():
        import time as _t

        from keystone_tpu.compile_bench import compile_count_report

        t0 = _t.perf_counter()
        rep = compile_count_report()
        rep["seconds"] = round(_t.perf_counter() - t0, 2)
        problems = []
        if not rep["all_warm_runs_zero_compiles"]:
            problems.append("a warm run performed cold compiles")
        if not rep["all_warm_beats_cold"]:
            problems.append("a warm run did not beat the cold wall clock")
        if not rep["all_apply_compiles_bounded"]:
            problems.append("apply-run compiles exceed plan programs")
        if not rep["host_tail_padding_saves_programs"]:
            problems.append("chunk padding failed to remove the "
                            "ragged-tail program")
        if problems:
            rep["error"] = "; ".join(problems)
        return rep

    compile_tier = None
    if not args.skip_compile_tier:
        compile_tier = run_tier(
            "compile_count", "compile_tier", "compile_tier_done",
            "seconds", compile_fn)
    detail.update({"progress": "compile_tier",
                   "compile_count": compile_tier})
    print("BENCH_DETAIL " + json.dumps(detail), flush=True)

    # Fused tier LAST: the SAME training run as one XLA program (the
    # `--fused` CLI path, run_fused) — filter learning, featurize,
    # scaler, the pipeline's own BCD solve, and train/test confusion in
    # a single device execution, so per-dispatch latency is paid once.
    # Solver-identical to the pipeline path (it jits the same
    # _bcd_fit_impl), hence reported as a tier of the same record. It
    # runs after every other tier because its cold compile is the
    # biggest single program in the bench: if the tunnel wedges inside
    # that compile, the watchdog-killed child has already checkpointed
    # everything else.
    def fused_fn():
        run_fused(train, test, config)  # compile + warm
        # fresh-valued timed run (PERF.md methodology: the transport
        # memoizes byte-identical executions); perturbation dispatched
        # and fenced BEFORE the timed window
        import random as _random

        from keystone_tpu.loaders.csv_loader import LabeledData

        eps = _random.random() * 1e-6
        train_f = LabeledData(
            labels=train.labels,
            data=train.data.map_batches(lambda x: x * (1.0 + eps)).sync())
        t0 = time.perf_counter()
        fused_res = run_fused(train_f, test, config)
        fused_s = time.perf_counter() - t0
        return {
            "train_seconds": round(fused_s, 3),
            "images_per_sec": round(train.data.count / fused_s, 2),
            "test_accuracy": round(fused_res["test_accuracy"], 4),
            "note": "one-execution training run (run_fused, the --fused "
                    "CLI path); includes train+test featurize and both "
                    "confusion matrices",
        }

    fused_detail = run_tier("fused", "fused_tier", "fused_done",
                            "train_seconds", fused_fn)
    detail.update({"progress": "complete", "fused": fused_detail})
    print("BENCH_DETAIL " + json.dumps(detail), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
