"""Benchmark: RandomPatchCifar featurize+solve throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Baseline: the driver-defined north star is RandomPatchCifar over 50 000
CIFAR images reaching >=84% accuracy in <60 s on a v5e-16 pod, i.e.
833 images/sec across 16 chips (BASELINE.md). vs_baseline compares this
single-chip warm throughput against the full-pod 833 img/s target, so
vs_baseline > 1.0 means one chip alone already beats the whole-pod
reference rate.

Wedge resilience: the TPU here sits behind the axon tunnel, which can
wedge for hours (any device op hangs until killed). This driver-facing
entry therefore NEVER touches the device in-process. It
  1. probes device liveness in a subprocess with a hard timeout,
  2. runs the workload in a killable child process (``--child``) that
     emits phase markers as it progresses,
  3. retries within a deadline, and
  4. ALWAYS prints valid JSON — on persistent failure the record carries
     an "error" plus the last-known-good measurement from
     BENCH_LAST_GOOD.json (marked "stale": true) instead of a traceback.

Uses the learnable synthetic CIFAR task (no dataset egress in this
environment — see BENCH notes); pass --train-path for real CIFAR binaries.
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
LAST_GOOD = os.path.join(REPO, "BENCH_LAST_GOOD.json")
BASELINE_IMGS_PER_SEC = 833.0  # north-star pod rate: 50k imgs / 60 s on v5e-16

PROBE_SRC = (
    "import os, jax;"
    "jax.config.update('jax_platforms', 'cpu') "
    "if os.environ.get('KEYSTONE_BACKEND') == 'cpu' else None;"
    "import jax.numpy as jnp;"
    "print('devices', jax.devices());"
    "print('probe_sum', float(jnp.ones((2, 2)).sum()))"
)


def log(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def probe_device(timeout_s: float) -> bool:
    """True iff a trivial device op completes within timeout_s (run in a
    subprocess so a wedged tunnel cannot hang this process)."""
    try:
        r = subprocess.run(
            [sys.executable, "-u", "-c", PROBE_SRC],
            timeout=timeout_s, capture_output=True, text=True, cwd=REPO,
        )
        ok = r.returncode == 0 and "probe_sum" in r.stdout
        log(f"liveness probe: {'ok' if ok else 'failed'}"
            + ("" if ok else f" (rc={r.returncode}, {r.stderr.strip()[-200:]})"))
        return ok
    except subprocess.TimeoutExpired:
        log(f"liveness probe: timed out after {timeout_s:.0f}s (tunnel wedged)")
        return False


def run_child(args, timeout_s: float):
    """Run the measured workload in a child; returns (detail dict | None,
    phases list). Phase markers let a killed run report partial progress."""
    cmd = [
        sys.executable, "-u", os.path.abspath(__file__), "--child",
        "--n-train", str(args.n_train), "--n-test", str(args.n_test),
        "--num-filters", str(args.num_filters),
    ]
    if args.train_path:
        cmd += ["--train-path", args.train_path]
    if args.test_path:
        cmd += ["--test-path", args.test_path]
    import threading

    phases = []
    detail = [None]

    def consume(pipe):
        # Reader thread: a wedged child stops producing output without
        # exiting, so the parent must never block on readline itself.
        for line in pipe:
            line = line.strip()
            try:
                if line.startswith("BENCH_PHASE "):
                    phases.append(json.loads(line[len("BENCH_PHASE "):]))
                    log(f"phase: {phases[-1]}")
                elif line.startswith("BENCH_DETAIL "):
                    detail[0] = json.loads(line[len("BENCH_DETAIL "):])
            except ValueError as e:
                log(f"unparseable child line {line[:120]!r}: {e}")

    proc = None
    try:
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=sys.stderr, text=True, cwd=REPO
        )
        reader = threading.Thread(target=consume, args=(proc.stdout,), daemon=True)
        reader.start()
        try:
            proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            log(f"child timed out after {timeout_s:.0f}s; killing")
            return None, phases
        reader.join(timeout=10.0)
        if proc.returncode != 0:
            log(f"child exited rc={proc.returncode}")
            return None, phases
        return detail[0], phases
    except Exception as e:  # never let an exception skip the JSON record
        log(f"child failed: {e!r}")
        return None, phases
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait()


def emit(record):
    print(json.dumps(record), flush=True)


def result_record(detail, extra=None):
    imgs_per_sec = detail["images_per_sec"]
    rec = {
        "metric": "cifar_randompatch_train_images_per_sec",
        "value": round(imgs_per_sec, 2),
        "unit": "images/sec (1 chip, warm)",
        "vs_baseline": round(imgs_per_sec / BASELINE_IMGS_PER_SEC, 4),
        "detail": detail,
    }
    if extra:
        rec.update(extra)
    return rec


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--train-path")
    p.add_argument("--test-path")
    p.add_argument("--n-train", type=int, default=50_000)
    p.add_argument("--n-test", type=int, default=10_000)
    p.add_argument("--num-filters", type=int, default=256)
    p.add_argument("--liveness-timeout", type=float, default=90.0)
    p.add_argument("--run-timeout", type=float, default=1500.0)
    p.add_argument("--retry-wait", type=float, default=120.0)
    p.add_argument("--attempts", type=int, default=3)
    p.add_argument("--deadline", type=float, default=2700.0,
                   help="total seconds before giving up and emitting the "
                        "error record")
    args = p.parse_args()

    if args.child:
        return child_main(args)

    t_start = time.monotonic()
    error = None
    for attempt in range(1, args.attempts + 1):
        remaining = args.deadline - (time.monotonic() - t_start)
        if remaining <= args.liveness_timeout:
            error = error or "deadline exhausted before a live-device attempt"
            break
        log(f"attempt {attempt}/{args.attempts} "
            f"({remaining:.0f}s of deadline left)")
        if not probe_device(min(args.liveness_timeout, remaining)):
            error = "device liveness probe failed (axon tunnel wedged)"
            if attempt < args.attempts:
                time.sleep(min(args.retry_wait,
                               max(0.0, args.deadline - (time.monotonic() - t_start))))
            continue
        remaining = args.deadline - (time.monotonic() - t_start)
        detail, phases = run_child(args, min(args.run_timeout, remaining))
        if detail is not None:
            rec = result_record(detail)
            if detail.get("platform") != "cpu":  # only real-device runs
                # qualify as the stale-fallback record
                try:
                    with open(LAST_GOOD, "w") as f:
                        json.dump(rec, f, indent=1)
                except OSError as e:
                    log(f"could not persist last-good record: {e}")
            emit(rec)
            return 0
        error = ("workload run failed/timed out"
                 + (f"; last phase: {phases[-1]}" if phases else " before any phase"))
        if attempt < args.attempts:
            time.sleep(max(0.0, min(args.retry_wait,
                                    args.deadline - (time.monotonic() - t_start))))

    # Persistent failure: valid JSON with the last-known-good measurement.
    stale = None
    if os.path.exists(LAST_GOOD):
        try:
            with open(LAST_GOOD) as f:
                stale = json.load(f)
        except (OSError, ValueError):
            stale = None
    if stale is not None:
        stale.setdefault("detail", {})
        stale["detail"]["stale"] = True
        stale["error"] = error
        emit(stale)
    else:
        emit({
            "metric": "cifar_randompatch_train_images_per_sec",
            "value": 0.0,
            "unit": "images/sec (1 chip, warm)",
            "vs_baseline": 0.0,
            "error": error,
        })
    return 0


def phase(name, **kw):
    print("BENCH_PHASE " + json.dumps({"phase": name, **kw}), flush=True)


def child_main(args):
    """The measured workload. Runs in a killable subprocess; prints phase
    markers and finally one BENCH_DETAIL line."""
    if os.environ.get("KEYSTONE_BACKEND") == "cpu":  # debug/test path; the
        # programmatic override works where env-var platform forcing can
        # hang under the axon sitecustomize (see keystone_tpu/__main__.py)
        import jax

        jax.config.update("jax_platforms", "cpu")
    phase("import")
    from keystone_tpu.pipelines.random_patch_cifar import (
        RandomPatchCifarConfig,
        build_pipeline,
    )
    from keystone_tpu.loaders.cifar_loader import cifar_loader, synthetic_cifar
    from keystone_tpu.evaluation import MulticlassClassifierEvaluator
    from keystone_tpu.workflow import PipelineEnv
    import jax

    phase("devices", platform=jax.devices()[0].platform,
          n=len(jax.devices()))

    config = RandomPatchCifarConfig(num_filters=args.num_filters)
    if args.train_path:
        train = cifar_loader(args.train_path)
        test = cifar_loader(args.test_path or args.train_path)
        synthetic = False
    else:
        train, test = synthetic_cifar(args.n_train, args.n_test)
        synthetic = True
    phase("data", n_train=train.data.count, n_test=test.data.count,
          synthetic=synthetic)

    # Warm-up at the SAME shapes (jit caches are shape-keyed): run the
    # full workload once untimed so the measured run reflects steady-state
    # TPU throughput, not compile time. This also places the training
    # arrays on device once; the timed run reuses them.
    evaluator = MulticlassClassifierEvaluator(config.num_classes)
    warm_pipe = build_pipeline(train, config)
    evaluator(warm_pipe(train.data), train.labels)
    phase("warm_done")

    PipelineEnv.reset()
    t0 = time.perf_counter()
    predictor = build_pipeline(train, config)
    train_metrics = evaluator(predictor(train.data), train.labels)
    elapsed = time.perf_counter() - t0
    phase("timed_done", seconds=round(elapsed, 3))
    test_metrics = evaluator(predictor(test.data), test.labels)

    # Analytic FLOPs of the dominant programs (featurize conv + BCD
    # solve), for a derived MFU against the v5e bf16 peak (197 TFLOP/s).
    n = train.data.count
    F, p = config.num_filters, config.patch_size
    pos = (32 - p + 1) ** 2  # valid conv positions
    conv_flops = 2.0 * n * pos * (p * p * 3) * (F + 1)  # filters + mean conv
    d = 8 * F  # 2x2 pool grid x two-sided rectifier channels
    k = config.num_classes
    B = min(config.block_size, d)
    # BCD sweep: per-block Gram (2nB^2 x d/B blocks) + correlation and
    # two residual GEMMs, which scale with k, not the block width
    solve_flops = 2.0 * n * d * B + 6.0 * n * d * k
    total_flops = conv_flops + solve_flops
    V5E_PEAK = 1.97e14
    detail = {
        "n_train": train.data.count,
        "train_seconds": round(elapsed, 3),
        "images_per_sec": round(train.data.count / elapsed, 2),
        "train_error": round(train_metrics.error, 4),
        "test_accuracy": round(test_metrics.accuracy, 4),
        "num_filters": config.num_filters,
        "analytic_tflops": round(total_flops / 1e12, 2),
        "mfu_vs_v5e_peak": round(total_flops / elapsed / V5E_PEAK, 4),
        "synthetic": synthetic,
        "platform": jax.devices()[0].platform,
        "data_note": (None if not synthetic else
                      "real CIFAR-10 binaries are not obtainable in this "
                      "zero-egress environment; synthetic learnable task at "
                      "identical shapes/scale (see BENCH notes in README)"),
    }
    print("BENCH_DETAIL " + json.dumps(detail), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
