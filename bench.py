"""Benchmark: RandomPatchCifar featurize+solve throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the driver-defined north star is RandomPatchCifar over 50 000
CIFAR images reaching >=84% accuracy in <60 s on a v5e-16 pod, i.e.
833 images/sec across 16 chips (BASELINE.md). vs_baseline compares this
single-chip warm throughput against the full-pod 833 img/s target, so
vs_baseline > 1.0 means one chip alone already beats the whole-pod
reference rate.

Uses the learnable synthetic CIFAR task (no dataset egress in this
environment); pass --train-path to run on real CIFAR binaries.
"""

import argparse
import json
import time

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--train-path")
    p.add_argument("--test-path")
    p.add_argument("--n-train", type=int, default=10_000)
    p.add_argument("--n-test", type=int, default=2_000)
    p.add_argument("--num-filters", type=int, default=256)
    args = p.parse_args()

    from keystone_tpu.pipelines.random_patch_cifar import (
        RandomPatchCifarConfig,
        build_pipeline,
    )
    from keystone_tpu.loaders.cifar_loader import cifar_loader, synthetic_cifar
    from keystone_tpu.evaluation import MulticlassClassifierEvaluator
    from keystone_tpu.workflow import PipelineEnv

    config = RandomPatchCifarConfig(num_filters=args.num_filters)
    if args.train_path:
        train = cifar_loader(args.train_path)
        test = cifar_loader(args.test_path or args.train_path)
    else:
        train, test = synthetic_cifar(args.n_train, args.n_test)

    # Warm-up at the SAME shapes (jit caches are shape-keyed): run the
    # full workload once untimed so the measured run reflects steady-state
    # TPU throughput, not compile time.
    evaluator = MulticlassClassifierEvaluator(config.num_classes)
    warm_pipe = build_pipeline(train, config)
    evaluator(warm_pipe(train.data), train.labels)
    PipelineEnv.reset()
    t0 = time.perf_counter()
    predictor = build_pipeline(train, config)
    train_metrics = evaluator(predictor(train.data), train.labels)
    elapsed = time.perf_counter() - t0
    test_metrics = evaluator(predictor(test.data), test.labels)

    imgs_per_sec = train.data.count / elapsed
    baseline = 833.0  # north-star pod rate: 50k imgs / 60 s on v5e-16
    print(
        json.dumps(
            {
                "metric": "cifar_randompatch_train_images_per_sec",
                "value": round(imgs_per_sec, 2),
                "unit": "images/sec (1 chip, warm)",
                "vs_baseline": round(imgs_per_sec / baseline, 4),
                "detail": {
                    "n_train": train.data.count,
                    "train_seconds": round(elapsed, 3),
                    "train_error": round(train_metrics.error, 4),
                    "test_accuracy": round(test_metrics.accuracy, 4),
                    "num_filters": config.num_filters,
                    "synthetic": not bool(args.train_path),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
